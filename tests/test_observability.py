"""Observability checker tests: set-full, log-file-pattern, timeline
HTML, latency/rate plots, clock plot — golden-style expected-map
assertions in the reference's checker_test.clj style
(checker_test.clj:516-698)."""

import os

import pytest

from jepsen_tpu import checker
from jepsen_tpu.checker import clock as clock_mod
from jepsen_tpu.checker import plots, timeline
from jepsen_tpu.history import History, Op


def op(typ, process, f, value, time, **extra):
    return Op(typ, f=f, process=process, value=value, time=time,
              extra=extra)


def hist(ops):
    return History(ops).index()


def sf(ops):
    return checker.set_full().check({}, hist(ops), {})


class TestSetFull:
    def test_never_read(self):
        res = sf([op("invoke", 0, "add", 0, 0),
                  op("ok", 0, "add", 0, 1_000_000)])
        assert res["valid?"] == "unknown"
        assert res["never-read"] == [0]
        assert res["attempt-count"] == 1
        assert res["stable-count"] == 0
        assert res["lost-count"] == 0

    def test_never_confirmed_never_read(self):
        # add invoked but never acked; read sees nothing
        res = sf([op("invoke", 0, "add", 0, 0),
                  op("invoke", 1, "read", None, 1_000_000),
                  op("ok", 1, "read", [], 2_000_000)])
        assert res["valid?"] == "unknown"
        assert res["never-read"] == [0]

    @pytest.mark.parametrize("order", [
        "r a r+ a'", "r a a' r+", "a r r+ a'", "a r a' r+", "a a' r r+"])
    def test_successful_read_concurrent_or_after(self, order):
        # checker_test.clj:554-573: every interleaving of a concurrent
        # or subsequent observing read is stable with latency 0
        t = [0]

        def mk(tag):
            t[0] += 1_000_000
            return {
                "a": op("invoke", 0, "add", 0, t[0]),
                "a'": op("ok", 0, "add", 0, t[0]),
                "r": op("invoke", 1, "read", None, t[0]),
                "r+": op("ok", 1, "read", [0], t[0]),
            }[tag]
        res = sf([mk(x) for x in order.split()])
        assert res["valid?"] is True
        assert res["stable-count"] == 1
        assert res["stable-latencies"] == {0: 0, 0.5: 0, 0.95: 0,
                                           0.99: 0, 1: 0}

    def test_absent_read_after_is_lost(self):
        res = sf([op("invoke", 0, "add", 0, 0),
                  op("ok", 0, "add", 0, 1_000_000),
                  op("invoke", 1, "read", None, 2_000_000),
                  op("ok", 1, "read", [], 3_000_000)])
        assert res["valid?"] is False
        assert res["lost"] == [0]
        assert res["stable-count"] == 0

    def test_flutter_stable_and_lost(self):
        # checker_test.clj:642-681: a0 known then missing -> lost;
        # a1 seen early, missing, then recovered -> stable + stale.
        ms = 1_000_000
        h = [op("invoke", 0, "add", 0, 0 * ms),         # a0
             op("ok", 0, "add", 0, 1 * ms),             # a0'
             op("invoke", 0, "add", 1, 2 * ms),         # a1
             op("invoke", 2, "read", None, 3 * ms),     # r2
             op("ok", 2, "read", [1], 4 * ms),          # r2'1
             op("ok", 0, "add", 1, 5 * ms),             # a1'
             op("invoke", 2, "read", None, 6 * ms),     # r2
             op("invoke", 3, "read", None, 7 * ms),     # r3
             op("ok", 3, "read", [1], 8 * ms),          # r3'1
             op("ok", 2, "read", [0], 9 * ms)]          # r2'0
        res = sf(h)
        assert res["valid?"] is False
        assert res["lost"] == [0]
        assert res["stale"] == [1]
        assert res["stable-count"] == 1
        assert res["stable-latencies"] == {0: 2, 0.5: 2, 0.95: 2,
                                           0.99: 2, 1: 2}
        assert res["lost-latencies"] == {0: 5, 0.5: 5, 0.95: 5,
                                         0.99: 5, 1: 5}
        worst = res["worst-stale"]
        assert len(worst) == 1
        assert worst[0]["element"] == 1
        assert worst[0]["outcome"] == "stable"
        assert worst[0]["stable-latency"] == 2

    def test_duplicates_invalidate(self):
        res = sf([op("invoke", 0, "add", 0, 0),
                  op("ok", 0, "add", 0, 1_000_000),
                  op("invoke", 1, "read", None, 2_000_000),
                  op("ok", 1, "read", [0, 0], 3_000_000)])
        assert res["valid?"] is False
        assert res["duplicated"] == {0: 2}
        assert res["duplicated-count"] == 1

    def test_linearizable_mode_fails_stale(self):
        ms = 1_000_000
        h = [op("invoke", 0, "add", 0, 0),
             op("ok", 0, "add", 0, 1 * ms),
             op("invoke", 1, "read", None, 2 * ms),
             op("ok", 1, "read", [], 3 * ms),      # missed once
             op("invoke", 1, "read", None, 4 * ms),
             op("ok", 1, "read", [0], 5 * ms)]     # then observed
        assert checker.set_full().check({}, hist(h), {})["valid?"] is True
        assert checker.set_full(linearizable=True).check(
            {}, hist(h), {})["valid?"] is False


class TestLogFilePattern:
    def test_matches(self, tmp_path):
        test = {"name": "lfp", "start_time": "t0",
                "store_root": str(tmp_path), "nodes": ["n1", "n2", "n3"]}
        from jepsen_tpu import store
        for node, text in [("n1", "foo\nevil1\nevil2 more text\nbar"),
                           ("n2", "foo\nbar\nbaz evil\nfoo\n")]:
            p = store.path_bang(test, node, "db.log")
            with open(p, "w") as fh:
                fh.write(text)
        res = checker.log_file_pattern(r"evil\d+", "db.log").check(
            test, History(), {})
        assert res["valid?"] is False
        assert res["count"] == 2
        assert res["matches"] == [
            {"node": "n1", "line": "evil1"},
            {"node": "n1", "line": "evil2 more text"}]

    def test_no_match_valid(self, tmp_path):
        test = {"name": "lfp2", "start_time": "t0",
                "store_root": str(tmp_path), "nodes": ["n1"]}
        res = checker.log_file_pattern("panic", "db.log").check(
            test, History(), {})
        assert res["valid?"] is True


@pytest.fixture
def demo_history():
    ms = 1_000_000
    ops = []
    t = 0
    for i in range(40):
        p = i % 4
        t += 5 * ms
        f = ["read", "write", "cas"][i % 3]
        ops.append(op("invoke", p, f, i % 5, t))
        t += 2 * ms
        ops.append(op(["ok", "fail", "info"][i % 7 % 3], p, f, i % 5, t))
    # a nemesis window
    ops.insert(10, op("invoke", "nemesis", "start", None, 20 * ms))
    ops.insert(11, op("info", "nemesis", "start", None, 21 * ms))
    ops.append(op("invoke", "nemesis", "stop", None, t + ms))
    ops.append(op("info", "nemesis", "stop", None, t + 2 * ms))
    return hist(ops)


class TestTimeline:
    def test_renders_html(self, tmp_path, demo_history):
        test = {"name": "tl", "start_time": "t0",
                "store_root": str(tmp_path)}
        res = timeline.html().check(test, demo_history, {})
        assert res == {"valid?": True}
        p = os.path.join(str(tmp_path), "tl", "t0", "timeline.html")
        doc = open(p).read()
        assert "class='op ok'" in doc
        assert "class='op info'" in doc
        # every completed pair renders exactly one div
        assert doc.count("class='op ") == len(demo_history.pairs())

    def test_subdirectory_and_key(self, tmp_path, demo_history):
        test = {"name": "tl2", "start_time": "t0",
                "store_root": str(tmp_path)}
        timeline.html().check(test, demo_history,
                              {"subdirectory": ["independent", "3"],
                               "history_key": 3})
        p = os.path.join(str(tmp_path), "tl2", "t0", "independent", "3",
                         "timeline.html")
        assert "key 3" in open(p).read()

    def test_truncation(self, tmp_path):
        ms = 1_000_000
        ops = []
        for i in range(timeline.OP_LIMIT + 5):
            ops.append(op("invoke", 0, "read", None, i * ms))
            ops.append(op("ok", 0, "read", 1, i * ms + 1))
        test = {"name": "tl3", "start_time": "t0",
                "store_root": str(tmp_path)}
        timeline.html().check(test, hist(ops), {})
        doc = open(os.path.join(str(tmp_path), "tl3", "t0",
                                "timeline.html")).read()
        assert "Showing only" in doc


class TestPlots:
    def test_latency_and_rate_graphs(self, tmp_path, demo_history):
        test = {"name": "perfy", "start_time": "t0",
                "store_root": str(tmp_path)}
        res = checker.perf().check(test, demo_history, {})
        assert res["valid?"] is True
        d = os.path.join(str(tmp_path), "perfy", "t0")
        assert os.path.exists(os.path.join(d, "latency-raw.png"))
        assert os.path.exists(os.path.join(d, "latency-quantiles.png"))
        assert os.path.exists(os.path.join(d, "rate.png"))

    def test_empty_history_no_crash(self, tmp_path):
        test = {"name": "perfe", "start_time": "t0",
                "store_root": str(tmp_path)}
        res = checker.perf().check(test, History(), {})
        assert res["valid?"] is True

    def test_quantile_series(self):
        pts = [(1.0, 10.0), (2.0, 20.0), (3.0, 30.0), (40.0, 5.0)]
        qs = plots.quantile_series(pts, 30.0, qs=(0.5, 1.0))
        # bucket 0 (mid 15): values 10,20,30 -> q0.5=20, q1=30
        assert qs[0.5] == ([15.0, 45.0], [20.0, 5.0])
        assert qs[1.0] == ([15.0, 45.0], [30.0, 5.0])


class TestClock:
    def test_datasets_and_plot(self, tmp_path):
        ms = 1_000_000
        h = hist([
            op("info", "nemesis", "bump", None, 1 * ms,
               clock_offsets={"n1.x.com": 0.5, "n2.x.com": 0.0}),
            op("info", "nemesis", "bump", None, 5 * ms,
               clock_offsets={"n1.x.com": 2.5}),
            op("ok", 0, "read", 1, 9 * ms),
        ])
        ds = clock_mod.history_datasets(h)
        n1 = ds["n1.x.com"]
        assert n1[0] == [0.001, 0.005, 0.009]  # extended to final time
        assert n1[1] == [0.5, 2.5, 2.5]
        test = {"name": "clk", "start_time": "t0",
                "store_root": str(tmp_path)}
        res = checker.clock_plot().check(test, h, {})
        assert res["valid?"] is True
        assert os.path.exists(os.path.join(
            str(tmp_path), "clk", "t0", "clock-skew.png"))

    def test_short_node_names(self):
        out = clock_mod.short_node_names(
            ["n1.foo.com", "n2.foo.com", "m.foo.com"])
        assert out == {"n1.foo.com": "n1", "n2.foo.com": "n2",
                       "m.foo.com": "m"}

    def test_no_offsets_no_file(self, tmp_path):
        test = {"name": "clk2", "start_time": "t0",
                "store_root": str(tmp_path)}
        h = hist([op("ok", 0, "read", 1, 1_000_000)])
        assert checker.clock_plot().check(test, h, {})["valid?"] is True
        assert not os.path.exists(os.path.join(
            str(tmp_path), "clk2", "t0", "clock-skew.png"))
