"""Observability checker tests: set-full, log-file-pattern, timeline
HTML, latency/rate plots, clock plot — golden-style expected-map
assertions in the reference's checker_test.clj style
(checker_test.clj:516-698) — plus the search-telemetry subsystem
(doc/OBSERVABILITY.md): per-chunk metrics timeseries from the WGL
kernels, checker phase spans in the clients' trace.jsonl format, and
the search-progress panel."""

import json
import os

import pytest

from jepsen_tpu import checker, metrics, trace
from jepsen_tpu.checker import clock as clock_mod
from jepsen_tpu.checker import plots, timeline
from jepsen_tpu.history import History, Op


def op(typ, process, f, value, time, **extra):
    return Op(typ, f=f, process=process, value=value, time=time,
              extra=extra)


def hist(ops):
    return History(ops).index()


def sf(ops):
    return checker.set_full().check({}, hist(ops), {})


class TestSetFull:
    def test_never_read(self):
        res = sf([op("invoke", 0, "add", 0, 0),
                  op("ok", 0, "add", 0, 1_000_000)])
        assert res["valid?"] == "unknown"
        assert res["never-read"] == [0]
        assert res["attempt-count"] == 1
        assert res["stable-count"] == 0
        assert res["lost-count"] == 0

    def test_never_confirmed_never_read(self):
        # add invoked but never acked; read sees nothing
        res = sf([op("invoke", 0, "add", 0, 0),
                  op("invoke", 1, "read", None, 1_000_000),
                  op("ok", 1, "read", [], 2_000_000)])
        assert res["valid?"] == "unknown"
        assert res["never-read"] == [0]

    @pytest.mark.parametrize("order", [
        "r a r+ a'", "r a a' r+", "a r r+ a'", "a r a' r+", "a a' r r+"])
    def test_successful_read_concurrent_or_after(self, order):
        # checker_test.clj:554-573: every interleaving of a concurrent
        # or subsequent observing read is stable with latency 0
        t = [0]

        def mk(tag):
            t[0] += 1_000_000
            return {
                "a": op("invoke", 0, "add", 0, t[0]),
                "a'": op("ok", 0, "add", 0, t[0]),
                "r": op("invoke", 1, "read", None, t[0]),
                "r+": op("ok", 1, "read", [0], t[0]),
            }[tag]
        res = sf([mk(x) for x in order.split()])
        assert res["valid?"] is True
        assert res["stable-count"] == 1
        assert res["stable-latencies"] == {0: 0, 0.5: 0, 0.95: 0,
                                           0.99: 0, 1: 0}

    def test_absent_read_after_is_lost(self):
        res = sf([op("invoke", 0, "add", 0, 0),
                  op("ok", 0, "add", 0, 1_000_000),
                  op("invoke", 1, "read", None, 2_000_000),
                  op("ok", 1, "read", [], 3_000_000)])
        assert res["valid?"] is False
        assert res["lost"] == [0]
        assert res["stable-count"] == 0

    def test_flutter_stable_and_lost(self):
        # checker_test.clj:642-681: a0 known then missing -> lost;
        # a1 seen early, missing, then recovered -> stable + stale.
        ms = 1_000_000
        h = [op("invoke", 0, "add", 0, 0 * ms),         # a0
             op("ok", 0, "add", 0, 1 * ms),             # a0'
             op("invoke", 0, "add", 1, 2 * ms),         # a1
             op("invoke", 2, "read", None, 3 * ms),     # r2
             op("ok", 2, "read", [1], 4 * ms),          # r2'1
             op("ok", 0, "add", 1, 5 * ms),             # a1'
             op("invoke", 2, "read", None, 6 * ms),     # r2
             op("invoke", 3, "read", None, 7 * ms),     # r3
             op("ok", 3, "read", [1], 8 * ms),          # r3'1
             op("ok", 2, "read", [0], 9 * ms)]          # r2'0
        res = sf(h)
        assert res["valid?"] is False
        assert res["lost"] == [0]
        assert res["stale"] == [1]
        assert res["stable-count"] == 1
        assert res["stable-latencies"] == {0: 2, 0.5: 2, 0.95: 2,
                                           0.99: 2, 1: 2}
        assert res["lost-latencies"] == {0: 5, 0.5: 5, 0.95: 5,
                                         0.99: 5, 1: 5}
        worst = res["worst-stale"]
        assert len(worst) == 1
        assert worst[0]["element"] == 1
        assert worst[0]["outcome"] == "stable"
        assert worst[0]["stable-latency"] == 2

    def test_duplicates_invalidate(self):
        res = sf([op("invoke", 0, "add", 0, 0),
                  op("ok", 0, "add", 0, 1_000_000),
                  op("invoke", 1, "read", None, 2_000_000),
                  op("ok", 1, "read", [0, 0], 3_000_000)])
        assert res["valid?"] is False
        assert res["duplicated"] == {0: 2}
        assert res["duplicated-count"] == 1

    def test_linearizable_mode_fails_stale(self):
        ms = 1_000_000
        h = [op("invoke", 0, "add", 0, 0),
             op("ok", 0, "add", 0, 1 * ms),
             op("invoke", 1, "read", None, 2 * ms),
             op("ok", 1, "read", [], 3 * ms),      # missed once
             op("invoke", 1, "read", None, 4 * ms),
             op("ok", 1, "read", [0], 5 * ms)]     # then observed
        assert checker.set_full().check({}, hist(h), {})["valid?"] is True
        assert checker.set_full(linearizable=True).check(
            {}, hist(h), {})["valid?"] is False


class TestLogFilePattern:
    def test_matches(self, tmp_path):
        test = {"name": "lfp", "start_time": "t0",
                "store_root": str(tmp_path), "nodes": ["n1", "n2", "n3"]}
        from jepsen_tpu import store
        for node, text in [("n1", "foo\nevil1\nevil2 more text\nbar"),
                           ("n2", "foo\nbar\nbaz evil\nfoo\n")]:
            p = store.path_bang(test, node, "db.log")
            with open(p, "w") as fh:
                fh.write(text)
        res = checker.log_file_pattern(r"evil\d+", "db.log").check(
            test, History(), {})
        assert res["valid?"] is False
        assert res["count"] == 2
        assert res["matches"] == [
            {"node": "n1", "line": "evil1"},
            {"node": "n1", "line": "evil2 more text"}]

    def test_no_match_valid(self, tmp_path):
        test = {"name": "lfp2", "start_time": "t0",
                "store_root": str(tmp_path), "nodes": ["n1"]}
        res = checker.log_file_pattern("panic", "db.log").check(
            test, History(), {})
        assert res["valid?"] is True


@pytest.fixture
def demo_history():
    ms = 1_000_000
    ops = []
    t = 0
    for i in range(40):
        p = i % 4
        t += 5 * ms
        f = ["read", "write", "cas"][i % 3]
        ops.append(op("invoke", p, f, i % 5, t))
        t += 2 * ms
        ops.append(op(["ok", "fail", "info"][i % 7 % 3], p, f, i % 5, t))
    # a nemesis window
    ops.insert(10, op("invoke", "nemesis", "start", None, 20 * ms))
    ops.insert(11, op("info", "nemesis", "start", None, 21 * ms))
    ops.append(op("invoke", "nemesis", "stop", None, t + ms))
    ops.append(op("info", "nemesis", "stop", None, t + 2 * ms))
    return hist(ops)


class TestTimeline:
    def test_renders_html(self, tmp_path, demo_history):
        test = {"name": "tl", "start_time": "t0",
                "store_root": str(tmp_path)}
        res = timeline.html().check(test, demo_history, {})
        assert res == {"valid?": True}
        p = os.path.join(str(tmp_path), "tl", "t0", "timeline.html")
        doc = open(p).read()
        assert "class='op ok'" in doc
        assert "class='op info'" in doc
        # every completed pair renders exactly one div
        assert doc.count("class='op ") == len(demo_history.pairs())

    def test_subdirectory_and_key(self, tmp_path, demo_history):
        test = {"name": "tl2", "start_time": "t0",
                "store_root": str(tmp_path)}
        timeline.html().check(test, demo_history,
                              {"subdirectory": ["independent", "3"],
                               "history_key": 3})
        p = os.path.join(str(tmp_path), "tl2", "t0", "independent", "3",
                         "timeline.html")
        assert "key 3" in open(p).read()

    def test_truncation(self, tmp_path):
        ms = 1_000_000
        ops = []
        for i in range(timeline.OP_LIMIT + 5):
            ops.append(op("invoke", 0, "read", None, i * ms))
            ops.append(op("ok", 0, "read", 1, i * ms + 1))
        test = {"name": "tl3", "start_time": "t0",
                "store_root": str(tmp_path)}
        timeline.html().check(test, hist(ops), {})
        doc = open(os.path.join(str(tmp_path), "tl3", "t0",
                                "timeline.html")).read()
        # the visible truncation banner: styled, and it names N of M
        assert "truncated: showing" in doc
        assert f"{timeline.OP_LIMIT:,}" in doc
        assert f"{timeline.OP_LIMIT + 5:,}" in doc
        assert ".truncation-warning" in doc  # the banner style exists


class TestPlots:
    def test_latency_and_rate_graphs(self, tmp_path, demo_history):
        test = {"name": "perfy", "start_time": "t0",
                "store_root": str(tmp_path)}
        res = checker.perf().check(test, demo_history, {})
        assert res["valid?"] is True
        d = os.path.join(str(tmp_path), "perfy", "t0")
        assert os.path.exists(os.path.join(d, "latency-raw.png"))
        assert os.path.exists(os.path.join(d, "latency-quantiles.png"))
        assert os.path.exists(os.path.join(d, "rate.png"))

    def test_empty_history_no_crash(self, tmp_path):
        test = {"name": "perfe", "start_time": "t0",
                "store_root": str(tmp_path)}
        res = checker.perf().check(test, History(), {})
        assert res["valid?"] is True

    def test_quantile_series(self):
        pts = [(1.0, 10.0), (2.0, 20.0), (3.0, 30.0), (40.0, 5.0)]
        qs = plots.quantile_series(pts, 30.0, qs=(0.5, 1.0))
        # bucket 0 (mid 15): values 10,20,30 -> q0.5=20, q1=30
        assert qs[0.5] == ([15.0, 45.0], [20.0, 5.0])
        assert qs[1.0] == ([15.0, 45.0], [30.0, 5.0])


class TestSearchTelemetry:
    """The tentpole acceptance surface: a CPU-platform wgl.check with
    telemetry enabled yields (a) a per-chunk timeseries exportable as
    JSONL and Prometheus text, (b) checker phase spans in the same
    trace.jsonl format clients use, (c) a util block with rounds /
    frontier / memo-hit-rate stats — while a disabled run carries no
    telemetry and an identical verdict."""

    CHUNK_KEYS = {"chunk", "wall_s", "poll_s", "transfer_s",
                  "frontier", "backlog", "K", "rounds", "explored",
                  "memo_hits", "memo_inserts", "memo_hit_rate",
                  "kernel", "platform"}

    def _hist(self, n=300, seed=5):
        from jepsen_tpu import synth
        return synth.cas_register_history(n, n_procs=4, seed=seed,
                                          crash_p=0.005)

    def _model(self):
        from jepsen_tpu.models import cas_register
        return cas_register()

    def test_enabled_run_produces_timeseries_spans_util(self, tmp_path):
        from jepsen_tpu.ops import wgl
        reg = metrics.Registry()
        tr = trace.Tracer(sampled=True)
        # a caller-side root span: every phase span must nest under it
        # into ONE trace (checker.Linearizable opens the same root)
        with tr.span("check linearizable"):
            res = wgl.check(self._model(), self._hist(), time_limit=60,
                            metrics=reg, tracer=tr)
        assert res["valid?"] is True
        # (c) util block
        util = res["util"]
        for k in ("rounds", "frontier_fill", "memo_hit_rate",
                  "configs_per_s", "first_call_s", "chunks",
                  "backlog_peak"):
            assert k in util, k
        assert util["chunks"] >= 1
        # (a) per-chunk timeseries, in the result AND the registry
        pts = res["telemetry"]["chunks"]
        assert len(pts) == util["chunks"]
        assert self.CHUNK_KEYS <= set(pts[0])
        assert pts[0]["cold"] is True
        assert pts[-1]["explored"] == res["configs_explored"]
        assert pts[0]["kernel"] == "wgl32"
        assert reg.series("wgl_chunks").points[-1]["explored"] == \
            res["configs_explored"]
        # instruments are labeled by kernel AND platform so raced
        # competition lanes (same kernel, different platform) stay
        # distinguishable
        assert reg.counter("wgl_configs_explored_total").value(
            kernel="wgl32", platform="cpu") == res["configs_explored"]
        assert reg.histogram("wgl_poll_seconds").count(
            kernel="wgl32", platform="cpu") == util["chunks"]
        # JSONL + Prometheus exports parse
        p = str(tmp_path / "m.jsonl")
        assert reg.export_jsonl(p) > 0
        for line in open(p):
            json.loads(line)
        text = reg.prometheus_text()
        assert "# TYPE wgl_configs_explored_total counter" in text
        assert "# TYPE wgl_poll_seconds histogram" in text
        # (b) phase spans, one trace, rooted where the caller is
        names = {s.name for s in tr.spans}
        assert {"encode", "compile", "host-poll"} <= names
        tp = str(tmp_path / "trace.jsonl")
        assert tr.export(tp) == len(tr.spans)
        rows = [json.loads(x) for x in open(tp)]
        # same OTLP-flavored shape TracedClient spans use
        for r in rows:
            assert {"name", "traceId", "spanId", "startTimeUnixNano",
                    "endTimeUnixNano", "attributes"} <= set(r)
        assert len({r["traceId"] for r in rows}) == 1

    def test_disabled_run_is_clean_and_verdict_identical(self):
        from jepsen_tpu.ops import wgl
        h, m = self._hist(), self._model()
        reg = metrics.Registry()
        r_on = wgl.check(m, h, time_limit=60, metrics=reg)
        # pin the disabled registry explicitly so a JEPSEN_TPU_METRICS
        # env enable in the outer environment can't flip this test
        with metrics.use(metrics.NULL):
            r_off = wgl.check(m, h, time_limit=60)
        assert "telemetry" not in r_off
        assert r_off["valid?"] == r_on["valid?"]
        # the search itself is deterministic: telemetry must not
        # perturb what was explored
        assert r_off["configs_explored"] == r_on["configs_explored"]
        assert r_off["util"]["rounds"] == r_on["util"]["rounds"]

    def test_cpu_platform_strategy_carries_telemetry(self):
        # the platform="cpu" lane (host kernel layout) must report the
        # same telemetry surface as the default strategy
        from jepsen_tpu.ops import wgl
        reg = metrics.Registry()
        res = wgl.check(self._model(), self._hist(seed=11),
                        time_limit=60, platform="cpu", metrics=reg)
        assert res["valid?"] is True
        assert res["platform"] == "cpu"
        assert self.CHUNK_KEYS <= set(res["telemetry"]["chunks"][0])
        assert res["util"]["chunks"] >= 1

    def test_wide_window_kernel_labels_wgln(self):
        from jepsen_tpu import synth
        from jepsen_tpu.ops import wgl
        reg = metrics.Registry()
        ht = synth.long_tail_history(60, seed=3)
        res = wgl.check(self._model(), ht, time_limit=120, metrics=reg)
        assert res["valid?"] is True
        assert res["telemetry"]["chunks"][0]["kernel"] == "wgln"
        assert reg.counter("wgl_chunks_total").value(
            kernel="wgln", platform="cpu") >= 1

    def test_checker_renders_search_progress_panel(self, tmp_path):
        tr = trace.Tracer(sampled=True)
        test = {"name": "prog", "start_time": "t0",
                "store_root": str(tmp_path), "tracer": tr}
        with metrics.use(metrics.Registry()):
            res = checker.linearizable(
                self._model(), algorithm="tpu-wgl",
                time_limit=60).check(test, self._hist(seed=7), {})
        assert res["valid?"] is True
        p = res["search-progress-png"]
        assert os.path.exists(p)
        assert p.endswith("search-progress.png")
        # the whole analysis nests under one root span
        roots = [s for s in tr.spans if s.parent_id is None]
        assert [s.name for s in roots] == ["check linearizable"]
        assert len({s.trace_id for s in tr.spans}) == 1

    def test_competition_emits_oracle_race_span(self, tmp_path):
        tr = trace.Tracer(sampled=True)
        test = {"name": "race", "start_time": "t0",
                "store_root": str(tmp_path), "tracer": tr}
        res = checker.linearizable(
            self._model(), algorithm="competition",
            time_limit=30).check(test, self._hist(seed=13), {})
        assert res["valid?"] is True
        names = {s.name for s in tr.spans}
        assert "oracle-race" in names
        assert len({s.trace_id for s in tr.spans}) == 1

    def test_search_progress_graph_direct(self, tmp_path):
        test = {"name": "sp", "start_time": "t0",
                "store_root": str(tmp_path)}
        chunks = [{"wall_s": 0.1 * i, "poll_s": 0.1, "frontier": 16,
                   "backlog": i * 10, "K": 16, "explored": 100 * i,
                   "explored_delta": 100, "memo_hit_rate": 0.5}
                  for i in range(1, 5)]
        p = plots.search_progress_graph(test, chunks)
        assert p and os.path.exists(p)
        # malformed input never raises (the verdict rides along)
        assert plots.search_progress_graph(test, None) is None
        assert plots.search_progress_graph(test, [{"bogus": 1}]) is None

    def test_linear_report_carries_search_stats(self):
        from jepsen_tpu.checker import linear_report
        h = hist([op("invoke", 0, "read", None, 0),
                  op("ok", 0, "read", 1, 1_000_000)])
        doc = linear_report.render(h, {
            "algorithm": "tpu-wgl", "configs_explored": 1234,
            "wall_s": 0.5,
            "util": {"rounds": 7, "memo_hit_rate": 0.25},
            "op": {"index": 0, "f": "read", "process": 0}})
        assert "device search: 1234 configs, 7 rounds" in doc
        assert "memo hit rate 0.25" in doc

    def test_profiler_hook_is_opt_in_and_nonfatal(self, tmp_path):
        # capture failures must never block the verdict; success drops
        # a trace dir and records it on the result
        from jepsen_tpu.ops import wgl
        d = str(tmp_path / "prof")
        res = wgl.check(self._model(), self._hist(seed=17),
                        time_limit=60, profile_dir=d)
        assert res["valid?"] is True
        if res.get("profile_dir"):  # capture worked on this stack
            assert os.path.isdir(d) and os.listdir(d)


class TestClock:
    def test_datasets_and_plot(self, tmp_path):
        ms = 1_000_000
        h = hist([
            op("info", "nemesis", "bump", None, 1 * ms,
               clock_offsets={"n1.x.com": 0.5, "n2.x.com": 0.0}),
            op("info", "nemesis", "bump", None, 5 * ms,
               clock_offsets={"n1.x.com": 2.5}),
            op("ok", 0, "read", 1, 9 * ms),
        ])
        ds = clock_mod.history_datasets(h)
        n1 = ds["n1.x.com"]
        assert n1[0] == [0.001, 0.005, 0.009]  # extended to final time
        assert n1[1] == [0.5, 2.5, 2.5]
        test = {"name": "clk", "start_time": "t0",
                "store_root": str(tmp_path)}
        res = checker.clock_plot().check(test, h, {})
        assert res["valid?"] is True
        assert os.path.exists(os.path.join(
            str(tmp_path), "clk", "t0", "clock-skew.png"))

    def test_short_node_names(self):
        out = clock_mod.short_node_names(
            ["n1.foo.com", "n2.foo.com", "m.foo.com"])
        assert out == {"n1.foo.com": "n1", "n2.foo.com": "n2",
                       "m.foo.com": "m"}

    def test_no_offsets_no_file(self, tmp_path):
        test = {"name": "clk2", "start_time": "t0",
                "store_root": str(tmp_path)}
        h = hist([op("ok", 0, "read", 1, 1_000_000)])
        assert checker.clock_plot().check(test, h, {})["valid?"] is True
        assert not os.path.exists(os.path.join(
            str(tmp_path), "clk2", "t0", "clock-skew.png"))
