"""Shape-aware engine routing tests (ops/route.py): near-serial and
model-pruned shapes decide on the jitlin sweep, branchy shapes on the
device kernel; every result explains its engine choice."""

from jepsen_tpu import synth
from jepsen_tpu.models import cas_register, mutex, register
from jepsen_tpu.ops import route, wgl_ref


def test_mutex_routes_to_jitlin():
    # the BENCH r3 offender: device frontier_fill 0.136, memo 0.0 —
    # the model prunes nearly every interleaving, jitlin sweeps it
    h = synth.mutex_history(400, n_procs=4, seed=7)
    r = route.check_routed(mutex(), h, time_limit=30)
    assert r["valid?"] is True
    assert r["engine"] == "jitlin", r["route_reason"]
    assert r["shape"]["n_ok"] > 0


def test_branchy_routes_to_device():
    h = synth.adversarial_wave_history(4, width=12, span=3, seed=7)
    r = route.check_routed(cas_register(), h, time_limit=120)
    assert r["valid?"] is False  # invalid by construction
    assert r["engine"] == "device", r["route_reason"]
    assert "branchy" in r["route_reason"]


def test_routed_verdicts_match_oracle():
    for seed in range(6):
        lie = 0.1 if seed % 2 else 0.0
        h = synth.cas_register_history(60, n_procs=4, seed=seed,
                                       lie_p=lie, crash_p=0.03)
        r = route.check_routed(cas_register(), h, time_limit=30)
        ref = wgl_ref.check(cas_register(), h)
        assert r["valid?"] == ref["valid?"], (seed, r, ref)
        assert "engine" in r and "route_reason" in r


def test_empty_history_and_shape_stats():
    from jepsen_tpu.history import History
    from jepsen_tpu.ops.encode import encode
    r = route.check_routed(register(), History(), time_limit=5)
    assert r["valid?"] is True
    # shape_stats n == 0 branch directly
    h = History([])
    enc = encode(register(), synth.cas_register_history(10, seed=1))
    s = route.shape_stats(enc)
    assert s["n_ok"] > 0 and s["mean_depth"] > 0
    enc0 = type(enc)(**{**enc.__dict__, "n_ok": 0})
    s0 = route.shape_stats(enc0)
    assert s0 == {"n_ok": 0, "n_info": enc0.n_info,
                  "W_raw": enc0.window_raw,
                  "mean_depth": 0.0, "p95_depth": 0}
