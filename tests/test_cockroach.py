"""Cockroach suite tests: the monotonic and comments checkers on
hand-built histories (including the anomalies each exists to catch),
and both workloads live against the pgwire stub from the postgres
suite tests (real SQL behind the from-scratch wire codec)."""

import threading

import pytest

from jepsen_tpu import core
from jepsen_tpu.dbs import cockroach as cr
from jepsen_tpu.history import History, invoke, ok

from test_postgres import PgStub, PgStubHandler


# -- checker units ----------------------------------------------------------

def _row(val, sts, node="n1", process=0):
    return {"val": val, "sts": sts, "node": node, "process": process}


def test_monotonic_checker_valid():
    h = History([
        invoke(0, "add", None), ok(0, "add", _row(0, "a")),
        invoke(1, "add", None), ok(1, "add", _row(1, "b")),
        invoke(0, "read", None),
        ok(0, "read", [_row(0, "a"), _row(1, "b")]),
    ]).index()
    res = cr.MonotonicChecker().check({}, h, {})
    assert res["valid?"] is True, res


def test_monotonic_checker_catches_inversion_dup_loss():
    # value order inverted relative to timestamp order
    h = History([
        invoke(0, "read", None),
        ok(0, "read", [_row(1, "a"), _row(0, "b")]),
    ]).index()
    res = cr.MonotonicChecker().check({}, h, {})
    assert res["valid?"] is False and res["off-order-val"]
    # duplicate values
    h = History([
        invoke(0, "read", None),
        ok(0, "read", [_row(0, "a"), _row(0, "b")]),
    ]).index()
    assert cr.MonotonicChecker().check({}, h, {})["duplicates"] == [0]
    # acknowledged add lost
    h = History([
        invoke(0, "add", None), ok(0, "add", _row(5, "a")),
        invoke(0, "read", None), ok(0, "read", []),
    ]).index()
    res = cr.MonotonicChecker().check({}, h, {})
    assert res["valid?"] is False and res["lost"] == [5]


def test_comments_checker_catches_missing_predecessor():
    # w0 completes BEFORE w1 is invoked; a read sees w1 but not w0
    h = History([
        invoke(0, "write", 0), ok(0, "write", 0),
        invoke(1, "write", 1), ok(1, "write", 1),
        invoke(2, "read", None), ok(2, "read", [1]),
    ]).index()
    res = cr.CommentsChecker().check({}, h, {})
    assert res["valid?"] is False
    assert res["errors"][0]["missing"] == [0]
    # seeing both (or neither) is fine; so is missing a CONCURRENT one
    h2 = History([
        invoke(0, "write", 0),
        invoke(1, "write", 1), ok(1, "write", 1),
        ok(0, "write", 0),  # w0 concurrent with w1: no precedence
        invoke(2, "read", None), ok(2, "read", [1]),
    ]).index()
    assert cr.CommentsChecker().check({}, h2, {})["valid?"] is True


# -- live against the pgwire stub -------------------------------------------

@pytest.fixture()
def stub(tmp_path):
    srv = PgStub(("127.0.0.1", 0), PgStubHandler,
                 str(tmp_path / "crdb.db"))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address
    srv.shutdown()


def _run(stub, tmp_path, workload, **kw):
    opts = {"nodes": ["n1"], "concurrency": kw.pop("concurrency", 3),
            "time_limit": kw.pop("time_limit", 4),
            "workload": workload,
            "addr": f"{stub[0]}:{stub[1]}",
            "store_root": str(tmp_path / "store"), **kw}
    return core.run(cr.cockroach_test(opts))


def test_monotonic_suite_live(stub, tmp_path):
    done = _run(stub, tmp_path, "monotonic")
    res = done["results"]
    assert res["valid?"] is True, res
    assert res["monotonic"]["add-count"] > 0
    assert res["monotonic"]["read-count"] >= res["monotonic"]["add-count"]


def test_comments_suite_live(stub, tmp_path):
    done = _run(stub, tmp_path, "comments")
    res = done["results"]
    assert res["valid?"] is True, res
    assert res["comments"]["write-count"] > 0


def test_tests_fn_sweeps(tmp_path):
    names = [t["name"] for t in cr.cockroach_tests(
        {"nodes": ["n1"], "concurrency": 2,
         "store_root": str(tmp_path)})]
    assert names == ["cockroach-comments", "cockroach-monotonic"]


@pytest.mark.parametrize("which", ["monotonic", "comments"])
@pytest.mark.slow  # ~17s alone on 1 CI cpu (tier-1 budget: tests/conftest.py)
def test_full_suite_live(tmp_path, which):
    """LIVE pgwire mini servers under the kill/restart nemesis: the
    strict-serializability checkers must hold across crash recovery
    (WAL + full-fsync engines behind the wire)."""
    done = core.run(cr.cockroach_test({
        "nodes": ["c1"], "concurrency": 4, "time_limit": 8,
        "nemesis_interval": 2.5, "workload": which,
        "store_root": str(tmp_path / "store"),
        "sandbox": str(tmp_path / "cluster")}))
    res = done["results"]
    assert res["valid?"] is True, res
