"""The tensorized Elle pipeline (ISSUE 10): elle/build.py edge-column
parity against the host builders, the trim/packed device kernels
against the host oracle, shape-aware auto-routing, the
precompile_elle_closure warm path, and the kind="elle" run ledger."""

import random

import numpy as np
import pytest

from jepsen_tpu import ledger, synth
from jepsen_tpu.analysis import guards
from jepsen_tpu.elle import append, build, wr
from jepsen_tpu.elle import tpu as elle_tpu
from jepsen_tpu.elle.graph import (PROCESS, REALTIME, RW, WR, WW,
                                   DepGraph, process_graph,
                                   realtime_graph)
from jepsen_tpu.history import History, Op
from jepsen_tpu.ops import aot
from jepsen_tpu.ops.route import elle_cycle_route


def edge_set(edges):
    return set(map(tuple, np.asarray(edges).reshape(-1, 3).tolist()))


def split_ops(h):
    oks = [op for op in h
           if op.is_ok and op.f in ("txn", None) and op.value]
    infos = [op for op in h
             if op.is_info and op.f in ("txn", None) and op.value]
    return oks, infos


def host_append_graph(h, additional=()):
    oks, infos = split_ops(h)
    writer, _ = append._writer_index(oks, infos)
    orders, _ = append._version_orders(oks)
    g = append.graph(h, orders=orders, writer=writer, oks=oks)
    if "realtime" in additional:
        g.merge(realtime_graph(h))
    if "process" in additional:
        g.merge(process_graph(h))
    return g, writer, orders


# -- builder parity corpus ---------------------------------------------------

@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("corrupt", [0.0, 0.15])
@pytest.mark.parametrize("additional",
                         [(), ("realtime",), ("realtime", "process")])
def test_append_builder_edge_parity(seed, corrupt, additional):
    """Tensorized append construction produces EXACTLY the host
    builders' edge set, writer index, and version orders — clean and
    corrupted histories (with aborted/info txns: crash_p is on), all
    additional-graph combinations."""
    h = synth.list_append_history(250, seed=seed, corrupt_p=corrupt,
                                  crash_p=0.02)
    g, writer, orders = host_append_graph(h, additional)
    oks, infos = split_ops(h)
    b = build.build_append(h, oks, infos, additional_graphs=additional)
    assert edge_set(b.tensors.edges) == edge_set(g.edges)
    assert b.writer == writer
    assert b.orders == orders
    # corrupted reads break prefix-compatibility -> the exact host
    # loop re-derives the order-dependent anomaly payloads
    if corrupt and b.builder == "host-fallback":
        _, anoms = append._version_orders(oks)
        assert [a["key"] for a in b.order_anomalies] == \
            [a["key"] for a in anoms]


@pytest.mark.parametrize("seed", range(2))
@pytest.mark.parametrize("stale", [0.0, 0.2])
@pytest.mark.parametrize("kw", [
    {}, {"sequential_keys": True}, {"linearizable_keys": True},
    {"wfr_keys": True},
    {"sequential_keys": True, "linearizable_keys": True,
     "wfr_keys": True}])
def test_wr_builder_edge_parity(seed, stale, kw):
    """Tensorized wr construction matches the host evidence builders
    across every version-order evidence source."""
    h = synth.wr_register_history(220, seed=seed, stale_p=stale,
                                  crash_p=0.02)
    oks, infos = split_ops(h)
    writer = wr._writer_index(oks + infos)
    orders, cyclic = wr._version_orders(h, oks, writer, **kw)
    g = wr._txn_graph(oks, writer, orders)
    g.merge(realtime_graph(h))
    b = build.build_wr(h, oks, infos, additional_graphs=("realtime",),
                       **kw)
    assert edge_set(b.tensors.edges) == edge_set(g.edges)
    assert b.writer == writer
    assert sorted(c["key"] for c in b.cyclic_anomalies) == \
        sorted(c["key"] for c in cyclic)


def test_builder_handles_g1a_g1b_fixtures():
    """Hand-built aborted-read / intermediate-read histories flow
    through the tensor path with full verdict parity."""
    def op(i, typ, mops, t):
        return Op(type=typ, f="txn", process=0, value=mops, time=t,
                  index=i)

    ops = [op(0, "invoke", [["append", "x", 1]], 0),
           op(1, "fail", [["append", "x", 1]], 1),
           op(2, "invoke", [["append", "x", 2], ["append", "x", 3]], 2),
           op(3, "ok", [["append", "x", 2], ["append", "x", 3]], 3),
           op(4, "invoke", [["r", "x", None]], 4),
           op(5, "ok", [["r", "x", [1, 2]]], 5)]
    h = History()
    for o in ops:
        h.append(o)
    h = h.index()
    res_d = append.check(h, additional_graphs=("realtime",),
                         cycle_backend="device")
    res_h = append.check(h, additional_graphs=("realtime",),
                         cycle_backend="host")
    assert res_d["valid?"] == res_h["valid?"] is False
    assert set(res_d["anomaly-types"]) == set(res_h["anomaly-types"])
    assert "G1a" in res_d["anomaly-types"]
    assert "G1b" in res_d["anomaly-types"]


def test_realtime_arrays_match_sweep_under_ties():
    """The vectorized reduced realtime graph equals the host sweep on
    histories dense with equal timestamps and zero-duration ops."""
    for seed in range(12):
        rng = random.Random(seed)
        h = History()
        pend = {}
        t = 0
        evs = []
        for _ in range(70):
            p = rng.randrange(4)
            if p in pend:
                inv = pend.pop(p)
                evs.append(Op(type=rng.choice(["ok", "ok", "info",
                                               "fail"]),
                              f="txn", process=p, value=inv.value,
                              time=inv.time + rng.choice([0, 0, 1, 3])))
            else:
                o = Op(type="invoke", f="txn", process=p,
                       value=[["append", "x", rng.randrange(999)]],
                       time=t)
                pend[p] = o
                evs.append(o)
            t += rng.choice([0, 1])
        for i, o in enumerate(evs):
            h.append(o.with_(index=i))
        hg = realtime_graph(h)
        _idx, _inv, _comp, redges = build.realtime_arrays(h)
        assert set(map(tuple, redges.tolist())) == \
            set(map(tuple, np.asarray(hg.edges)[:, :2].tolist())), seed


# -- device-vs-host verdict parity (full pipeline) --------------------------

@pytest.mark.parametrize("corrupt", [0.0, 0.2])
def test_append_device_parity(corrupt):
    h = synth.list_append_history(400, seed=5, corrupt_p=corrupt,
                                  crash_p=0.02)
    res_d = append.check(h, additional_graphs=("realtime",),
                         cycle_backend="device")
    res_h = append.check(h, additional_graphs=("realtime",),
                         cycle_backend="host")
    assert res_d["valid?"] == res_h["valid?"]
    assert set(res_d["anomaly-types"]) == set(res_h["anomaly-types"])
    assert res_d["cycle-engine"] == "device"
    assert res_d["cycle-util"]["kernel"] in ("trim", "bf16", "packed")


@pytest.mark.parametrize("stale", [0.0, 0.15])
def test_wr_device_parity(stale):
    h = synth.wr_register_history(400, seed=5, stale_p=stale,
                                  crash_p=0.02)
    kw = dict(linearizable_keys=True, additional_graphs=("realtime",))
    res_d = wr.check(h, cycle_backend="device", **kw)
    res_h = wr.check(h, cycle_backend="host", **kw)
    assert res_d["valid?"] == res_h["valid?"]
    assert set(res_d["anomaly-types"]) == set(res_h["anomaly-types"])


@pytest.mark.parametrize("seed", range(8))
def test_trim_generic_graph_parity(seed):
    """The trim kernel agrees with the host oracle on arbitrary
    DepGraphs (no builder metadata: every edge scatters)."""
    rng = random.Random(seed)
    g = DepGraph()
    n = rng.randrange(3, 70)
    for i in range(n):
        g.add_node(i)
    for _ in range(rng.randrange(0, 4 * n)):
        g.add_edge(rng.randrange(n), rng.randrange(n),
                   rng.choice([WW, WR, RW, REALTIME, PROCESS]))
    host = elle_tpu.standard_cycle_search(g, backend="host")
    trim = elle_tpu.standard_cycle_search(g, backend="trim")
    for q in ("G0", "G1c", "G-single", "G2"):
        assert (host[q] is None) == (trim[q] is None), q


# -- packed closure ----------------------------------------------------------

@pytest.mark.parametrize("seed", range(5))
def test_packed_bit_identical_to_bf16(seed):
    """The uint32 bitset closure is bit-identical to the bf16 kernel:
    same SCC partitions, same rw-closure bits, same per-iteration
    reach counts and executed-squaring count."""
    rng = random.Random(seed)
    g = DepGraph()
    n = rng.randrange(4, 90)
    for i in range(n):
        g.add_node(i)
    for _ in range(rng.randrange(0, 5 * n)):
        g.add_edge(rng.randrange(n), rng.randrange(n),
                   rng.choice([WW, WR, RW, REALTIME, PROCESS]))
    r_bf = elle_tpu.cycle_queries(g)
    r_pk = elle_tpu.cycle_queries_packed(g)
    for si in range(3):
        assert set(map(tuple, r_bf["sccs"][si])) == \
            set(map(tuple, r_pk["sccs"][si])), si
    assert np.array_equal(np.asarray(r_bf["rw_closed"]),
                          np.asarray(r_pk["rw_closed"]))
    assert r_bf["util"]["iter_reach"] == r_pk["util"]["iter_reach"]
    assert r_bf["util"]["iters_run"] == r_pk["util"]["iters_run"]


def test_packed_lifts_capacity_past_bf16_cap():
    """cycle_queries refuses graphs past DEFAULT_MAX_N; the packed
    kernel's cap is 4x higher (the 16x memory cut is what buys it)."""
    assert elle_tpu.PACKED_MAX_N == 4 * elle_tpu.DEFAULT_MAX_N
    g = DepGraph()
    for i in range(30):
        g.add_edge(i, (i + 1) % 30, WW)
    assert elle_tpu.cycle_queries(g, max_n=10) is None
    res = elle_tpu.cycle_queries_packed(g, max_n=64)
    assert res is not None
    assert res["util"]["kernel"] == "packed"
    assert res["util"]["closure_bytes"] < 3 * 128 * 128 * 2  # < bf16


# -- routing -----------------------------------------------------------------

def test_route_small_graph_stays_host():
    backend, reason = elle_cycle_route(n=40, e=120, rw_edges=10,
                                       accel=False, device_ok=True)
    assert backend == "host"
    assert "small graph" in reason


def test_route_no_backend_stays_host():
    backend, reason = elle_cycle_route(n=5000, e=20000, rw_edges=4000,
                                       accel=False, device_ok=False)
    assert backend == "host"


def test_route_big_graph_goes_device():
    backend, reason = elle_cycle_route(n=3000, e=15000, rw_edges=2600,
                                       accel=False, device_ok=True)
    assert backend == "device"
    assert "device closure battery" in reason


def test_route_over_packed_capacity_falls_host():
    backend, reason = elle_cycle_route(n=40000, e=100000,
                                       rw_edges=9000, accel=True,
                                       device_ok=True)
    assert backend == "host"
    assert "capacity" in reason


def test_capacity_shape_routes_device():
    """The elle_append_8k regression (ISSUE 10 satellite): at the
    kernel's own capacity config the auto route must pick the device
    engine — r05 sat on `engine: host` for every elle config."""
    h = synth.list_append_history(900, n_procs=5, seed=7)
    res = append.check(h, additional_graphs=("realtime",),
                       cycle_backend="auto")
    assert res["cycle-engine"] == "device", res.get("cycle-route-reason")
    assert "device closure battery" in res["cycle-route-reason"]
    res_h = append.check(h, additional_graphs=("realtime",),
                         cycle_backend="host")
    assert res["valid?"] == res_h["valid?"] is True


# -- warm path ---------------------------------------------------------------

def test_precompile_elle_closure_zero_recompiles():
    """aot.precompile_elle_closure warms every kernel the router can
    pick for a shape bucket; the subsequent auto-routed check stays at
    ZERO XLA compiles under CompileGuard (the service warm path)."""
    h = synth.list_append_history(700, n_procs=5, seed=9)
    oks, infos = split_ops(h)
    bt = build.build_append(h, oks, infos,
                            additional_graphs=("realtime",))
    rep = aot.precompile_elle_closure(
        elle_tpu.shape_bucket_for(bt.tensors))
    assert "trim" in rep
    with guards.CompileGuard(max_compiles=0):
        res = append.check(h, additional_graphs=("realtime",),
                           cycle_backend="auto")
    assert res["cycle-engine"] == "device"
    assert res["valid?"] is True


# -- ledger ------------------------------------------------------------------

def test_elle_analyses_land_in_ledger(tmp_path):
    """Every elle analysis records a kind="elle" ledger entry with
    engine + device-seconds, so /runs aggregates and regressions()
    cover both checker families."""
    led = ledger.Ledger(str(tmp_path))
    h = synth.list_append_history(600, n_procs=5, seed=2)
    hw = synth.wr_register_history(600, n_procs=5, seed=2)
    with ledger.use(led):
        append.check(h, additional_graphs=("realtime",),
                     cycle_backend="auto")
        wr.check(hw, linearizable_keys=True,
                 additional_graphs=("realtime",),
                 cycle_backend="auto")
    recs = led.query(kind="elle")
    assert len(recs) == 2
    names = {r["name"] for r in recs}
    assert names == {"elle.append", "elle.wr"}
    for r in recs:
        assert r["engine"] == "device"
        assert r["verdict"] is True
        assert r.get("device_s") is not None  # util.kernel_s rode in
        assert r["wall_s"] > 0
    agg = led.aggregate(recs)
    assert agg["runs"] == 2


# -- telemetry ---------------------------------------------------------------

def test_elle_series_lint_clean(tmp_path):
    """elle_build / elle_closure points pass the telemetry linter —
    and a drifted point fails it."""
    import json
    import sys

    sys.path.insert(0, "scripts")
    import telemetry_lint

    from jepsen_tpu import metrics
    reg = metrics.Registry(enabled=True)
    h = synth.list_append_history(600, n_procs=5, seed=4)
    with metrics.use(reg):
        append.check(h, additional_graphs=("realtime",),
                     cycle_backend="device")
    p = tmp_path / "m.jsonl"
    reg.export_jsonl(str(p))
    lines = [json.loads(ln) for ln in open(p) if ln.strip()]
    assert any(ln.get("series") == "elle_build" for ln in lines)
    assert any(ln.get("series") == "elle_closure" for ln in lines)
    assert telemetry_lint.lint_jsonl_file(str(p)) == []
    bad = dict(next(ln for ln in lines
                    if ln.get("series") == "elle_build"))
    bad.pop("builder")
    with open(p, "a") as fh:
        fh.write(json.dumps(bad) + "\n")
    assert telemetry_lint.lint_jsonl_file(str(p)) != []
