"""Web UI tests: serve a real store dir on an ephemeral port and fetch
pages with urllib — home table, dir browse, file serving, zip download,
and path-traversal rejection (web.clj:146-390)."""

import io
import threading
import urllib.request
import zipfile

import pytest

from jepsen_tpu import checker, core, fakes, web
from jepsen_tpu import generator as gen


@pytest.fixture(scope="module")
def store_root(tmp_path_factory):
    """One real (dummy-remote) run in a fresh store."""
    root = str(tmp_path_factory.mktemp("webstore"))
    reg = fakes.SharedRegister()
    core.run({
        "name": "web-demo",
        "store_root": root,
        "nodes": ["n1", "n2"],
        "concurrency": 2,
        "ssh": {"dummy?": True},
        "client": fakes.AtomClient(reg),
        "checker": checker.stats(),
        "generator": gen.limit(10, gen.clients(
            gen.repeat(lambda: {"f": "read"}))),
    })
    return root


@pytest.fixture(scope="module")
def base_url(store_root):
    server = web.serve(host="127.0.0.1", port=0, store_root=store_root)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


def get(url, expect=200):
    try:
        resp = urllib.request.urlopen(url, timeout=10)
        assert resp.status == expect
        return resp.read()
    except urllib.error.HTTPError as e:
        assert e.code == expect
        return e.read()


def test_home_lists_run_with_validity(base_url):
    body = get(base_url + "/").decode()
    assert "web-demo" in body
    assert "True" in body  # validity cell
    assert "/files/web-demo" in body


def test_dir_browse(base_url):
    body = get(base_url + "/files/web-demo").decode()
    assert "web-demo" in body
    # the run-timestamp subdir is rendered as a colored cell
    assert "latest" in body or "<div" in body


def test_file_serving(base_url, store_root):
    from jepsen_tpu import store
    latest = store.latest(store_root)
    rel = latest.split(store_root)[-1].strip("/")
    body = get(f"{base_url}/files/{rel}/results.json").decode()
    assert '"valid?"' in body


def test_zip_download(base_url, store_root):
    from jepsen_tpu import store
    latest = store.latest(store_root)
    rel = latest.split(store_root)[-1].strip("/")
    raw = get(f"{base_url}/files/{rel}.zip")
    z = zipfile.ZipFile(io.BytesIO(raw))
    names = z.namelist()
    assert "results.json" in names
    assert "test.jepsen" in names


def test_path_traversal_rejected(base_url):
    get(base_url + "/files/../../../etc/passwd", expect=403)


def test_missing_file_404(base_url):
    get(base_url + "/files/nope/nothing.txt", expect=404)


# --- live run status (doc/OBSERVABILITY.md "watching a live run") ----------

STATUS_KEYS = {"schema", "active", "test", "phase", "started",
               "updated", "elapsed_s", "eta_s", "keys", "devices",
               "search", "nemesis", "ops", "faults"}


def test_status_json_idle_schema(base_url):
    """With no run in flight, /status.json still answers with the full
    documented schema (active: false stub)."""
    import json

    from jepsen_tpu import fleet
    assert not fleet.get_default().enabled  # no ambient run status
    snap = json.loads(get(base_url + "/status.json"))
    assert STATUS_KEYS <= set(snap)
    assert snap["active"] is False
    assert snap["keys"] == {"total": 0, "decided": 0, "live": 0,
                            "failures": 0}


def test_status_json_mid_run(base_url):
    """serve answers /status.json MID-RUN: an ambient RunStatus fed by
    the fan-out is visible through the endpoint while keys are still
    live."""
    import json

    from jepsen_tpu import fleet
    st = fleet.RunStatus(test="live-run", progress=False)
    with fleet.use(st):
        st.phase("independent-check")
        st.begin_keys(10)
        st.device_state("TFRT_CPU_0", "searching", key_index=3)
        st.key_done({"key_index": 0, "device": "TFRT_CPU_0",
                     "engine": "device", "wall_s": 0.2, "valid?": True})
        st.nemesis_event("start-partition", True)
        st.search_poll({"frontier": 12, "backlog": 3, "explored": 500,
                        "poll_s": 0.1})
        snap = json.loads(get(base_url + "/status.json"))
    assert STATUS_KEYS <= set(snap)
    assert snap["active"] is True
    assert snap["test"] == "live-run"
    assert snap["phase"] == "independent-check"
    assert snap["keys"]["total"] == 10
    assert snap["keys"]["decided"] == 1
    assert snap["devices"]["TFRT_CPU_0"]["keys_done"] == 1
    assert snap["search"]["frontier"] == 12
    assert snap["nemesis"] == {"active": True, "f": "start-partition",
                               "since_s": snap["nemesis"]["since_s"]}
    assert snap["eta_s"] is not None

    # the HTML panel renders the same source and auto-refreshes
    with fleet.use(st):
        body = get(base_url + "/status").decode()
    assert "http-equiv='refresh'" in body
    assert "live-run" in body
    assert "TFRT_CPU_0" in body
    assert "nemesis window OPEN" in body


def test_status_json_file_fallback(base_url, store_root):
    """An out-of-process run is visible via the current-status.json
    mirror under the store root."""
    import json

    from jepsen_tpu import fleet
    st = fleet.RunStatus(
        test="other-proc",
        status_file=f"{store_root}/{fleet.STATUS_FILENAME}",
        progress=False)
    st.begin_keys(3)
    st.finish(valid=True)
    snap = json.loads(get(base_url + "/status.json"))
    assert snap["test"] == "other-proc"
    assert snap["phase"] == "done"
