"""Web UI tests: serve a real store dir on an ephemeral port and fetch
pages with urllib — home table, dir browse, file serving, zip download,
and path-traversal rejection (web.clj:146-390)."""

import io
import threading
import urllib.request
import zipfile

import pytest

from jepsen_tpu import checker, core, fakes, web
from jepsen_tpu import generator as gen


@pytest.fixture(scope="module")
def store_root(tmp_path_factory):
    """One real (dummy-remote) run in a fresh store."""
    root = str(tmp_path_factory.mktemp("webstore"))
    reg = fakes.SharedRegister()
    core.run({
        "name": "web-demo",
        "store_root": root,
        "nodes": ["n1", "n2"],
        "concurrency": 2,
        "ssh": {"dummy?": True},
        "client": fakes.AtomClient(reg),
        "checker": checker.stats(),
        "generator": gen.limit(10, gen.clients(
            gen.repeat(lambda: {"f": "read"}))),
    })
    return root


@pytest.fixture(scope="module")
def base_url(store_root):
    server = web.serve(host="127.0.0.1", port=0, store_root=store_root)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


def get(url, expect=200):
    try:
        resp = urllib.request.urlopen(url, timeout=10)
        assert resp.status == expect
        return resp.read()
    except urllib.error.HTTPError as e:
        assert e.code == expect
        return e.read()


def test_home_lists_run_with_validity(base_url):
    body = get(base_url + "/").decode()
    assert "web-demo" in body
    assert "True" in body  # validity cell
    assert "/files/web-demo" in body


def test_dir_browse(base_url):
    body = get(base_url + "/files/web-demo").decode()
    assert "web-demo" in body
    # the run-timestamp subdir is rendered as a colored cell
    assert "latest" in body or "<div" in body


def test_file_serving(base_url, store_root):
    from jepsen_tpu import store
    latest = store.latest(store_root)
    rel = latest.split(store_root)[-1].strip("/")
    body = get(f"{base_url}/files/{rel}/results.json").decode()
    assert '"valid?"' in body


def test_zip_download(base_url, store_root):
    from jepsen_tpu import store
    latest = store.latest(store_root)
    rel = latest.split(store_root)[-1].strip("/")
    raw = get(f"{base_url}/files/{rel}.zip")
    z = zipfile.ZipFile(io.BytesIO(raw))
    names = z.namelist()
    assert "results.json" in names
    assert "test.jepsen" in names


def test_path_traversal_rejected(base_url):
    get(base_url + "/files/../../../etc/passwd", expect=403)


def test_missing_file_404(base_url):
    get(base_url + "/files/nope/nothing.txt", expect=404)
