"""TiDB suite tests: the combinatorial option-axis machinery
(all-combos / expected-to-pass / quick, tidb/core.clj:46-151), the
MySQL->sqlite dialect bridge the mini server adds for TiDB SQL
(FOR UPDATE, ON DUPLICATE KEY UPDATE), the pd/tikv/tidb daemon-stack
automation as command assertions, and full workloads end-to-end
against LIVE mini servers under the kill/restart nemesis."""

import subprocess
import sys
import time

import pytest

from jepsen_tpu import core
from jepsen_tpu.dbs import galera as ga
from jepsen_tpu.dbs import tidb as ti
from jepsen_tpu.history import History, fail, invoke, ok


# -- option-axis combinatorics (core.clj:111-151) ---------------------------

def test_all_combos():
    assert ti.all_combos({}) == [{}]
    combos = ti.all_combos({"a": [1, 2], "b": [True, False]})
    assert len(combos) == 4
    assert {"a": 1, "b": True} in combos
    assert len({tuple(sorted(c.items())) for c in combos}) == 4
    # the reference's append axes: 2*2*2 = 8
    assert len(ti.all_combos(ti.WORKLOAD_OPTIONS["append"])) == 8
    # register: 2*2*2*2 = 16
    assert len(ti.all_combos(ti.WORKLOAD_OPTIONS["register"])) == 16
    assert ti.all_combos(ti.WORKLOAD_OPTIONS["table"]) == [{}]


def test_expected_to_pass_pins_retry_off():
    table = ti.expected_to_pass(ti.WORKLOAD_OPTIONS)
    for w, opts in table.items():
        assert opts["auto_retry"] == [False]
        assert opts["auto_retry_limit"] == [0]
    # other axes survive
    assert table["register"]["read_lock"] == [None, "FOR UPDATE"]


def test_quick_options_shape():
    q = ti.quick_workload_options(ti.WORKLOAD_OPTIONS)
    # redundant workloads dropped (core.clj:145-151)
    for dropped in ("bank", "long-fork", "monotonic", "sequential",
                    "table"):
        assert dropped not in q
    assert "append" in q and "bank-multitable" in q
    # retry axes -> defaults, read locks off
    assert q["append"]["auto_retry"] == ["default"]
    assert q["append"]["read_lock"] == [None]
    # use-index kept only where true
    assert q.get("register", {}).get("use_index") == [True]


def test_tidb_tests_matrix(tmp_path):
    opts = {"nodes": ["n1"], "concurrency": 2, "combos": "quick",
            "store_root": str(tmp_path / "s"),
            "sandbox": str(tmp_path / "c")}
    tests = list(ti.tidb_tests(opts))
    names = [t["name"] for t in tests]
    assert len(names) == len(set(names)), "duplicate test names"
    # quick keeps 6 workloads; register expands use_index=True only
    assert any("register" in n for n in names)
    assert not any("long-fork" in n for n in names)
    # explicit workload + combos=all expands every axis product
    all_reg = list(ti.tidb_tests({**opts, "workload": "register",
                                  "combos": "all"}))
    assert len(all_reg) == 16


# -- the dialect bridge (mini server translate) -----------------------------

@pytest.fixture()
def mini(tmp_path):
    srv_py = tmp_path / "minimysql.py"
    srv_py.write_text(ga.MINIMYSQL_SRC)
    port = 26980
    proc = subprocess.Popen(
        [sys.executable, str(srv_py), "--port", str(port),
         "--dir", str(tmp_path), "--password", ga.MINI_PASSWORD],
        cwd=tmp_path)
    deadline = time.monotonic() + 10
    conn = None
    while conn is None:
        try:
            conn = ga.MySqlConn("127.0.0.1", port, timeout=2)
        except OSError:
            assert time.monotonic() < deadline, "never up"
            time.sleep(0.1)
    yield conn, port
    conn.close()
    proc.kill()
    proc.wait(timeout=10)


def test_on_duplicate_key_update_bridge(mini):
    conn, _ = mini
    conn.query("CREATE TABLE test (id INT NOT NULL PRIMARY KEY, "
               "sk INT, val INT)")
    conn.query("INSERT INTO test (id, sk, val) VALUES (1, 1, 10) "
               "ON DUPLICATE KEY UPDATE val = 10")
    conn.query("INSERT INTO test (id, sk, val) VALUES (1, 1, 20) "
               "ON DUPLICATE KEY UPDATE val = 20")
    rows, _ = conn.query("SELECT val FROM test WHERE id = 1")
    assert rows == [["20"]]


def test_for_update_bridge(mini):
    conn, _ = mini
    conn.query("CREATE TABLE t2 (id INT PRIMARY KEY, v INT)")
    conn.query("INSERT INTO t2 VALUES (1, 7)")
    rows, _ = conn.query("SELECT v FROM t2 WHERE id = 1 FOR UPDATE")
    assert rows == [["7"]]


def test_session_axes_accepted(mini):
    conn, _ = mini
    conn.query("SET @@tidb_disable_txn_auto_retry = 1")
    conn.query("SET @@tidb_retry_limit = 0")
    rows, _ = conn.query("SELECT 1")
    assert rows == [["1"]]


# -- table-workload checker -------------------------------------------------

def test_table_checker():
    h = History([
        invoke(0, "insert", [1, 0]),
        fail(0, "insert", [1, 0], error="doesn't-exist"),
    ]).index()
    res = ti.TableChecker().check({}, h, {})
    assert res["valid?"] is False and res["errors"]
    h2 = History([
        invoke(0, "insert", [1, 0]),
        fail(0, "insert", [1, 0], error="duplicate-key"),
        invoke(1, "create-table", 2), ok(1, "create-table", 2),
    ]).index()
    assert ti.TableChecker().check({}, h2, {})["valid?"] is True


# -- full suites against LIVE mini servers ----------------------------------

def _options(tmp_path, which, **kw):
    return {"nodes": kw.pop("nodes", ["t1"]),
            "concurrency": kw.pop("concurrency", 4),
            "time_limit": kw.pop("time_limit", 8),
            "nemesis_interval": kw.pop("nemesis_interval", 2.5),
            "workload": which,
            "store_root": str(tmp_path / "store"),
            "sandbox": str(tmp_path / "cluster"), **kw}


@pytest.mark.parametrize("which,axes", [
    ("register", {"use_index": True, "read_lock": "FOR UPDATE"}),
    ("append", {}),
    ("set-cas", {"read_lock": "FOR UPDATE"}),
    ("table", {}),
    ("bank-multitable", {"update_in_place": False}),
])
@pytest.mark.slow  # ~42s alone on 1 CI cpu (tier-1 budget: tests/conftest.py)
def test_full_suite_live(tmp_path, which, axes):
    done = core.run(ti.tidb_test(_options(tmp_path, which, **axes)))
    res = done["results"]
    assert res["valid?"] is True, res


# -- real-cluster automation (tarball mode) ---------------------------------

def test_tarball_commands():
    from jepsen_tpu import control as c
    from jepsen_tpu.control.dummy import DummyRemote

    log: list = []
    db = ti.TidbDB()
    test = {"nodes": ["n1", "n2", "n3"], "force_reinstall": True}
    with c.with_remote(DummyRemote(log)):
        with c.on("n2"):
            db.setup(test, "n2")
    cmds = [x[1] for x in log if isinstance(x[1], str)]
    joined = "\n".join(cmds)
    # install via (cached) archive fetch into /opt/tidb
    assert "/opt/tidb" in joined
    assert "download.pingcap.org" in ti.tarball_url(ti.VERSION)
    # dependency order: pd before tikv before tidb
    i_pd = joined.index("pd-server")
    i_kv = joined.index("tikv-server")
    i_db = joined.index("tidb-server")
    assert i_pd < i_kv < i_db
    assert "--initial-cluster" in joined
    assert "pd1=http://n1:2380" in joined
    assert "pd2=http://n2:2380" in joined
    assert "--store tikv" in joined or "--store" in joined
    # kill runs in reverse dependency order
    log.clear()
    with c.with_remote(DummyRemote(log)):
        with c.on("n2"):
            db.kill(test, "n2")
    kcmds = "\n".join(x[1] for x in log if isinstance(x[1], str))
    assert kcmds.index("tidb-server") < kcmds.index("tikv-server") \
        < kcmds.index("pd-server")


def test_pd_cluster_strings():
    test = {"nodes": ["a", "b"]}
    assert ti.pd_name(test, "a") == "pd1"
    assert ti.pd_initial_cluster(test) == \
        "pd1=http://a:2380,pd2=http://b:2380"
    assert ti.pd_endpoints(test) == "a:2379,b:2379"
