"""Service-plane tests (jepsen_tpu/service.py): canonical bucket
keying, admission (malformed / queue-full / quota), queue semantics
and coalescing, the warm registry + fs_cache restart re-warm, the
request-scoped trace/series/ledger surfaces, and the web front door
(POST /check, SSE framing, /status.json service block). Histories
are small (one tiny shape bucket per process) and ladder warming is
off (`warm_ladder=False`, first-touch accounting) so the file stays
inside the tier-1 budget; the full warm-ladder zero-recompile proof
runs in scripts/service_smoke.py."""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

from jepsen_tpu import fs_cache, ledger, synth, web
from jepsen_tpu import service as service_mod
from jepsen_tpu import slo as slo_mod

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts"))
import telemetry_lint  # noqa: E402


@pytest.fixture(autouse=True)
def _isolate(monkeypatch, tmp_path):
    monkeypatch.setattr(fs_cache, "DIR",
                        str(tmp_path / "fs-cache-iso"))
    prev = service_mod.set_default(None)
    slo_mod._reset()
    yield
    service_mod.set_default(prev)
    slo_mod._reset()


def _service(root, **kw):
    kw.setdefault("workers", 1)
    kw.setdefault("warm_ladder", False)
    kw.setdefault("slo_every_s", 3600.0)
    # mesh routing off by default: the legacy admission/serve tests
    # assert serial-path semantics; TestMeshRoute & co. opt back in
    kw.setdefault("mesh_serving", False)
    return service_mod.Service(str(root), **kw)


def _hist(n=120, seed=1):
    return synth.cas_register_history(n, n_procs=4, seed=seed)


def _post(svc, h, **kw):
    payload = {"model": "cas-register", "history": h, **kw}
    return svc.submit(payload)


def _wait(svc, rid, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        info = svc.get(rid)
        if info and info["state"] in ("done", "rejected"):
            return info
        time.sleep(0.02)
    raise AssertionError(f"run {rid} never finished")


# --- canonical bucket keying (pure host) -----------------------------------

def _fake_enc(window_raw=10, n=100, ic=4, S=16, O=32, times_max=100):
    z = np.full(n, times_max, dtype=np.int32)
    return SimpleNamespace(
        window_raw=window_raw, inv=z, ret=z,
        sufminret=np.full(n + 1, times_max, dtype=np.int32),
        inv_info=np.full(ic, times_max, dtype=np.int32),
        table=np.zeros((S, O), dtype=np.int32))


class TestBucketFor:
    def test_same_quantum_same_key(self):
        k1, b1 = service_mod.bucket_for(_fake_enc(n=100, ic=4))
        k2, b2 = service_mod.bucket_for(_fake_enc(n=250, ic=30))
        assert k1 == k2
        assert b1 == b2
        assert b1["n_pad"] == 256 and b1["ic_pad"] == 32

    def test_concurrency_jitter_does_not_fragment_narrow(self):
        # narrow windows all key at W_eff 32 — per-request jitter in
        # window_raw must not defeat the warm pool
        keys = {service_mod.bucket_for(_fake_enc(window_raw=w))[0]
                for w in (6, 10, 17, 32)}
        assert len(keys) == 1

    def test_quantum_straddle_splits(self):
        k1, _ = service_mod.bucket_for(_fake_enc(n=250))
        k2, _ = service_mod.bucket_for(_fake_enc(n=270))
        assert k1 != k2

    def test_wide_branch_splits(self):
        k_narrow, b_n = service_mod.bucket_for(_fake_enc(
            window_raw=30))
        k_wide, b_w = service_mod.bucket_for(_fake_enc(
            window_raw=40))
        assert k_narrow != k_wide
        assert b_w["w_eff"] == 64 and b_n["w_eff"] == 32

    def test_pack_bit_in_key(self):
        from jepsen_tpu.ops.wgl32 import PACK_MAX
        k_packed, _ = service_mod.bucket_for(_fake_enc())
        k_unpacked, _ = service_mod.bucket_for(
            _fake_enc(times_max=PACK_MAX + 1))
        assert k_packed != k_unpacked


# --- admission --------------------------------------------------------------

class TestAdmission:
    def test_malformed_requests_raise(self, tmp_path):
        svc = _service(tmp_path)
        with pytest.raises(ValueError, match="unknown model"):
            svc.submit({"model": "nope", "history": _hist()})
        with pytest.raises(ValueError, match="empty"):
            svc.submit({"model": "cas-register", "history": []})
        with pytest.raises(ValueError, match="unknown checker"):
            svc.submit({"checker": "zap", "history": _hist()})
        with pytest.raises(ValueError, match="'type'"):
            svc.submit({"model": "cas-register",
                        "history": [{"f": "read"}]})
        svc.close()

    def test_submit_queues_with_position(self, tmp_path):
        svc = _service(tmp_path)
        svc.hold(True)
        out1 = _post(svc, _hist(seed=1))
        out2 = _post(svc, _hist(seed=2))
        assert out1["state"] == "queued" and out1["position"] == 1
        assert out2["position"] == 2 and out2["depth"] == 2
        assert out1["bucket"] == out2["bucket"]
        info = svc.get(out1["id"])
        assert info["state"] == "queued"
        assert [e["event"] for e in info["events"]] == ["queued"]
        assert svc.get("no-such-run") is None
        svc.close()

    def test_queue_full_rejects(self, tmp_path):
        svc = _service(tmp_path, max_queue=1)
        svc.hold(True)
        _post(svc, _hist(seed=1))
        out = _post(svc, _hist(seed=2))
        assert out["state"] == "rejected"
        assert out["cause"] == "queue-full"
        svc.close()

    def test_quota_rejects_and_is_per_tenant(self, tmp_path):
        led = ledger.Ledger(str(tmp_path))
        led.record({"kind": "service-request", "name": "s",
                    "verdict": True, "tenant": "greedy",
                    "warm_hit": True, "batch_n": 1,
                    "device_s": 2.0, "wall_s": 2.0,
                    "phases": {"search_s": 2.0}})
        svc = _service(tmp_path, quota_device_s=1.0)
        svc.hold(True)
        assert svc.tenant_usage("greedy") == 2.0
        out = _post(svc, _hist(seed=1), tenant="greedy")
        assert out["state"] == "rejected" and out["cause"] == "quota"
        rec = svc.ledger.get(out["id"])
        assert rec["verdict"] == "unknown"
        assert rec["cause"] == "quota"
        assert rec["tenant"] == "greedy"
        # another tenant is not throttled by greedy's spend
        out2 = _post(svc, _hist(seed=2), tenant="frugal")
        assert out2["state"] == "queued"
        svc.close()

    def test_rejection_excluded_from_slo(self, tmp_path):
        svc = _service(tmp_path, quota_device_s=0.0)
        svc.hold(True)
        out = _post(svc, _hist(seed=1), tenant="t")
        assert out["state"] == "rejected"
        rec = svc.ledger.get(out["id"])
        for obj in slo_mod.default_objectives():
            assert obj.good(rec) is None
        svc.close()


# --- end-to-end serve -------------------------------------------------------

@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """One service, two sequential same-bucket requests (the second
    is a first-touch warm hit) — shared by the result/telemetry
    assertions below so the kernel compile is paid once."""
    root = tmp_path_factory.mktemp("service-store")
    prev_dir = fs_cache.DIR
    fs_cache.DIR = str(tmp_path_factory.mktemp("fs-cache"))
    svc = _service(root)
    infos = []
    for seed in (1, 2):
        out = _post(svc, _hist(seed=seed), tenant="tester")
        infos.append(_wait(svc, out["id"]))
    yield svc, infos
    svc.close()
    fs_cache.DIR = prev_dir
    service_mod.set_default(None)


class TestServe:
    def test_verdicts_and_warm_accounting(self, served):
        svc, (i1, i2) = served
        assert i1["verdict"] is True and i2["verdict"] is True
        assert i1["warm_hit"] is False
        assert i2["warm_hit"] is True
        assert i1["bucket"] == i2["bucket"]

    def test_lifecycle_events(self, served):
        svc, (i1, _) = served
        names = [e["event"] for e in i1["events"]]
        assert names == ["queued", "serving", "done"]
        done = i1["events"][-1]
        assert done["verdict"] == "true"
        assert isinstance(done["wall_s"], float)

    def test_phase_walls(self, served):
        svc, (i1, _) = served
        assert set(i1["phases"]) >= {"admit_s", "preflight_s",
                                     "queue_wait_s", "search_s",
                                     "respond_s"}
        assert all(isinstance(v, float) and v >= 0
                   for v in i1["phases"].values())

    def test_request_spans_share_one_trace(self, served):
        svc, (i1, _) = served
        spans = [sp for sp in svc.tracer.spans
                 if sp.attrs.get("run_id") == i1["id"]]
        names = {sp.name for sp in spans}
        assert names >= {"admit", "preflight", "queue-wait",
                         "search", "respond"}
        assert len({sp.trace_id for sp in spans}) == 1

    def test_ledger_records(self, served):
        svc, (i1, i2) = served
        recs = svc.ledger.query(kind="service-request")
        assert len(recs) == 2
        by_id = {r["id"]: r for r in recs}
        assert by_id[i1["id"]]["warm_hit"] is False
        assert by_id[i2["id"]]["warm_hit"] is True
        for r in recs:
            assert r["verdict"] is True
            assert r["tenant"] == "tester"
            assert isinstance(r["phases"], dict)
            assert isinstance(r["device_s"], (int, float))
        idx = os.path.join(svc.store_root, "ledger", "index.jsonl")
        assert telemetry_lint.lint_ledger_file(idx) == []

    def test_service_series_lints(self, served, tmp_path):
        svc, _infos = served
        pts = svc.mx.series("service").points
        assert len(pts) == 2
        for p in pts:
            assert p["verdict"] == "true"
            assert isinstance(p["queue_depth"], int)
            assert isinstance(p["batch_n"], int)
        path = str(tmp_path / "service_metrics.jsonl")
        svc.mx.export_jsonl(path)
        assert telemetry_lint.lint_jsonl_file(path) == []

    def test_snapshot_and_status_block(self, served):
        svc, _infos = served
        snap = svc.snapshot()
        assert snap["served"] == 2 and snap["rejected"] == 0
        assert snap["warm_rate"] == 0.5
        assert snap["warm_buckets"] == 1
        # the serving process's default answers the status block
        # (the autouse isolation fixture cleared it)
        service_mod.set_default(svc)
        s = web.status_snapshot(svc.store_root)
        assert s["service"]["served"] == 2
        assert s["service"]["active"] is True

    def test_drifted_series_point_fails_lint(self, tmp_path):
        pt = {"type": "sample", "series": "service", "t": 1.0,
              "run_id": "r", "tenant": "t", "bucket": "b",
              "verdict": True, "wait_s": 0.1, "serve_s": 0.1,
              "total_s": 0.2, "warm_hit": "yes", "batch_n": 1,
              "queue_depth": 0}
        p = tmp_path / "m.jsonl"
        p.write_text(json.dumps(pt) + "\n")
        errs = telemetry_lint.lint_jsonl_file(str(p))
        assert any("verdict" in e for e in errs)
        assert any("warm_hit" in e for e in errs)

    def test_drifted_record_fails_lint(self, tmp_path):
        rec = {"schema": 1, "id": "x", "kind": "service-request",
               "name": "s", "t": 1.0, "verdict": "valid",
               "tenant": "t", "warm_hit": True,
               "phases": {"search_s": "fast"}}
        p = tmp_path / "index.jsonl"
        (tmp_path / "nothing").mkdir()
        p.write_text(json.dumps(rec) + "\n")
        errs = telemetry_lint.lint_ledger_file(str(p))
        assert any("verdict" in e for e in errs)
        assert any("search_s" in e for e in errs)


class TestCoalesce:
    def test_held_same_bucket_requests_serve_as_one_batch(
            self, tmp_path):
        svc = _service(tmp_path)
        svc.hold(True)
        outs = [_post(svc, _hist(seed=s)) for s in (3, 4)]
        svc.hold(False)
        infos = [_wait(svc, o["id"]) for o in outs]
        assert all(i["verdict"] is True for i in infos)
        pts = {p["run_id"]: p for p in
               svc.mx.series("service").points}
        assert [pts[o["id"]]["batch_n"] for o in outs] == [2, 2]
        assert svc.snapshot()["batches"] == 1
        svc.close()


class TestElle:
    def test_elle_append_request(self, tmp_path):
        svc = _service(tmp_path)
        h = synth.list_append_history(60, n_procs=4, seed=1)
        out = svc.submit({"checker": "elle-append", "history": h,
                          "tenant": "e"})
        assert out["bucket"].startswith("elle-append/")
        info = _wait(svc, out["id"])
        assert info["verdict"] is True
        rec = svc.ledger.get(out["id"])
        assert rec["checker"] == "elle-append"
        assert rec["verdict"] is True
        svc.close()


class TestRewarm:
    def test_plan_registry_round_trip(self, tmp_path, monkeypatch):
        """A warmed bucket's plan lands in fs_cache; a NEW service
        (the process-restart stand-in) re-warms it and answers its
        first same-bucket request as a warm hit. The precompile is
        stubbed — the real zero-recompile proof is the smoke's."""
        calls = []

        def fake_precompile(bucket, accel=False):
            calls.append(dict(bucket))
            return {2: 0.0}

        import jepsen_tpu.ops.aot as aot
        monkeypatch.setattr(aot, "precompile_service_bucket",
                            fake_precompile)
        svc = _service(tmp_path / "a", warm_ladder=True)
        out = _post(svc, _hist(seed=5))
        _wait(svc, out["id"])
        svc.close()
        assert len(calls) == 1
        plans = fs_cache.list_data(("service-plan",))
        assert len(plans) == 1 and plans[0]["bucket"] == calls[0]

        svc2 = _service(tmp_path / "b", warm_ladder=True,
                        rewarm=True)
        assert len(calls) == 2  # restart re-warmed the plan
        out2 = _post(svc2, _hist(seed=6))
        info = _wait(svc2, out2["id"])
        assert info["warm_hit"] is True
        svc2.close()


# --- mesh routing -----------------------------------------------------------

def _held_batch(svc, hs):
    svc.hold(True)
    outs = [_post(svc, h) for h in hs]
    svc.hold(False)
    return outs, [_wait(svc, o["id"]) for o in outs]


@pytest.fixture(scope="module")
def mesh_served(tmp_path_factory):
    """One service over the conftest 8-device mesh; the SAME four
    same-bucket histories served twice — mesh routing off (the
    serial baseline) then on (one lane-group round set) — shared by
    the parity/telemetry assertions so the kernels compile once."""
    root = tmp_path_factory.mktemp("mesh-store")
    prev_dir = fs_cache.DIR
    fs_cache.DIR = str(tmp_path_factory.mktemp("mesh-cache"))
    svc = service_mod.Service(
        str(root), workers=1, warm_ladder=False,
        slo_every_s=3600.0, max_batch=4, mesh_serving=False)
    hs = [_hist(seed=s) for s in (31, 32, 33, 34)]
    s_outs, s_infos = _held_batch(svc, hs)
    svc.mesh_serving = True
    m_outs, m_infos = _held_batch(svc, hs)
    yield svc, (s_outs, s_infos), (m_outs, m_infos)
    svc.close()
    fs_cache.DIR = prev_dir
    service_mod.set_default(None)


class TestMeshRoute:
    def test_verdict_parity_with_serial(self, mesh_served):
        _svc, (_, s_infos), (_, m_infos) = mesh_served
        assert [i["verdict"] for i in m_infos] == \
            [i["verdict"] for i in s_infos]
        assert all(i["verdict"] is True for i in m_infos)

    def test_one_lane_group_round_set(self, mesh_served):
        svc, _s, _m = mesh_served
        pts = svc.mx.series("service_batch").points
        assert [p["mode"] for p in pts] == ["serial", "mesh"]
        mp = pts[-1]
        assert mp["batch_n"] == 4 and mp["rounds"] >= 1
        assert sum(mp["shards"].values()) == 4
        assert svc.snapshot()["mesh_batches"] == 1

    def test_results_carry_mesh_coordinates(self, mesh_served):
        svc, _s, (m_outs, _) = mesh_served
        with svc._lock:
            results = [svc._runs[o["id"]].result for o in m_outs]
        for r in results:
            assert isinstance(r.get("mesh"), dict)
            assert "shard" in r["mesh"] and "slot" in r["mesh"]

    def test_batch_series_lints(self, mesh_served, tmp_path):
        svc, _s, _m = mesh_served
        path = str(tmp_path / "mesh_metrics.jsonl")
        svc.mx.export_jsonl(path)
        assert telemetry_lint.lint_jsonl_file(path) == []


class TestMeshDegrade:
    def test_single_device_degrades_to_serial(self, tmp_path,
                                              monkeypatch):
        svc = _service(tmp_path, mesh_serving=True, max_batch=4)
        monkeypatch.setattr(svc, "_device_count", lambda: 1)
        _outs, infos = _held_batch(
            svc, [_hist(seed=s) for s in (35, 36)])
        assert all(i["verdict"] is True for i in infos)
        pts = svc.mx.series("service_batch").points
        assert pts[-1]["mode"] == "degrade"
        assert pts[-1]["cause"] == "single-device"
        assert svc.snapshot()["degrades"] == 1
        svc.close()

    def test_infeasible_plan_degrades(self, tmp_path, monkeypatch):
        """check_mesh returning None (preflight-infeasible plan, not
        an error) must fall back to the serial path and record the
        routing decision as a degrade."""
        from jepsen_tpu.parallel import mesh as pmesh
        svc = _service(tmp_path, mesh_serving=True, max_batch=4)
        monkeypatch.setattr(pmesh, "check_mesh",
                            lambda *a, **k: None)
        _outs, infos = _held_batch(
            svc, [_hist(seed=s) for s in (37, 38)])
        assert all(i["verdict"] is True for i in infos)
        pts = svc.mx.series("service_batch").points
        assert pts[-1]["mode"] == "degrade"
        assert pts[-1]["cause"] == "mesh-declined"
        svc.close()


class TestMeshAttribution:
    def test_lane_serve_bills_own_wall_only(self, tmp_path):
        """A lane that retires at round r never bills the sibling
        rounds r+1..R as serve time: serve_s is the shard's OWN wall
        and everything before the lane started is queue_wait_s."""
        svc = _service(tmp_path)
        svc.hold(True)
        outs = [_post(svc, _hist(seed=s)) for s in (51, 52)]
        with svc._lock:
            reqs = [svc._runs[o["id"]] for o in outs]
        t0 = time.monotonic()
        walls = [0.05, 0.4]
        for sl, (req, w) in enumerate(zip(reqs, walls)):
            res = {"valid?": True,
                   "shard": {"t0": t0 + 0.01, "wall_s": w,
                             "device": "TFRT_CPU_0"},
                   "mesh": {"shard": 0, "slot": sl}}
            svc._finish_mesh_member(req, res, True, 2, t0)
        assert reqs[0].serve_s == pytest.approx(walls[0])
        assert reqs[1].serve_s == pytest.approx(walls[1])
        assert reqs[0].serve_s < walls[1]
        for req in reqs:
            assert req.phases["search_s"] == req.serve_s
            assert req.phases["queue_wait_s"] >= 0.0
            assert req.state == "done"
        with svc._cv:
            svc._queues.clear()
        svc.hold(False)
        svc.close()


class TestShed:
    def test_burn_sheds_with_retry_after_and_recovers(
            self, tmp_path):
        svc = _service(tmp_path, shed_hold_s=30.0)
        svc._note_slo({"alerts": [{"objective": "warm-p50"}]})
        assert svc.shedding() is not None
        out = _post(svc, _hist(seed=41), tenant="t")
        assert out["state"] == "rejected"
        assert out["cause"] == "shed"
        assert float(out["retry_after_s"]) > 0
        # sheds are admission rejections: excluded from every SLO
        # objective, never counted against availability
        rec = svc.ledger.get(out["id"])
        assert rec["shed"] is True
        for obj in slo_mod.default_objectives():
            assert obj.good(rec) is None
        # a clean report closes the window immediately
        svc._note_slo({"alerts": []})
        assert svc.shedding() is None
        out2 = _post(svc, _hist(seed=42), tenant="t")
        assert out2["state"] == "queued"
        assert _wait(svc, out2["id"])["verdict"] is True
        svc.close()

    def test_no_shed_below_threshold(self, tmp_path):
        svc = _service(tmp_path)
        assert svc.shedding() is None
        out = _post(svc, _hist(seed=43))
        assert out["state"] == "queued"
        assert _wait(svc, out["id"])["verdict"] is True
        assert svc.snapshot()["shed"] == 0
        svc.close()


# --- the web front door -----------------------------------------------------

@pytest.fixture(scope="module")
def http_service(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("http-store"))
    prev_dir = fs_cache.DIR
    fs_cache.DIR = str(tmp_path_factory.mktemp("http-cache"))
    svc = service_mod.Service(root, workers=1, warm_ladder=False,
                              slo_every_s=3600.0)
    server = web.serve(host="127.0.0.1", port=0, store_root=root,
                       service=svc)
    threading.Thread(target=server.serve_forever,
                     daemon=True).start()
    base = f"http://127.0.0.1:{server.server_port}"
    yield base, svc
    server.shutdown()
    svc.close()
    fs_cache.DIR = prev_dir
    service_mod.set_default(None)


def _http_post(base, path, obj, expect=202):
    data = json.dumps(obj, default=str).encode()
    req = urllib.request.Request(
        base + path, data=data,
        headers={"Content-Type": "application/json"})
    try:
        resp = urllib.request.urlopen(req, timeout=60)
        assert resp.status == expect
        return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        assert e.code == expect, (e.code, e.read())
        return json.loads(e.read())


def _sse_events(raw: str):
    """[(event, data dict)] from an SSE stream body."""
    out = []
    ev = None
    for line in raw.splitlines():
        if line.startswith("event: "):
            ev = line[len("event: "):]
        elif line.startswith("data: "):
            out.append((ev, json.loads(line[len("data: "):])))
    return out


class TestHTTP:
    def test_post_check_and_sse_stream(self, http_service):
        base, svc = http_service
        h = [op.to_dict() for op in _hist(seed=7)]
        out = _http_post(base, "/check",
                         {"model": "cas-register", "history": h,
                          "tenant": "http"})
        assert out["state"] == "queued"
        assert out["watch"] == f"/runs/{out['id']}/events"
        _wait(svc, out["id"])
        raw = urllib.request.urlopen(
            base + out["watch"] + "?wait=30",
            timeout=60).read().decode()
        events = _sse_events(raw)
        names = [e for e, _ in events]
        assert names[0] == "snapshot"
        assert names[-1] == "end"
        assert {"queued", "serving", "done"} <= set(names)
        done = next(d for e, d in events if e == "done")
        assert done["verdict"] == "true"
        assert done["run_id"] == out["id"]

    def test_global_events_stream_carries_status(self,
                                                 http_service):
        base, _svc = http_service
        raw = urllib.request.urlopen(
            base + "/events?limit=2&wait=5",
            timeout=30).read().decode()
        events = _sse_events(raw)
        assert events, "stream yielded nothing"
        # an idle feed falls back to throttled status events
        statuses = [d for e, d in events if e == "status"]
        for s in statuses:
            assert "keys" in s and "service" in s

    def test_unknown_run_events_404(self, http_service):
        base, _svc = http_service
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/runs/nope/events",
                                   timeout=10)
        assert ei.value.code == 404

    def test_bad_post_bodies(self, http_service):
        base, _svc = http_service
        out = _http_post(base, "/check", {"model": "nope",
                                          "history": [1]},
                         expect=400)
        assert "error" in out
        req = urllib.request.Request(
            base + "/check", data=b"not json",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400

    def test_post_without_service_503(self, tmp_path):
        server = web.serve(host="127.0.0.1", port=0,
                           store_root=str(tmp_path))
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{server.server_port}"
        out = _http_post(base, "/check", {"model": "cas-register",
                                          "history": []},
                         expect=503)
        assert "no service" in out["error"]
        server.shutdown()

    def test_status_json_service_block(self, http_service):
        base, svc = http_service
        # the autouse isolation fixture clears the module default
        # each test; the serve process installs it once at startup
        service_mod.set_default(svc)
        s = json.loads(urllib.request.urlopen(
            base + "/status.json", timeout=10).read())
        assert s["service"]["active"] is True
        assert set(s["service"]) >= {"queued", "served", "rejected",
                                     "warm_rate", "recent"}
        assert set(s["slo"]) >= {"checked", "alerts_total",
                                 "burning", "last"}

    def test_slo_panel_served(self, http_service):
        base, _svc = http_service
        resp = urllib.request.urlopen(base + "/slo", timeout=10)
        assert resp.status == 200
        assert b"service objectives" in resp.read()


# --- concurrent lifecycle: the threadlint T005 regression corpus -----------

class TestConcurrentLifecycle:
    """Deterministic two-thread regressions for the races threadlint
    surfaced (T005 on start/close): duplicate worker pools /
    heartbeat threads from concurrent start(), and double-join /
    join-under-lock deadlock from concurrent close()."""

    def test_concurrent_start_claims_once(self, tmp_path):
        svc = _service(tmp_path / "s", workers=2,
                       heartbeat_every_s=3600.0)
        barrier = threading.Barrier(2)

        def go():
            barrier.wait(timeout=5)
            svc.start()

        ts = [threading.Thread(target=go) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        try:
            # one worker pool, not two (the old unlocked check let
            # both starters populate _threads)
            assert len(svc._threads) == 2
            # one heartbeat thread claimed, alive, and exactly one
            hb = svc._hb_thread
            assert hb is not None and hb.is_alive()
        finally:
            svc.close()

    def test_concurrent_close_joins_once_and_returns(self, tmp_path):
        """Two concurrent close() calls: both must RETURN (the old
        code could join the heartbeat under the service lock — a
        deadlock against the heartbeat's own lock take) and the
        detach-under-lock means only one closer joins each thread."""
        svc = _service(tmp_path / "s", workers=1,
                       heartbeat_every_s=3600.0).start()
        hb = svc._hb_thread
        assert hb is not None
        barrier = threading.Barrier(2)
        done = []

        def go():
            barrier.wait(timeout=5)
            svc.close(timeout=10)
            done.append(True)

        ts = [threading.Thread(target=go) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert done == [True, True], "a close() call deadlocked"
        assert svc._hb_thread is None and svc._autopilot is None
        assert not hb.is_alive()
        assert svc._threads == []

    def test_start_close_start_restarts(self, tmp_path):
        """close() must leave the claims reusable — a second start()
        after close() brings the pool back."""
        svc = _service(tmp_path / "s", workers=1)
        svc.start()
        svc.close()
        svc.start()
        try:
            info = _wait(svc, _post(svc, [
                op.to_dict() for op in _hist(40, seed=3)])["id"])
            assert info["state"] == "done"
        finally:
            svc.close()
