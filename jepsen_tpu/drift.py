"""Shared drift/regression math for the trend trackers.

Three places used to implement "did this number regress?" on their
own: `bench.compute_regressions` (per-config wall deltas + the 0.9x
fill rule), `ledger.Ledger.regressions` (the same wall comparison
generalized to every recorded run), and `bench._export_occupancy`
(the fill rule again against ledger priors). Divergence between them
is exactly the silent-drift failure mode the telemetry lint exists
for — a threshold bumped in one copy changes what gets flagged
without changing what gets printed elsewhere. This module is the one
definition all of them (and `jepsen_tpu/doctor.py`, which turns the
flags into diagnoses) consume:

  * `regression_threshold()` — the wall-time gate
    (`JEPSEN_TPU_BENCH_REGRESSION_X`, default 1.5x best prior);
  * `delta_row()` — latest-vs-priors comparison row (prev/best
    deltas, ratio, the regressed flag);
  * `fill_regressed()` / `FILL_REGRESSION_X` — the occupancy rule: a
    fill below 0.9x the best same-platform prior regressed, even if
    wall time improved;
  * HBM drift stays in `jepsen_tpu/devices.py` (`drift_x` /
    `drift_regressed` / `HBM_DRIFT_X`) — it was already
    single-sourced there; this module just re-exports it so drift
    consumers have one import.

Same-platform-only comparison is the CALLER's job (a cpu round next
to a tpu round is a hardware change, not a regression) — these
helpers only do the arithmetic.
"""

from __future__ import annotations

import os
from typing import Optional

from .devices import HBM_DRIFT_X, drift_regressed, drift_x  # noqa: F401

# Wall-time regression gate: latest > REGRESSION_X * best prior.
REGRESSION_X = 1.5

# Occupancy regression gate: latest fill < FILL_REGRESSION_X * best
# prior fill — a change that wins wall time by emptying the lanes
# still trips the tracker (ROADMAP item 5).
FILL_REGRESSION_X = 0.9


def regression_threshold(default: float = REGRESSION_X) -> float:
    """The wall-time threshold, env-overridable — the ONE place
    JEPSEN_TPU_BENCH_REGRESSION_X is read."""
    try:
        return float(os.environ.get("JEPSEN_TPU_BENCH_REGRESSION_X",
                                    str(default)))
    except ValueError:
        return default


def wall_regressed(latest: float, best_prior: Optional[float],
                   threshold: Optional[float] = None) -> bool:
    """Is `latest` a regression against the best prior wall?"""
    if best_prior is None or best_prior <= 0:
        return False
    t = regression_threshold() if threshold is None else threshold
    return latest > t * best_prior


def delta_row(latest: float, priors: list,
              threshold: Optional[float] = None) -> dict:
    """The latest-vs-priors comparison row every wall tracker emits:
    prev/best priors, the delta and ratio, and the regressed flag
    (`wall_regressed`). `priors` must be time-ordered (prev = last)."""
    t = regression_threshold() if threshold is None else threshold
    prev = priors[-1] if priors else None
    best = min(priors) if priors else None
    row = {"latest": latest, "prev": prev, "best_prior": best}
    if prev is not None:
        row["delta_vs_prev_s"] = round(latest - prev, 3)
    if best is not None and best > 0:
        row["ratio_vs_best"] = round(latest / best, 3)
        row["regressed"] = wall_regressed(latest, best, t)
    return row


def fill_regressed(latest: float, best_prior: Optional[float]) -> bool:
    """Is `latest` fill a regression against the best prior fill?"""
    if best_prior is None or best_prior <= 0:
        return False
    return latest < FILL_REGRESSION_X * best_prior


def fill_row(latest: float, priors: list) -> dict:
    """The fill comparison row (best prior is the HIGHEST fill)."""
    best = max(priors) if priors else None
    return {"latest": latest, "best_prior": best,
            "regressed": fill_regressed(latest, best)}
