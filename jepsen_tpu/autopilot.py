"""Autopilot: a verify-or-revert control loop over the doctor's remedies.

ROADMAP item 3. PRs 11-16 built the full sense-making stack — preflight
predicts, the observatory measures, the doctor diagnoses (D001-D012
findings already carry structured `remedy` blocks), the SLO engine
prices the damage — yet a human still applied every fix. This module
closes the loop: a supervisor thread polls the doctor + SLO snapshots
at the existing cadences and maps findings to actuators through a
frozen rule->action **policy table**:

  D001 compile-storm   -> warm-bucket   aot.precompile_service_bucket
                                        the offending canonical bucket
  D002 fill-collapse   -> pin-ladder    force a ladder rebucket via
  D003 ladder-thrash   -> pin-ladder    ops/adapt.pin_ladder (the
                                        recorded adapt hint)
  D005 straggler-skew  -> apply-steal   apply the finding's attached
                                        steal/rebucket plan
  D012 queue-backlog   -> resize-pool   grow the worker pool (warm
                                        backlog) or tighten admission
                                        (cold backlog)
  burn (SLO budget)    -> pre-shed      open the shed window BEFORE
                                        the error budget empties

Every action runs under a **verify-or-revert contract**: the decision
and the application are banked as `kind="autopilot-action"` ledger
records (rule, compact finding evidence, action, params, the baseline
metric window), a verify deadline is armed, and the next pass must
show the targeted metric improved past the rule's threshold — else
the action is rolled back (the rollback is banked too) and the rule
is **quarantined for the run**: quarantine state rides `/status.json`
and the `/autopilot` panel, and further firings are recorded as
`suppressed`, never silently retried. Failed actuator applications
(a precompile raises, the steal target vanished, a pool resize is
rejected) land as structured `fleet.record_fault(stage="autopilot")`
events — the doctor can diagnose its own supervisor.

Surfacing (the telemetry IS the feature):

  * a linted `autopilot` metrics series — one point per lifecycle
    event (decision / apply / verify / revert / suppress) with the
    metric value before/after — plus `autopilot_events_total`
    counters and one `kind="autopilot-action"` ledger record per
    event (scripts/telemetry_lint.py validates both);
  * an `autopilot` block on `/status.json` (idle stub
    `{"active": false}`, mirror-aware) and the auto-refreshing
    `/autopilot` web panel: the policy table, live quarantines, and
    the action history with verdicts;
  * Perfetto instant markers in their own "autopilot actions" lane
    (`perfetto_instants` -> `trace.to_perfetto`'s `instants=`);
  * `python -m jepsen_tpu autopilot <run_id|latest|bench>` — offline
    replay of what the policy WOULD have done against any banked run
    (pure decide step, no actuators, read-only), which turns the
    frozen D-catalog into a regression-tested policy surface.

Architecture: the `Supervisor` talks to a `Host` adapter — diagnose /
slo_report / probe(metric) / actuate(rule, finding) — so the policy
lifecycle is unit-testable against fabricated hosts
(tests/test_autopilot.py) while `ServiceHost` binds it to a live
`service.Service`. `scripts/autopilot_smoke.py` proves the closed
loop in CI: a seeded PR-9-style compile storm fires D001, the
autopilot warms the bucket through the real AOT path, and the next
pass verifies at zero further compiles.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from . import fleet
from . import ledger as ledger_mod
from . import metrics as metrics_mod
from .analysis import lockwatch

SCHEMA = 1

# Lifecycle events, in order. `decision` = a policy rule matched a
# finding; `apply` = the actuator ran (baseline banked, deadline
# armed); `verify` = the metric improved past the threshold;
# `revert` = it did not (or the actuator failed) — rolled back and
# quarantined; `suppress` = a quarantined rule fired again.
EVENTS = ("decision", "apply", "verify", "revert", "suppress")

# The Perfetto lane autopilot markers render in (trace.instant_events
# groups instants by their `lane` key).
PERFETTO_LANE = "autopilot actions"

# Quarantine persistence: every quarantine/clear banks a
# `kind="autopilot-quarantine"` ledger record, and a fresh Supervisor
# replays them so a rule that failed verification stays quarantined
# across service restarts (a restart must not silently re-arm an
# actuator the last run proved harmful). The escape hatch: set this
# env truthy (or `serve --clear-quarantine`) to start clean — the
# clear itself is banked, never silent.
CLEAR_QUARANTINE_ENV = "JEPSEN_TPU_AUTOPILOT_CLEAR_QUARANTINE"

# Pre-shed trigger: an objective whose error budget has burned down
# to this remaining fraction (or is already burn-alerting) opens the
# shed window before the budget empties.
PRE_SHED_BUDGET_FRAC = 0.5

# ServiceHost probe window: the "before" baseline for windowed
# metrics (recent compiles) looks back this far.
PROBE_WINDOW_S = 60.0

# Bounded in-process history (the /autopilot panel + snapshot).
HISTORY_CAP = 64


@dataclass(frozen=True)
class PolicyRule:
    """One frozen policy-table row: which finding triggers it, which
    actuator runs, which metric must improve, and by how much.

    `direction` "down" verifies when the probed metric fell to
    `improve_x` of the baseline (or under `abs_ok` absolutely);
    "up" when it rose past `improve_x` times the baseline (or over
    `abs_ok`). An unprobeable after-value NEVER verifies — the
    contract is "show the improvement", not "assume it"."""

    rule: str            # doctor rule id, or "burn" for the SLO gate
    action: str
    metric: str
    direction: str = "down"
    improve_x: float = 0.5
    abs_ok: Optional[float] = None
    description: str = ""

    def improved(self, before, after) -> bool:
        if not isinstance(after, (int, float)):
            return False
        after = float(after)
        if self.direction == "down":
            if self.abs_ok is not None and after <= self.abs_ok:
                return True
            if not isinstance(before, (int, float)):
                return False
            return after <= self.improve_x * float(before)
        if self.abs_ok is not None and after >= self.abs_ok:
            return True
        if not isinstance(before, (int, float)):
            return False
        return after >= self.improve_x * float(before)


# The frozen policy table (doc/OBSERVABILITY.md "Autopilot plane").
# Thresholds reference the planes that own them: D005's abs_ok is
# fleet.REBUCKET_SKEW_X (skew back under the steal gate), burn's is
# 1.0 (burning at or under budget). Adding a row is additive;
# changing a row's semantics is a policy change the replay CLI makes
# regression-testable.
POLICY: tuple = (
    PolicyRule(
        rule="D001", action="warm-bucket", metric="recent_compiles",
        direction="down", improve_x=0.5, abs_ok=0.0,
        description="AOT-warm the offending canonical bucket "
                    "(aot.precompile_service_bucket); verified when "
                    "compiles since the action drop to zero"),
    PolicyRule(
        rule="D002", action="pin-ladder", metric="frontier_fill",
        direction="up", improve_x=1.2, abs_ok=0.8,
        description="pin the adaptive ladder to the bucket the "
                    "recorded adapt hint names (ops/adapt.pin_ladder)"
                    "; verified when frontier fill recovers"),
    PolicyRule(
        rule="D003", action="pin-ladder", metric="ladder_switches",
        direction="down", improve_x=0.5, abs_ok=0.0,
        description="pin the thrashing ladder to its widest revisited "
                    "bucket; verified when switches stop"),
    PolicyRule(
        rule="D005", action="apply-steal", metric="work_skew",
        direction="down", improve_x=0.9,
        abs_ok=fleet.REBUCKET_SKEW_X,
        description="apply the finding's attached steal plan; "
                    "verified when work skew falls back under the "
                    "steal gate"),
    PolicyRule(
        rule="D012", action="resize-pool", metric="queue_depth",
        direction="down", improve_x=0.5, abs_ok=0.0,
        description="grow the worker pool (warm backlog) or tighten "
                    "admission (cold backlog); verified when the "
                    "queue drains"),
    PolicyRule(
        rule="burn", action="pre-shed", metric="burn_rate",
        direction="down", improve_x=0.9, abs_ok=1.0,
        description="open the admission shed window before the SLO "
                    "error budget empties; verified when the burn "
                    "rate falls back to budget"),
)


def policy_rows(policy: tuple = POLICY) -> list:
    """The policy table as plain dicts (the /autopilot panel and the
    snapshot's `policy` key)."""
    return [{"rule": e.rule, "action": e.action, "metric": e.metric,
             "direction": e.direction, "improve_x": e.improve_x,
             "abs_ok": e.abs_ok, "description": e.description}
            for e in policy]


def burn_finding(slo_report) -> Optional[dict]:
    """The synthetic "burn" trigger from an SLO evaluation: fires
    when any objective is burn-alerting OR its error budget has
    drained to PRE_SHED_BUDGET_FRAC — the pre-shed acts before the
    multi-window alert would force the service's own shed."""
    if not isinstance(slo_report, dict):
        return None
    hot: list = []
    rates: list = []
    for row in slo_report.get("objectives") or []:
        budget = row.get("budget") or {}
        rem = budget.get("remaining_frac")
        draining = (isinstance(rem, (int, float))
                    and rem <= PRE_SHED_BUDGET_FRAC)
        if row.get("burn_alert") or draining:
            hot.append(str(row.get("name")))
            longest = (row.get("windows") or [{}])[-1]
            if isinstance(longest.get("burn_rate"), (int, float)):
                rates.append(longest["burn_rate"])
    if not hot:
        return None
    return {"rule": "burn", "name": "error-budget-burn",
            "severity": "warn",
            "summary": f"error budget draining on {', '.join(hot)} "
                       f"— shed before it empties",
            "subject": ",".join(hot),
            "evidence": [{"series": "slo", "field": "burn_rate",
                          "indices": list(range(len(rates))),
                          "values": rates}],
            "action": "open the admission shed window",
            "objectives": hot}


def replay(report, slo_report=None, policy: tuple = POLICY) -> list:
    """What the policy WOULD do against a banked diagnosis: the pure
    decide step — no actuators run, nothing is banked. One decision
    per matched rule (the report's top-ranked finding for that rule),
    in policy-table order. The offline replay CLI and the
    replay-parity tests are built on this."""
    findings: dict = {}
    for f in (report or {}).get("findings") or []:
        findings.setdefault(f.get("rule"), f)
    bf = burn_finding(slo_report)
    if bf is not None:
        findings["burn"] = bf
    out: list = []
    for entry in policy:
        f = findings.get(entry.rule)
        if f is None:
            continue
        out.append({"rule": entry.rule, "action": entry.action,
                    "metric": entry.metric,
                    "severity": f.get("severity"),
                    "subject": f.get("subject"),
                    "summary": f.get("summary"),
                    "description": entry.description})
    return out


# ---------------------------------------------------------------------------
# Host adapters — what the supervisor senses and actuates through
# ---------------------------------------------------------------------------

class Host:
    """The supervisor's world interface. Fabricated hosts make the
    verify-or-revert lifecycle unit-testable; `ServiceHost` binds a
    live Service."""

    name = "host"

    def diagnose(self) -> Optional[dict]:
        """A doctor report (or None when there is nothing to read)."""
        return None

    def slo_report(self) -> Optional[dict]:
        """The latest SLO evaluation (or None)."""
        return None

    def probe(self, metric: str,
              since: Optional[float] = None) -> Optional[float]:
        """The current value of a policy metric. `since` anchors
        windowed metrics (compiles/switches SINCE the action was
        applied); instantaneous metrics ignore it. None = cannot be
        measured right now (which never verifies an action)."""
        return None

    def actuate(self, entry: PolicyRule, finding: dict) -> tuple:
        """Execute one policy action. Returns `(params, rollback)` —
        `params` is the banked parameter dict, `rollback` a no-arg
        callable that undoes the action (None when the action has no
        meaningful inverse). Raises on failure; the supervisor turns
        the raise into a structured autopilot fault + quarantine."""
        raise NotImplementedError


class ServiceHost(Host):
    """Bind the supervisor to a live `service.Service`: diagnoses the
    service's own registry + recent ledger records, reads the SLO
    engine's last evaluation, and actuates through the service's
    warm/pool/shed controls and the ops/adapt ladder pin."""

    name = "service"

    def __init__(self, service, *,
                 probe_window_s: float = PROBE_WINDOW_S):
        self.svc = service
        self.probe_window_s = float(probe_window_s)

    # -- sensing ------------------------------------------------------
    def diagnose(self) -> Optional[dict]:
        from . import doctor
        try:
            recs = self.svc.ledger.query(
                since=time.time() - max(self.probe_window_s, 300.0),
                limit=256)
            view = doctor.view_from_registry(
                self.svc.mx, target="service", records=recs)
            return doctor.diagnose(view)
        except Exception:  # noqa: BLE001 — a torn read is "nothing
            return None    # to act on", never a dead supervisor

    def slo_report(self) -> Optional[dict]:
        from . import slo as slo_mod
        return slo_mod.last_report()

    def probe(self, metric: str,
              since: Optional[float] = None) -> Optional[float]:
        svc = self.svc
        now = time.time()
        if metric == "recent_compiles":
            t0 = since if since is not None \
                else now - self.probe_window_s
            total = 0
            try:
                for rec in svc.ledger.query(since=t0):
                    c = rec.get("compiles")
                    if isinstance(c, int) and not isinstance(c, bool):
                        total += c
            except Exception:  # noqa: BLE001
                return None
            return float(total)
        if metric == "frontier_fill":
            pts = self._series_since("wgl_rounds", since)
            fills = [float(p["fill"]) for p in pts
                     if isinstance(p.get("fill"), (int, float))]
            return (round(sum(fills) / len(fills), 4)
                    if fills else None)
        if metric == "ladder_switches":
            return float(len(self._series_since("wgl_adapt", since)))
        if metric == "work_skew":
            skew = None
            try:
                t0 = since if since is not None \
                    else now - self.probe_window_s
                for rec in svc.ledger.query(since=t0):
                    s = ((rec.get("util") or {}).get("fleet")
                         or {}).get("work_skew")
                    if isinstance(s, (int, float)):
                        skew = float(s)
            except Exception:  # noqa: BLE001
                return None
            return skew
        if metric == "queue_depth":
            with svc._lock:
                return float(sum(len(q)
                                 for q in svc._queues.values()))
        if metric == "burn_rate":
            rep = self.slo_report()
            if not isinstance(rep, dict):
                return None
            rates = []
            for row in rep.get("objectives") or []:
                longest = (row.get("windows") or [{}])[-1]
                if isinstance(longest.get("burn_rate"), (int, float)):
                    rates.append(float(longest["burn_rate"]))
            return max(rates) if rates else None
        return None

    def _series_since(self, name: str, since: Optional[float]) -> list:
        try:
            pts = self.svc.mx.series(name).points
        except Exception:  # noqa: BLE001
            return []
        if since is None:
            return list(pts)
        return [p for p in pts
                if isinstance(p.get("t"), (int, float))
                and p["t"] >= since]

    # -- actuators ----------------------------------------------------
    def actuate(self, entry: PolicyRule, finding: dict) -> tuple:
        if entry.action == "warm-bucket":
            return self._warm_bucket(finding)
        if entry.action == "pin-ladder":
            return self._pin_ladder(entry, finding)
        if entry.action == "apply-steal":
            return self._apply_steal(finding)
        if entry.action == "resize-pool":
            return self._resize_pool(finding)
        if entry.action == "pre-shed":
            return self._pre_shed(finding)
        raise RuntimeError(f"no actuator for {entry.action!r}")

    def _warm_bucket(self, finding: dict) -> tuple:
        """D001: AOT-warm the offending canonical bucket through the
        service's own warm path (aot.precompile_service_plan wraps
        precompile_service_bucket) and mark it warm, so every later
        same-bucket request is a warm hit. The revert is honest:
        un-mark the bucket (the service re-warms on its next cold
        batch) — compiled executables stay in the jit caches."""
        svc = self.svc
        subject = str(finding.get("subject") or "")
        with svc._lock:
            runs = list(svc._runs.values())
        req = None
        for r in reversed(runs):  # newest first
            if getattr(r, "bucket", None) is None \
                    or getattr(r, "bucket_key", None) is None:
                continue
            from .service import _key_str
            if subject and subject in (_key_str(r.bucket_key),
                                       str(r.bucket)):
                req = r
                break
            if req is None:
                with svc._lock:
                    cold = r.bucket_key not in svc._warm
                if cold:
                    req = r
        if req is None:
            raise RuntimeError(
                "no live request carries the offending bucket "
                f"(subject {subject!r}) — nothing to precompile")
        if not svc._warm_bucket(req):
            raise RuntimeError(
                f"precompile failed for bucket {req.bucket_key!r}")
        key = req.bucket_key
        with svc._lock:
            svc._warm[key] = {"t": time.time(), "warm_s": 0.0,
                              "autopilot": True}

        def rollback() -> None:
            with svc._lock:
                svc._warm.pop(key, None)

        from .service import _key_str
        return {"bucket": _key_str(key)}, rollback

    def _pin_ladder(self, entry: PolicyRule, finding: dict) -> tuple:
        """D002/D003: force a ladder rebucket via ops/adapt's pin —
        every live Policy switches to the pinned bucket on its next
        poll and holds. The pin target is the recorded adapt hint:
        for thrash, the widest bucket the wgl_adapt evidence visited
        (settle wide, stop the ping-pong); for fill collapse, the
        recommend() bucket for the observed frontier (narrow to the
        wavefront). Rollback = unpin (hysteresis resumes)."""
        from .ops import adapt
        ks: list = []
        for ev in finding.get("evidence") or []:
            for v in ev.get("values") or []:
                if isinstance(v, (int, float)) and not isinstance(
                        v, bool) and v >= 1:
                    ks.append(int(v))
        if finding.get("rule") == "D003":
            k = max(ks) if ks else adapt.LADDER32[-1]
        else:
            pts = self._series_since("wgl_rounds", None)
            fronts = [float(p["frontier"]) for p in pts[-32:]
                      if isinstance(p.get("frontier"), (int, float))]
            occ = (sum(fronts) / len(fronts)) if fronts else 1.0
            k = adapt.recommend(adapt.LADDER32, occ)
        pin = adapt.pin_ladder(
            k, reason=f"autopilot-{finding.get('rule')}")
        return {"k": pin["k"],
                "reason": pin["reason"]}, adapt.unpin_ladder

    def _apply_steal(self, finding: dict) -> tuple:
        """D005: the finding's remedy IS the executable steal plan —
        but a service process has no standing mesh group to hand it
        to (mesh lane groups live inside one check_mesh call, which
        applies fleet.steal_plan itself between polls). Until the
        multi-host fleet (ROADMAP item 2) gives the plan a standing
        router to land on, this actuator reports the vanished target
        as a structured failure rather than pretending."""
        remedy = finding.get("remedy")
        if not isinstance(remedy, dict):
            raise RuntimeError("steal target vanished: the finding "
                               "carries no steal plan")
        raise RuntimeError(
            "steal target vanished: no live mesh group accepts "
            f"a steal plan (plan moved {len(remedy.get('keys') or [])}"
            " key(s))")

    def _resize_pool(self, finding: dict) -> tuple:
        """D012: a WARM backlog (warm-hit rate >= the doctor's split)
        is a capacity problem — grow the worker pool; a COLD one is a
        compile storm arriving through the front door — tighten
        admission (halve max_queue) so preflight/D001 can catch up
        instead of queueing more cold work. Both are reversible."""
        svc = self.svc
        snap = svc.snapshot()
        warm_rate = snap.get("warm_rate")
        from . import doctor
        warm = (warm_rate is None
                or float(warm_rate) >= doctor.QUEUE_WARM_SPLIT)
        if warm:
            from .service import POOL_MAX
            change = svc.resize_workers(min(svc.workers * 2,
                                            POOL_MAX))

            def rollback() -> None:
                svc.resize_workers(change["from"])

            return {"resize": change, "mode": "capacity"}, rollback
        prev_q = svc.max_queue
        svc.max_queue = max(8, prev_q // 2)

        def rollback_q() -> None:
            svc.max_queue = prev_q

        return {"max_queue": {"from": prev_q, "to": svc.max_queue},
                "mode": "tighten-admission"}, rollback_q

    def _pre_shed(self, finding: dict) -> tuple:
        """burn: open the shed window NOW — new arrivals 503 with a
        retry-after while the budget drains, before the multi-window
        alert would have forced the same brake harder and later."""
        svc = self.svc
        burning = finding.get("objectives") or [
            finding.get("subject") or "error-budget"]
        info = svc.open_shed(burning, source="autopilot")
        return {"shed": info}, svc.close_shed


# ---------------------------------------------------------------------------
# Supervisor — the verify-or-revert lifecycle
# ---------------------------------------------------------------------------

class Supervisor:
    """Poll the host, decide from the policy table, apply actuators,
    and hold every action to the verify-or-revert contract. One
    in-flight action per rule; a reverted rule is quarantined for the
    run. Thread-safe; `start()` runs `step()` on a daemon thread at
    `every_s`, or call `step()` directly (the tests do)."""

    def __init__(self, host: Host, *, every_s: float = 5.0,
                 verify_after_s: Optional[float] = None,
                 policy: tuple = POLICY, where: str = "service",
                 mx: Optional[metrics_mod.Registry] = None,
                 ledger: Optional[ledger_mod.Ledger] = None):
        self.host = host
        self.every_s = float(every_s)
        self.verify_after_s = (float(verify_after_s)
                               if verify_after_s is not None
                               else self.every_s)
        self.policy = tuple(policy)
        self.where = str(where)
        self._mx = mx
        self._ledger = ledger
        self._lock = lockwatch.lock("autopilot")
        self._pending: dict = {}      # rule -> in-flight action
        self._quarantine: dict = {}   # rule -> {t, reason, action_id}
        self._history: deque = deque(maxlen=HISTORY_CAP)
        self._counts = {e: 0 for e in EVENTS}
        self._steps = 0
        self._seq = 0
        self._qseq = 0
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rehydrate_quarantine()

    # -- lifecycle ----------------------------------------------------
    def start(self) -> "Supervisor":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop_ev.clear()
            self._thread = threading.Thread(
                target=self._loop, name="autopilot", daemon=True)
            self._thread.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        self._stop_ev.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        with self._lock:
            self._thread = None

    @property
    def active(self) -> bool:
        t = self._thread
        return (t is not None and t.is_alive()
                and not self._stop_ev.is_set())

    def _loop(self) -> None:
        while not self._stop_ev.wait(self.every_s):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — the supervisor
                # crashing silently would be the exact failure mode
                # this plane exists to remove
                try:
                    fleet.record_fault(fleet.fault_event(
                        e, stage="autopilot",
                        context={"rule": None, "action": "step"}),
                        mx=self._registry())
                except Exception:  # noqa: BLE001
                    pass

    # -- one control cycle --------------------------------------------
    def step(self, now: Optional[float] = None) -> dict:
        """One poll cycle: verify/revert every action past its
        deadline, then decide + apply against the fresh doctor/SLO
        findings. Returns a summary (the tests drive this directly)."""
        now = float(now if now is not None else time.time())
        out = {"verified": [], "reverted": [], "applied": [],
               "suppressed": [], "decisions": []}
        self._verify_pending(now, out)
        report = self._safe(self.host.diagnose)
        slo_rep = self._safe(self.host.slo_report)
        findings: dict = {}
        for f in (report or {}).get("findings") or []:
            findings.setdefault(f.get("rule"), f)
        bf = burn_finding(slo_rep)
        if bf is not None:
            findings["burn"] = bf
        for entry in self.policy:
            f = findings.get(entry.rule)
            if f is None:
                continue
            with self._lock:
                quarantined = entry.rule in self._quarantine
                in_flight = entry.rule in self._pending
            if quarantined:
                self._bank("suppress", entry, now, finding=f,
                           reason="quarantined")
                out["suppressed"].append(entry.rule)
                continue
            if in_flight:
                continue  # one action per rule until its verdict
            out["decisions"].append(entry.rule)
            self._decide_and_apply(entry, f, now, out)
        with self._lock:
            self._steps += 1
        return out

    def _verify_pending(self, now: float, out: dict) -> None:
        with self._lock:
            due = [(rule, act) for rule, act in self._pending.items()
                   if now >= act["deadline"]]
        for rule, act in due:
            entry: PolicyRule = act["entry"]
            before = act["baseline"]["value"]
            after = self._safe(self.host.probe, entry.metric,
                               act["t_applied"])
            with self._lock:
                self._pending.pop(rule, None)
            if entry.improved(before, after):
                self._bank("verify", entry, now, finding=act["finding"],
                           params=act["params"], before=before,
                           after=after, verdict="verified",
                           action_id=act["id"])
                out["verified"].append(rule)
                continue
            rolled = "none"
            rb = act.get("rollback")
            if rb is not None:
                try:
                    rb()
                    rolled = "applied"
                except Exception as e:  # noqa: BLE001 — a failed
                    rolled = "failed"   # rollback is itself a fault
                    self._record_actuator_fault(e, entry,
                                                phase="rollback")
            self._quarantine_rule(entry, now, act["id"],
                                  reason="verify-failed")
            self._bank("revert", entry, now, finding=act["finding"],
                       params=act["params"], before=before,
                       after=after, verdict="reverted",
                       reason="verify-failed", rollback=rolled,
                       action_id=act["id"], quarantined=True)
            out["reverted"].append(rule)

    def _decide_and_apply(self, entry: PolicyRule, finding: dict,
                          now: float, out: dict) -> None:
        action_id = self._next_id()
        baseline = self._safe(self.host.probe, entry.metric, None)
        self._bank("decision", entry, now, finding=finding,
                   before=baseline, action_id=action_id)
        try:
            params, rollback = self.host.actuate(entry, finding)
        except Exception as e:  # noqa: BLE001 — a failed actuator is
            # a structured fault + quarantine, never a dead loop
            self._record_actuator_fault(e, entry, phase="apply")
            self._quarantine_rule(entry, now, action_id,
                                  reason=f"apply-failed: "
                                         f"{type(e).__name__}: "
                                         f"{e}"[:200])
            self._bank("revert", entry, now, finding=finding,
                       before=baseline, verdict="reverted",
                       reason=f"apply-failed: {e}"[:200],
                       rollback="none", action_id=action_id,
                       quarantined=True)
            out["reverted"].append(entry.rule)
            return
        act = {"id": action_id, "entry": entry,
               "finding": finding, "params": params or {},
               "rollback": rollback, "t_applied": now,
               "baseline": {"metric": entry.metric,
                            "value": baseline,
                            "window_s": self.verify_after_s},
               "deadline": now + self.verify_after_s}
        with self._lock:
            self._pending[entry.rule] = act
        self._bank("apply", entry, now, finding=finding,
                   params=params or {}, before=baseline,
                   action_id=action_id)
        out["applied"].append(entry.rule)

    # -- plumbing -----------------------------------------------------
    def _safe(self, fn: Callable, *args):
        try:
            return fn(*args)
        except Exception:  # noqa: BLE001 — sensing failures read as
            return None    # "no data"; actuator failures are handled

    def _next_id(self) -> str:
        with self._lock:
            self._seq += 1
            return f"ap-{self._seq:04d}"

    def _registry(self):
        return (self._mx if self._mx is not None
                else metrics_mod.get_default())

    def _record_actuator_fault(self, exc: BaseException,
                               entry: PolicyRule,
                               phase: str) -> None:
        """Satellite contract: failed applications land as structured
        fleet faults (stage="autopilot") with rule/action attribution
        — the doctor can diagnose its own supervisor."""
        try:
            fleet.record_fault(fleet.fault_event(
                exc, stage="autopilot",
                context={"rule": entry.rule, "action": entry.action,
                         "phase": phase}), mx=self._registry())
        except Exception:  # noqa: BLE001
            pass

    def _quarantine_rule(self, entry: PolicyRule, now: float,
                         action_id: str, reason: str) -> None:
        info = {"t": round(now, 3), "reason": str(reason),
                "action": entry.action, "action_id": action_id}
        with self._lock:
            self._quarantine[entry.rule] = info
        self._bank_quarantine("quarantine", entry.rule, info)

    def _ledger_for_bank(self):
        return (self._ledger if self._ledger is not None
                else ledger_mod.get_default())

    def _bank_quarantine(self, event: str, rule: str,
                         info: Optional[dict] = None) -> None:
        """One `kind="autopilot-quarantine"` ledger record per
        quarantine transition — the durable half of the quarantine
        set. `event` is "quarantine" or "clear"; rehydration replays
        these in time order, the per-process `n` sequence breaking
        same-millisecond ties (record `t` rounds to 1 ms — a
        quarantine and its clear can land inside one tick, and the
        random id suffix must not decide which one "wins" the
        replay). Never raises."""
        try:
            with self._lock:
                self._qseq += 1
                n = self._qseq
            rec = {"kind": "autopilot-quarantine",
                   "name": f"autopilot-{rule}",
                   "event": event, "rule": str(rule),
                   "n": n, "where": self.where}
            if info:
                rec.update({"reason": info.get("reason"),
                            "action": info.get("action"),
                            "action_id": info.get("action_id")})
            self._ledger_for_bank().record(rec)
        except Exception:  # noqa: BLE001 — persistence must never
            pass           # hurt the control loop

    def clear_quarantine(self, rules=None) -> list:
        """Release quarantined rules (all, or the given subset) and
        bank each release — the explicit escape hatch (`serve
        --clear-quarantine` routes here via CLEAR_QUARANTINE_ENV).
        Returns the released rule ids."""
        with self._lock:
            targets = [r for r in (rules if rules is not None
                                   else list(self._quarantine))
                       if r in self._quarantine]
            for r in targets:
                self._quarantine.pop(r, None)
        for r in targets:
            self._bank_quarantine("clear", r)
        return targets

    def _rehydrate_quarantine(self) -> None:
        """Replay the store's `kind="autopilot-quarantine"` records
        (time-ordered: quarantine sets, clear releases) so a restart
        resumes with the quarantine the last run banked. With
        CLEAR_QUARANTINE_ENV truthy the replayed set is discarded AND
        the discard is banked, so the next restart starts clean too.
        Never raises."""
        try:
            led = self._ledger_for_bank()
            recs = led.query(kind="autopilot-quarantine")
        except Exception:  # noqa: BLE001
            return
        # query order is (t, id) — id suffixes are random, so break
        # same-millisecond ties with the banked sequence instead
        # (stable: equal keys keep the query order)
        recs = sorted(recs, key=lambda r: (r.get("t") or 0,
                                           r.get("n") or 0))
        # resume the sequence past everything replayed, so records
        # this process banks (the env-clear discards included) sort
        # after the replayed ones even inside the same millisecond
        with self._lock:
            self._qseq = max([self._qseq]
                             + [r["n"] for r in recs
                                if isinstance(r.get("n"), int)])
        restored: dict = {}
        for rec in recs:
            rule = rec.get("rule")
            if not rule:
                continue
            if rec.get("event") == "clear":
                restored.pop(str(rule), None)
            elif rec.get("event") == "quarantine":
                restored[str(rule)] = {
                    "t": rec.get("t"),
                    "reason": rec.get("reason"),
                    "action": rec.get("action"),
                    "action_id": rec.get("action_id"),
                    "restored": True}
        if not restored:
            return
        if os.environ.get(CLEAR_QUARANTINE_ENV, "").strip() \
                not in ("", "0", "false"):
            for rule in sorted(restored):
                self._bank_quarantine("clear", rule)
            return
        with self._lock:
            for rule, info in restored.items():
                self._quarantine.setdefault(rule, info)

    def _bank(self, event: str, entry: PolicyRule, now: float, *,
              finding: Optional[dict] = None,
              params: Optional[dict] = None,
              before=None, after=None,
              verdict: Optional[str] = None,
              reason: Optional[str] = None,
              rollback: Optional[str] = None,
              action_id: Optional[str] = None,
              quarantined: bool = False) -> None:
        """One lifecycle event into every plane: the `autopilot`
        series + counters, a `kind="autopilot-action"` ledger record,
        the bounded in-process history (snapshot / panel / Perfetto
        lane). Never raises — the control loop outranks its
        accounting."""
        with self._lock:
            self._counts[event] = self._counts.get(event, 0) + 1
            row = {"t": round(now, 3), "id": action_id,
                   "event": event, "rule": entry.rule,
                   "action": entry.action, "metric": entry.metric,
                   "verdict": verdict, "reason": reason,
                   "before": before, "after": after,
                   "subject": (finding or {}).get("subject")}
            self._history.append(row)
        try:
            mx = self._registry()
            if mx.enabled:
                pt = {"event": event, "rule": entry.rule,
                      "action": entry.action, "where": self.where,
                      "metric": entry.metric}
                if isinstance(before, (int, float)):
                    pt["metric_before"] = float(before)
                if isinstance(after, (int, float)):
                    pt["metric_after"] = float(after)
                if verdict:
                    pt["verdict"] = verdict
                if reason:
                    pt["reason"] = str(reason)
                mx.series(
                    "autopilot",
                    "autopilot control-loop lifecycle events "
                    "(decision/apply/verify/revert/suppress)"
                ).append(pt)
                mx.counter(
                    "autopilot_events_total",
                    "autopilot lifecycle events by rule").inc(
                    event=event, rule=entry.rule)
        except Exception:  # noqa: BLE001
            pass
        try:
            led = (self._ledger if self._ledger is not None
                   else ledger_mod.get_default())
            rec = {"kind": "autopilot-action",
                   "name": f"autopilot-{entry.rule}",
                   "event": event, "rule": entry.rule,
                   "action": entry.action, "where": self.where,
                   "metric": entry.metric,
                   "params": dict(params or {}),
                   "action_id": action_id}
            if finding is not None:
                from . import doctor
                rec["finding"] = (doctor.compact_finding(finding)
                                  if finding.get("rule") != "burn"
                                  else {k: finding.get(k) for k in
                                        ("rule", "name", "severity",
                                         "summary", "subject")})
            if event in ("apply", "verify", "revert"):
                rec["baseline"] = {"metric": entry.metric,
                                   "value": before,
                                   "window_s": self.verify_after_s}
            if after is not None:
                rec["metric_after"] = after
            if verdict:
                rec["verdict"] = verdict
            if reason:
                rec["reason"] = str(reason)
            if rollback:
                rec["rollback"] = rollback
            if quarantined:
                rec["quarantined"] = True
            led.record(rec)
        except Exception:  # noqa: BLE001
            pass

    # -- surfacing ----------------------------------------------------
    def history(self) -> list:
        with self._lock:
            return list(self._history)

    def quarantined(self) -> dict:
        with self._lock:
            return dict(self._quarantine)

    def snapshot(self) -> dict:
        """The `/status.json` `autopilot` block."""
        with self._lock:
            pending = [{"rule": r, "action": a["entry"].action,
                        "deadline_in_s": round(
                            a["deadline"] - time.time(), 3)}
                       for r, a in self._pending.items()]
            return {"active": self.active, "where": self.where,
                    "steps": self._steps,
                    "every_s": self.every_s,
                    "policy": policy_rows(self.policy),
                    "counts": dict(self._counts),
                    "quarantined": {r: dict(q) for r, q in
                                    self._quarantine.items()},
                    "pending": pending,
                    "actions": list(self._history)[-16:]}

    def perfetto_instants(self, cap: int = 64) -> list:
        """Instant markers for the "autopilot actions" Perfetto lane
        (trace.to_perfetto's `instants=`; trace.instant_events groups
        by the `lane` key)."""
        out: list = []
        for a in self.history():
            out.append({"t": float(a["t"]),
                        "name": f"{a['event']} {a['rule']} "
                                f"{a['action']}"[:80],
                        "lane": PERFETTO_LANE})
            if len(out) >= cap:
                break
        return out


# -- ambient default ---------------------------------------------------------
# The serving process's supervisor answers /status.json's `autopilot`
# block (the service/doctor snapshot pattern); Service.start installs
# it when constructed with autopilot=True.
_default: Optional[Supervisor] = None


def get_default() -> Optional[Supervisor]:
    return _default


def set_default(sup: Optional[Supervisor]) -> Optional[Supervisor]:
    global _default
    prev = _default
    _default = sup
    return prev


def snapshot() -> dict:
    """The module-level `/status.json` `autopilot` block: the default
    supervisor's snapshot, or the explicit idle stub."""
    sup = _default
    if sup is None:
        return {"active": False, "steps": 0, "counts": {},
                "quarantined": {}, "pending": [], "actions": []}
    return sup.snapshot()


def perfetto_instants(cap: int = 64) -> list:
    """The default supervisor's action markers ([] when idle)."""
    sup = _default
    return sup.perfetto_instants(cap=cap) if sup is not None else []


def _reset() -> None:
    """Test isolation: drop the ambient supervisor."""
    set_default(None)


# ---------------------------------------------------------------------------
# CLI — offline policy replay
# ---------------------------------------------------------------------------

def format_replay(decisions: list, report: dict) -> str:
    """The human rendering of one replay (the CLI's non-JSON path)."""
    head = (f"autopilot replay: target={report.get('target')} "
            f"platform={report.get('platform')} — ")
    if not decisions:
        return head + ("nothing to do (no policy rule matches the "
                       "diagnosis)")
    lines = [head + f"{len(decisions)} action(s) would fire"]
    for d in decisions:
        subj = f" @ {d['subject']}" if d.get("subject") else ""
        lines.append(f"  [{d['rule']}] {d['action']}{subj}: "
                     f"{d.get('summary')}")
        lines.append(f"{'':10s}-> verify via {d['metric']} — "
                     f"{d['description']}")
    return "\n".join(lines)


def cli_main(options: dict, arguments: Optional[list] = None) -> int:
    """`python -m jepsen_tpu autopilot <run_id|latest|bench>` —
    replay the frozen policy table against a banked run's diagnosis:
    print what the supervisor WOULD have done (decide step only — no
    actuators, read-only, nothing banked). The regression surface
    for the D-catalog -> action mapping."""
    from . import doctor
    from . import slo as slo_mod
    target = None
    for a in arguments or []:
        target = a
        break
    target = target or options.get("target") or "latest"
    root = options.get("root") or os.getcwd()
    store_root = options.get("store") or os.path.join(root, "store")
    try:
        if target == "bench":
            view = doctor.bench_view(root)
        else:
            view = doctor.run_view(store_root, target)
    except KeyError as e:
        print(f"autopilot: {e.args[0]}")
        return 254
    report = doctor.diagnose(view)
    try:
        slo_rep = slo_mod.evaluate_store(store_root)
    except Exception:  # noqa: BLE001 — no service traffic recorded
        slo_rep = None
    decisions = replay(report, slo_rep)
    if options.get("json"):
        print(json.dumps({"schema": SCHEMA,
                          "target": report.get("target"),
                          "rules_fired": report.get("rules_fired"),
                          "decisions": decisions,
                          "policy": policy_rows()},
                         indent=2, default=str))
    else:
        print(format_replay(decisions, report))
    return 0
