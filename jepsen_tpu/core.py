"""Test orchestration: the master run() lifecycle.

Capability parity with jepsen.core (`jepsen/src/jepsen/core.clj`):
`run(test)` takes a test map (documented at core.clj:328-353 — nodes,
ssh, os, db, client, nemesis, generator, checker, net, remote, …),
prepares it (core.clj:311-325), opens sessions to every node in
parallel (with-sessions, core.clj:275-295), sets up the OS
(core.clj:93-100) and DB (db.cycle with retries + log snarfing,
core.clj:172-181), runs the case — nemesis setup in parallel with
client open/setup per node, then the interpreter hot loop
(core.clj:183-219) — under the relative-time clock, indexes the
history, checks it (core.clj:221-237), persists everything through the
store (3-phase save), and logs a human verdict (core.clj:239-252).
"""

from __future__ import annotations

import logging
import os
import time as _time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

from . import checker as jchecker
from . import client as jclient
from . import control
from . import db as jdb
from . import nemesis as jnemesis
from . import util
from .generator import interpreter
from .history import History

log = logging.getLogger("jepsen_tpu.core")


def prepare_test(test: dict) -> dict:
    """Ensure start_time and concurrency (core.clj:311-325)."""
    test = dict(test)
    if not test.get("start_time"):
        test["start_time"] = _time.strftime("%Y%m%dT%H%M%S")
    if not test.get("concurrency"):
        test["concurrency"] = len(test.get("nodes") or [])
    return test


class _Sessions:
    """Open sessions to all nodes in parallel; close them afterwards
    (with-sessions + with-resources, core.clj:70-91, 275-295)."""

    def __init__(self, test: dict):
        self.test = test
        self.sessions: dict = {}

    def __enter__(self) -> dict:
        nodes = self.test.get("nodes") or []
        try:
            opened = util.real_pmap(control.bound_fn(control.session),
                                    nodes)
        except Exception:
            self.close()
            raise
        self.sessions = dict(zip(nodes, opened))
        return {**self.test, "sessions": self.sessions}

    def close(self):
        for s in self.sessions.values():
            try:
                control.disconnect(s)
            except Exception:  # noqa: BLE001
                pass

    def __exit__(self, *exc):
        self.close()


def snarf_logs(test: dict) -> None:
    """Download DB log files into the store directory (core.clj:102-136)."""
    db = test.get("db")
    store_dir = test.get("store_dir")
    if not isinstance(db, jdb.LogFiles) or not store_dir:
        return

    def snarf(t, node):
        from .control import nodeutil as cu
        for remote in db.log_files(t, node):
            if cu.file_exists(remote):
                local = os.path.join(store_dir, str(node),
                                     remote.lstrip("/"))
                os.makedirs(os.path.dirname(local), exist_ok=True)
                try:
                    control.download(remote, local)
                except Exception as e:  # noqa: BLE001
                    log.info("couldn't download %s: %s", remote, e)

    log.info("Snarfing log files")
    control.on_nodes(test, snarf)


def run_case(test: dict) -> list:
    """Set up nemesis (concurrently) + clients, run the interpreter,
    tear everything down (core.clj:183-219)."""
    client = test["client"]
    nemesis = jnemesis.validate(test.get("nemesis") or jnemesis.noop())
    with ThreadPoolExecutor(max_workers=1) as pool:
        nemesis_fut = pool.submit(nemesis.setup, test)

        def open_and_setup(node):
            c = client.open(test, node)
            c.setup(test)
            return c

        clients = util.real_pmap(open_and_setup, test.get("nodes") or [])
        nemesis = nemesis_fut.result()
    test = {**test, "nemesis": nemesis}
    try:
        return interpreter.run(test)
    finally:
        with ThreadPoolExecutor(max_workers=1) as pool:
            td = pool.submit(nemesis.teardown, test)

            def teardown_client(c):
                try:
                    c.teardown(test)
                finally:
                    c.close(test)

            util.real_pmap(teardown_client, clients)
            td.result()


def analyze(test: dict) -> dict:
    """Index the history, run the checker (core.clj:221-237)."""
    log.info("Analyzing...")
    # analysis kernels recompile per shape bucket; the persistent
    # cache makes repeat runs skip straight to the search (lazy here
    # — not CLI startup — so jax-free commands never import jax)
    from .util import enable_compilation_cache
    enable_compilation_cache()
    history = test["history"]
    if not isinstance(history, History):
        history = History(history)
    history = history.index()
    test = {**test, "history": history}
    test["results"] = jchecker.check_safe(
        test.get("checker") or jchecker.unbridled_optimism(),
        test, history, {})
    log.info("Analysis complete")
    return test


def log_results(test: dict) -> dict:
    """core.clj:239-252."""
    valid = test.get("results", {}).get("valid?")
    if valid is False:
        verdict = "Analysis invalid! (ノಥ益ಥ）ノ ┻━┻"
    elif valid == "unknown":
        verdict = ("Errors occurred during analysis, "
                   "but no anomalies found. ಠ~ಠ")
    else:
        verdict = "Everything looks good! ヽ('ー`)ノ"
    log.info("%r\n\n%s", test.get("results"), verdict)
    return test


def run(test: dict) -> dict:
    """Run a complete test; returns the test map with "history" and
    "results" (core.clj:327-406). See module docstring for phases."""
    test = prepare_test(test)

    from . import fleet, store
    from . import ledger as ledger_mod
    from . import watchdog as watchdog_mod
    t_run0 = _time.monotonic()
    writer = store.Writer(test) if test.get("name") else None
    # Live run status (fleet.RunStatus, doc/OBSERVABILITY.md): ambient
    # for the whole run — the interpreter, checker phase spans, and the
    # device fan-out all update it; `serve` exposes it at /status.json.
    # Updates land at poll/key boundaries only, so this is always on.
    # The throttled file mirror under the STORE ROOT lets an
    # out-of-process `serve` watch the run live.
    status_file = (os.path.join(test.get("store_root") or store.BASE_DIR,
                                fleet.STATUS_FILENAME)
                   if writer else None)
    status = fleet.RunStatus(test=test.get("name"),
                             status_file=status_file)
    prev_status = fleet.set_default(status)
    # Run-ledger + stall-watchdog accounting (doc/OBSERVABILITY.md):
    # named runs append per-analysis + per-run records under the store
    # root's ledger/, and a heartbeat watchdog surveils the device
    # loops so a hang INSIDE a device round is detected and recorded
    # instead of blocking silently. Both restore the previous ambient
    # defaults on exit.
    prev_ledger = ledger_mod.set_default(
        ledger_mod.Ledger(test.get("store_root") or store.BASE_DIR)
        if writer else ledger_mod.get_default())
    # Device observatory (devices.py): per-run HBM accounting sampled
    # at the kernels' existing poll cadences — /status.json's `hbm`
    # block, the /devices panel, and hbm_peak_measured on results all
    # read from this ambient monitor.
    from . import devices as devices_mod
    prev_devmon = devices_mod.set_default(devices_mod.DeviceMonitor())
    wd_installed = None
    if not watchdog_mod.get_default().enabled:
        wd_installed = watchdog_mod.Watchdog()
        prev_wd = watchdog_mod.set_default(wd_installed)
    if writer:
        test["store_dir"] = writer.dir
        store.start_logging(test)
    try:
        if writer:
            writer.save_0(test)
        remote_ctx = control.with_remote(test["remote"]) \
            if test.get("remote") is not None else None
        with (remote_ctx or _nullcontext()):
            with control.with_ssh(test.get("ssh")):
                with _Sessions(test) as test:
                    os_obj = test.get("os")
                    try:
                        if os_obj:
                            control.on_nodes(
                                test, lambda t, n: os_obj.setup(t, n))
                        try:
                            if test.get("db"):
                                jdb.cycle(test)
                            status.phase("run")
                            with util.with_relative_time():
                                test = {**test,
                                        "history": run_case(test)}
                            log.info("Run complete, writing")
                            if writer:
                                writer.save_1(test)
                            snarf_logs(test)
                        finally:
                            if test.get("db") and not test.get(
                                    "leave_db_running?"):
                                db = test["db"]
                                control.on_nodes(
                                    test, lambda t, n: db.teardown(t, n))
                    finally:
                        if os_obj:
                            control.on_nodes(
                                test, lambda t, n: os_obj.teardown(t, n))
                    status.phase("analyze")
                    test = analyze(test)
                    if writer:
                        writer.save_2(test)
        return log_results(test)
    finally:
        valid = (test.get("results") or {}).get("valid?")
        status.finish(valid=valid)
        fleet.set_default(prev_status)
        # a test-map tracer's spans land in the run dir (the dgraph
        # suites' span-export artifact, trace.clj + trace.py) — in the
        # outer finally so crashed runs (when the trace matters most)
        # still export, and guarded so a broken tracer can't void the
        # run's other artifacts
        tracer = test.get("tracer")
        artifacts = {}
        if tracer is not None and writer:
            try:
                n = tracer.export(os.path.join(writer.dir,
                                               "trace.jsonl"))
                # the same spans in Chrome/Perfetto trace_event form:
                # drop the file in ui.perfetto.dev and the run's
                # encode/compile/device-round/fan-out phases render as
                # a flame chart (doc/OBSERVABILITY.md walkthrough) —
                # with the occupancy plane's fill/frontier/backlog
                # series embedded as counter tracks under the spans
                from . import metrics as metrics_mod
                from . import occupancy as occupancy_mod
                tracer.export_perfetto(
                    os.path.join(writer.dir, "trace.perfetto.json"),
                    counters=occupancy_mod.perfetto_counter_tracks(
                        metrics_mod.get_default()))
                log.info("Exported %d spans", n)
                root = test.get("store_root") or store.BASE_DIR
                artifacts = {
                    "trace": os.path.relpath(
                        os.path.join(writer.dir, "trace.jsonl"), root),
                    "perfetto": os.path.relpath(
                        os.path.join(writer.dir,
                                     "trace.perfetto.json"), root)}
            except Exception:  # noqa: BLE001
                log.warning("trace export failed", exc_info=True)
        led = ledger_mod.get_default()
        if writer and led.enabled:
            # the run-level ledger record: per-analysis records were
            # appended by the checkers; this one ties them to the run
            # dir, the verdict, and the end-to-end wall
            try:
                root = test.get("store_root") or store.BASE_DIR
                wd_now = watchdog_mod.get_default()
                led.record({
                    "kind": "run", "name": test.get("name"),
                    "verdict": valid,
                    "wall_s": round(_time.monotonic() - t_run0, 4),
                    "ops": len(test.get("history") or []),
                    "stalls": len(wd_now.stalls) if wd_now.enabled
                    else 0,
                    "artifacts": {
                        "dir": os.path.relpath(writer.dir, root),
                        **artifacts}})
            except Exception:  # noqa: BLE001
                log.warning("ledger record failed", exc_info=True)
        ledger_mod.set_default(prev_ledger)
        devices_mod.set_default(prev_devmon)
        if wd_installed is not None:
            wd_installed.stop()
            watchdog_mod.set_default(prev_wd)
        if writer:
            store.stop_logging()
            writer.close()


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False
