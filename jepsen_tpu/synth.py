"""Synthetic concurrent histories for tests and benchmarks.

Simulates N logical processes running against a *real* in-memory object
(register / cas-register / mutex / fifo-queue) under a random interleaving,
emitting invoke/ok/fail/info events exactly as the interpreter journals
them. Because ops execute against real state, the histories are
linearizable by construction; `lie_p` injects occasional wrong read values
to produce known-invalid histories; `crash_p` leaves ops in the :info
state (applied or not, at random), exercising the may-linearize path.

This stands in for the etcd-style workloads the BASELINE configs name
(e.g. "etcd linearizable-register histories") without needing a cluster.
"""

from __future__ import annotations

import random
from typing import Optional

from . import history as h


def cas_register_history(n_ops: int, n_procs: int = 5, values: int = 5,
                         crash_p: float = 0.02, lie_p: float = 0.0,
                         seed: int = 0,
                         fs=("read", "write", "cas")) -> h.History:
    """A concurrent cas-register run (r/w/cas over `values` small ints,
    matching the reference workload's rand-int 5 values,
    jepsen/src/jepsen/tests/linearizable_register.clj:18-20)."""
    rng = random.Random(seed)
    hist = h.History()
    reg: Optional[int] = None
    pending: dict = {}
    free = list(range(n_procs))
    next_pid = n_procs
    issued = 0
    t = 0
    while issued < n_ops or pending:
        can_invoke = free and issued < n_ops
        if not can_invoke and not pending:
            break
        if can_invoke and (not pending or rng.random() < 0.6):
            p = free.pop(rng.randrange(len(free)))
            f = rng.choice(fs)
            if f == "read":
                v = None
            elif f == "write":
                v = rng.randrange(values)
            else:
                v = [rng.randrange(values), rng.randrange(values)]
            hist.append(h.invoke(p, f, v, time=t))
            pending[p] = (f, v)
            issued += 1
        else:
            p = rng.choice(list(pending))
            f, v = pending.pop(p)
            r = rng.random()
            if r < crash_p:
                hist.append(h.info(p, f, v, time=t))
                if rng.random() < 0.5 and f != "read":
                    if f == "write":
                        reg = v
                    elif v[0] == reg:
                        reg = v[1]
                # a crashed process is retired; the interpreter assigns a
                # fresh process id to its worker (interpreter.clj:233-236)
                free.append(next_pid)
                next_pid += 1
            else:
                if f == "read":
                    val = reg
                    if lie_p and rng.random() < lie_p:
                        val = (reg or 0) + 1
                    hist.append(h.ok(p, f, val, time=t))
                elif f == "write":
                    reg = v
                    hist.append(h.ok(p, f, v, time=t))
                else:
                    if v[0] == reg:
                        reg = v[1]
                        hist.append(h.ok(p, f, v, time=t))
                    else:
                        hist.append(h.fail(p, f, v, time=t))
                free.append(p)
        t += 1
    return hist.index()


def mutex_history(n_ops: int, n_procs: int = 4, seed: int = 0) -> h.History:
    """A concurrent mutex run: processes race to acquire; the simulated
    lock serializes them, so the history is linearizable."""
    rng = random.Random(seed)
    hist = h.History()
    holder: Optional[int] = None
    pending: dict = {}  # process -> f
    free = list(range(n_procs))
    issued = 0
    t = 0
    while issued < n_ops or pending:
        can_invoke = free and issued < n_ops
        if not can_invoke and not pending:
            break
        if can_invoke and (not pending or rng.random() < 0.5):
            p = free.pop(rng.randrange(len(free)))
            f = "release" if p == holder else "acquire"
            hist.append(h.invoke(p, f, None, time=t))
            pending[p] = f
            issued += 1
        else:
            # complete a pending op that is currently legal, if any
            completable = [p for p, f in pending.items()
                           if (f == "acquire" and holder is None)
                           or (f == "release" and holder == p)]
            if not completable:
                # everyone is stuck waiting on the lock: nobody can
                # complete until the holder releases — force an invoke
                if free and issued < n_ops:
                    continue
                break
            p = rng.choice(completable)
            f = pending.pop(p)
            holder = p if f == "acquire" else None
            hist.append(h.ok(p, f, None, time=t))
            free.append(p)
        t += 1
    return hist.index()


def fifo_queue_history(n_ops: int, n_procs: int = 4, seed: int = 0
                       ) -> h.History:
    """A concurrent FIFO-queue run against a real queue."""
    rng = random.Random(seed)
    hist = h.History()
    q: list = []
    nxt = 0
    pending: dict = {}
    free = list(range(n_procs))
    issued = 0
    t = 0
    while issued < n_ops or pending:
        can_invoke = free and issued < n_ops
        if not can_invoke and not pending:
            break
        if can_invoke and (not pending or rng.random() < 0.6):
            p = free.pop(rng.randrange(len(free)))
            # a dequeue is only issued when something can satisfy it,
            # or every process could end up blocked on an empty queue
            can_deq = q or any(f == "enqueue"
                               for f, _ in pending.values())
            if rng.random() < 0.55 or not can_deq:
                f, v = "enqueue", nxt
                nxt += 1
            else:
                f, v = "dequeue", None
            hist.append(h.invoke(p, f, v, time=t))
            pending[p] = (f, v)
            issued += 1
        else:
            completable = [p for p, (f, _) in pending.items()
                           if f == "enqueue" or q]
            if not completable:
                if free and issued < n_ops:
                    continue
                break
            p = rng.choice(completable)
            f, v = pending.pop(p)
            if f == "enqueue":
                q.append(v)
                hist.append(h.ok(p, f, v, time=t))
            else:
                hist.append(h.ok(p, f, q.pop(0), time=t))
            free.append(p)
        t += 1
    return hist.index()


def long_tail_history(n_quick: int, n_slow: int = 1, values: int = 5,
                      lie_p: float = 0.0, seed: int = 0) -> h.History:
    """Porcupine-style adversarial long tail: `n_slow` reads stay open
    across the whole run while other processes complete `n_quick` fast
    ops — every fast op overlaps the slow ones, so the WGL window
    requirement is ~n_quick (BASELINE.md "adversarial long-tail
    histories"; the JVM checker degrades in exactly this regime)."""
    rng = random.Random(seed)
    hist = h.History()
    reg: Optional[int] = None
    t = 0
    for p in range(n_slow):
        hist.append(h.invoke(p, "read", None, time=t))
        t += 1
    fast = n_slow
    for _ in range(n_quick):
        f = rng.choice(["write", "read", "cas"])
        if f == "write":
            v = rng.randrange(values)
        elif f == "cas":
            v = [rng.randrange(values), rng.randrange(values)]
        else:
            v = None
        hist.append(h.invoke(fast, f, v, time=t))
        t += 1
        if f == "write":
            reg = v
            hist.append(h.ok(fast, f, v, time=t))
        elif f == "cas":
            if v[0] == reg:
                reg = v[1]
                hist.append(h.ok(fast, f, v, time=t))
            else:
                hist.append(h.fail(fast, f, v, time=t))
        else:
            out = reg
            if lie_p and rng.random() < lie_p:
                out = (reg or 0) + 1
            hist.append(h.ok(fast, f, out, time=t))
        t += 1
    # the slow reads finally return: any value the register ever held is
    # linearizable somewhere in their span; report the final value
    for p in range(n_slow):
        hist.append(h.ok(p, "read", reg, time=t))
        t += 1
    return hist.index()


def adversarial_wave_history(n_waves: int, width: int = 14,
                             span: int = 5, seed: int = 0,
                             invalid: bool = True) -> h.History:
    """The device-or-nothing benchmark shape: a history whose decision
    REQUIRES mass state-space exhaustion, engineered so the reachable
    config count exceeds what a host DFS can visit in the 60 s budget
    while staying inside the device kernel's capacities.

    Structure (Porcupine-adversarial family, BASELINE.md "long-tail
    histories"; cf. the reference's truncated-analysis warning at
    jepsen/src/jepsen/checker.clj:213-216 — the JVM checker gives up on
    exactly this regime):

      * `n_waves` waves of `width` CONCURRENT blind writes of distinct
        values. Blind writes make every interleaving legal, so an
        exhaustive verdict must visit ~width * 2^(width-1) configs per
        wave (window-mask subsets x last-writer states). Waves are
        real-time ordered, so the space is the SUM over waves, not the
        product — total configs are tuned linearly by `n_waves`.
      * a straggler read held open across `span` waves stretches the
        WGL window to ~span*width+1 ops (> 32 forces the general
        wide-window kernel, not the uint32 fast path) without adding
        branching of its own.
      * `invalid` appends a final read of a never-written value, so
        NO search can shortcut: proving False means exhausting every
        reachable config — the fair fight between engines.

    At the defaults, width=14 gives ~135k configs/wave (measured;
    host oracle ~25-30k configs/s, i.e. DNF past ~14 waves), and the
    wavefront (~C(14,7)*14 = 48k live configs) fits the general
    kernel's scaled backlog (ops/wgl.py _pick_capacities)."""
    rng = random.Random(seed)
    hist = h.History()
    t = 0
    val = 0
    strag_pid = width  # dedicated straggler process id
    strag_open_since: Optional[int] = None
    last_wave_val: Optional[int] = None
    for wv in range(n_waves):
        if strag_open_since is None:
            hist.append(h.invoke(strag_pid, "read", None, time=t))
            t += 1
            strag_open_since = wv
        order = list(range(width))
        rng.shuffle(order)
        wave_vals = []
        for p in order:
            v = val
            val += 1
            hist.append(h.invoke(p, "write", v, time=t))
            t += 1
            wave_vals.append((p, v))
        rng.shuffle(wave_vals)
        for p, v in wave_vals:
            hist.append(h.ok(p, "write", v, time=t))
            t += 1
            last_wave_val = v
        if wv - strag_open_since + 1 >= span:
            # straggler returns the last write of this wave — legal
            # (linearize the read right here), so it constrains nothing
            hist.append(h.ok(strag_pid, "read", last_wave_val, time=t))
            t += 1
            strag_open_since = None
    if strag_open_since is not None:
        hist.append(h.ok(strag_pid, "read", last_wave_val, time=t))
        t += 1
    hist.append(h.invoke(0, "read", None, time=t))
    t += 1
    hist.append(h.ok(0, "read",
                     -1 if invalid else last_wave_val, time=t))
    return hist.index()


def _txn_scheduler(n_txns: int, n_procs: int, crash_p: float,
                   rng, next_txn, apply_ok, apply_crash) -> h.History:
    """Shared concurrent-txn simulation loop: random interleaving of
    invocations and completions, txns applied atomically at completion
    (serialization point inside the op window -> serializable AND
    realtime-consistent by construction), crashes left :info with a
    coin-flip apply, crashed processes retired for fresh pids
    (interpreter.clj:233-236).

    next_txn() -> mops; apply_ok(txn) -> completed mops;
    apply_crash(txn) -> None (the 'may have applied' branch)."""
    hist = h.History()
    pending: dict = {}
    free = list(range(n_procs))
    next_pid = n_procs
    issued = 0
    t = 0
    while issued < n_txns or pending:
        can_invoke = free and issued < n_txns
        if not can_invoke and not pending:
            break
        if can_invoke and (not pending or rng.random() < 0.6):
            p = free.pop(rng.randrange(len(free)))
            txn = next_txn()
            hist.append(h.invoke(p, "txn", txn, time=t))
            pending[p] = txn
            issued += 1
        else:
            p = rng.choice(list(pending))
            txn = pending.pop(p)
            if rng.random() < crash_p:
                hist.append(h.info(p, "txn", txn, time=t))
                if rng.random() < 0.5:  # may or may not have applied
                    apply_crash(txn)
                free.append(next_pid)
                next_pid += 1
            else:
                hist.append(h.ok(p, "txn", apply_ok(txn), time=t))
                free.append(p)
        t += 1
    return hist.index()


def list_append_history(n_txns: int, n_procs: int = 5, key_count: int = 4,
                        max_txn_length: int = 4, crash_p: float = 0.01,
                        corrupt_p: float = 0.0,
                        seed: int = 0) -> h.History:
    """A concurrent list-append run for the elle checkers (shared
    scheduler: _txn_scheduler). `corrupt_p` drops a random element from
    a random read's result to produce known-invalid histories.

    Shapes follow the reference generator (elle.list-append/gen via
    tests/cycle/append.clj:28-31): rotating key pool, unique
    monotonically increasing values per key."""
    from .elle.append import AppendGen

    rng = random.Random(seed)
    gen = AppendGen(key_count=key_count, max_txn_length=max_txn_length,
                    seed=seed)
    lists: dict = {}

    def apply_write(txn):
        for f, k, v in txn:
            if f == "append":
                lists.setdefault(k, []).append(v)

    def apply_ok(txn):
        done = []
        for f, k, v in txn:
            if f == "append":
                lists.setdefault(k, []).append(v)
                done.append([f, k, v])
            else:
                out = list(lists.get(k, []))
                if corrupt_p and out and rng.random() < corrupt_p:
                    out.pop(rng.randrange(len(out)))
                done.append([f, k, out])
        return done

    return _txn_scheduler(n_txns, n_procs, crash_p, rng, gen.txn,
                          apply_ok, apply_write)


def wr_register_history(n_txns: int, n_procs: int = 5, key_count: int = 4,
                        max_txn_length: int = 4, crash_p: float = 0.01,
                        stale_p: float = 0.0,
                        seed: int = 0) -> h.History:
    """A concurrent write/read-register run for the elle wr checker
    (shared scheduler: _txn_scheduler): unique writes per key (the
    rw-register workload's invariant). `stale_p` makes a read return
    the PREVIOUS value of its key, producing known anomalies.

    Shapes follow the reference generator (tests/cycle/wr.clj:14-53
    semantics via the shared WrGen key pool)."""
    from .elle.wr import WrGen

    rng = random.Random(seed)
    gen = WrGen(key_count=key_count, max_txn_length=max_txn_length,
                seed=seed)
    regs: dict = {}
    prev: dict = {}

    def apply_write(txn):
        for f, k, v in txn:
            if f == "w":
                prev[k] = regs.get(k)
                regs[k] = v

    def apply_ok(txn):
        done = []
        for f, k, v in txn:
            if f == "w":
                prev[k] = regs.get(k)
                regs[k] = v
                done.append([f, k, v])
            else:
                out = regs.get(k)
                if stale_p and k in prev and rng.random() < stale_p:
                    out = prev[k]
                done.append([f, k, out])
        return done

    return _txn_scheduler(n_txns, n_procs, crash_p, rng, gen.txn,
                          apply_ok, apply_write)
