"""Write/read register workload package (parity with
`jepsen/src/jepsen/tests/cycle/wr.clj:14-53`; engine is
`jepsen_tpu.elle.wr`). Writes are assumed unique."""

from __future__ import annotations

from typing import Iterable, Optional

from ..checker import Checker
from ..elle import wr as elle_wr
from .cycle_append import _dump_anomalies


class WrChecker(Checker):
    """Checker for write/read register histories. Options mirror
    wr.clj:16-28: sequential_keys / linearizable_keys / wfr_keys pick
    the version-order inference assumptions; additional_graphs adds
    realtime/process edges."""

    def __init__(self, anomalies: Iterable[str] = ("G0", "G1a", "G1b",
                                                   "G1c", "G-single",
                                                   "G2", "internal",
                                                   "cyclic-versions"),
                 additional_graphs: Iterable[str] = (),
                 sequential_keys: bool = False,
                 linearizable_keys: bool = False,
                 wfr_keys: bool = False):
        self.anomalies = tuple(anomalies)
        self.additional_graphs = tuple(additional_graphs)
        self.sequential_keys = sequential_keys
        self.linearizable_keys = linearizable_keys
        self.wfr_keys = wfr_keys

    def check(self, test, history, opts=None):
        res = elle_wr.check(
            history, anomalies=self.anomalies,
            additional_graphs=self.additional_graphs,
            sequential_keys=self.sequential_keys,
            linearizable_keys=self.linearizable_keys,
            wfr_keys=self.wfr_keys)
        _dump_anomalies(test, opts, res)
        return res


def checker(**opts) -> Checker:
    return WrChecker(**opts)


def gen(key_count: int = 3, min_txn_length: int = 1,
        max_txn_length: int = 4, max_writes_per_key: int = 32,
        seed: Optional[int] = None):
    return elle_wr.WrGen(
        key_count=key_count, min_txn_length=min_txn_length,
        max_txn_length=max_txn_length,
        max_writes_per_key=max_writes_per_key, seed=seed)


def workload(key_count: int = 3, min_txn_length: int = 1,
             max_txn_length: int = 4, max_writes_per_key: int = 32,
             seed: Optional[int] = None, **checker_opts) -> dict:
    return {"generator": gen(key_count, min_txn_length, max_txn_length,
                             max_writes_per_key, seed),
            "checker": checker(**checker_opts)}
