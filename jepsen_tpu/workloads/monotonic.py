"""Monotonic workload (tidb/src/tidb/monotonic.clj:1-113, also the
faunadb suite's monotonic family).

A collection of integer registers is incremented via read-write
transactions and read in small groups. Each key's value only ever
grows, so the values observed for a key order the transactions that
observed them; those per-key orders must be mutually consistent — no
transaction may observe x increase while y decreases relative to
another transaction. Violations are cycles in the union of the
per-key version orders, found with the same typed-graph machinery as
the elle checkers (WW edges + SCC search; monotonic.clj:105-111 wires
the reference's cycle/checker the same way).

Client contract:
    {"f": "inc",  "value": {k: v_after, ...}}   increment ks, report
                                                the values written
    {"f": "read", "value": {k: v, ...}}         read a key group
                                                (missing keys -> -1)
"""

from __future__ import annotations

from typing import Optional

from .. import checker as jchecker
from .. import generator as gen
from ..elle.graph import WW, DepGraph

DEFAULT_KEYS = 8
GROUP = 3


class MonotonicChecker(jchecker.Checker):
    """Cycle search over per-key observed-value orders."""

    def check(self, test, history, opts=None):
        oks = [op for op in history
               if op.is_ok and op.f in ("inc", "read")
               and isinstance(op.value, dict)]
        g = DepGraph()
        for op in oks:
            g.add_node(op.index)
        # per key: sort ops by observed value; earlier value -> later
        # value orders the txns (equal values are concurrent — no edge)
        by_key: dict = {}
        for op in oks:
            for k, v in op.value.items():
                if v is None:
                    continue
                by_key.setdefault(k, []).append((v, op.index))
        hub = -1  # synthetic hub ids are negative (history ids are >=0)
        for k, pairs in by_key.items():
            # group ops by distinct observed value: EVERY op at value v
            # precedes every op at the next distinct value (linking
            # only adjacent sorted pairs would let ties swallow edges
            # and miss real cycles). Large tie groups route through a
            # synthetic per-boundary hub node — O(|g1|+|g2|) edges with
            # identical cycle semantics (hubs never order group members
            # against each other) instead of O(|g1|*|g2|).
            groups: list = []
            for v, i in sorted(pairs):
                if groups and groups[-1][0] == v:
                    groups[-1][1].append(i)
                else:
                    groups.append((v, [i]))
            for (v1, g1), (v2, g2) in zip(groups, groups[1:]):
                label = {"key": k, "value": v1, "value'": v2}
                if len(g1) * len(g2) <= len(g1) + len(g2):
                    for i1 in g1:
                        for i2 in g2:
                            g.add_edge(i1, i2, WW, label)
                else:
                    for i1 in g1:
                        g.add_edge(i1, hub, WW, label)
                    for i2 in g2:
                        g.add_edge(hub, i2, WW, label)
                    hub -= 1
        cyc = g.find_cycle(types={WW})
        if cyc is None:
            return {"valid?": True, "op-count": len(oks),
                    "key-count": len(by_key)}
        # Report over real ops only: a hub hop a -> h -> b carries the
        # same label on both edges, so keep hub-exit steps and rewrite
        # their "from" to the preceding real node.
        raw = g.explain_cycle(cyc)
        steps = []
        prev_real = next(n for n in reversed(cyc[:-1]) if n >= 0)
        for s in raw:
            if s["to"] < 0:      # entering a hub: remember the source
                prev_real = s["from"]
                continue
            if s["from"] < 0:    # leaving a hub: attribute to source
                s = {**s, "from": prev_real}
            steps.append(s)
            prev_real = s["to"]
        real_cycle = [n for n in cyc if n >= 0]
        if real_cycle and real_cycle[0] != real_cycle[-1]:
            # keep the closed [a, ..., a] shape every cycle result in
            # the codebase uses (a hub at the cut point drops it)
            real_cycle.append(real_cycle[0])
        lines = []
        for s in steps:
            det = s["detail"] or {}
            v2 = det.get("value'")
            lines.append(
                f"T{s['from']} observed key {det.get('key')!r} at "
                f"{det.get('value')!r} before T{s['to']} observed it "
                f"at {v2!r}")
        return {"valid?": False, "cycle": real_cycle, "steps": steps,
                "explanation": "; ".join(lines)}


def checker() -> jchecker.Checker:
    return MonotonicChecker()


def _inc(test, ctx):
    k = gen.RNG.randrange(test.get("monotonic_keys", DEFAULT_KEYS))
    return {"f": "inc", "value": {k: None}}


def _read(test, ctx):
    n = test.get("monotonic_keys", DEFAULT_KEYS)
    ks = gen.RNG.sample(range(n), min(GROUP, n))
    return {"f": "read", "value": {k: None for k in ks}}


def generator():
    """Increments mixed with group reads (monotonic.clj:92-103)."""
    return gen.mix([_inc, _inc, _read])


def workload(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    return {"checker": checker(), "generator": generator(),
            "monotonic_keys": opts.get("keys", DEFAULT_KEYS)}
