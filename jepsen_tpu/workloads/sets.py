"""Set workload: unique adds, then read it all back.

The reference has no jepsen.tests.set namespace — every suite wires
its own add-stream against `checker/set` or `checker/set-full`
(e.g. the tutorial set test `doc/tutorial/08-set.md`, zookeeper-style
suites, and checker.clj:240-291/294-592). This bundles that common
shape: a stream of unique integer adds, a final read phase, and both
set checkers composed.
"""

from __future__ import annotations

import itertools
from typing import Optional

from .. import checker as jchecker
from .. import generator as gen


def adds():
    """add 0, add 1, add 2, ... (one-shot per value)."""
    counter = itertools.count()

    def add(test, ctx):
        return {"f": "add", "value": next(counter)}
    return add


def final_read(test, ctx):
    return {"f": "read", "value": None}


def workload(opts: Optional[dict] = None) -> dict:
    """Adds for time_limit seconds, then a read on every client
    (tutorial 08: add-until-timeout then read)."""
    opts = opts or {}
    return {
        "checker": jchecker.compose({
            "set": jchecker.set_checker(),
            "set-full": jchecker.set_full(
                linearizable=opts.get("linearizable", False)),
        }),
        "generator": gen.phases(
            gen.time_limit(opts.get("time_limit", 60),
                           gen.clients(adds())),
            gen.clients(gen.each_thread(gen.once(final_read)))),
    }
