"""Adya G2 / G1c anomaly workloads.

Capability parity with jepsen.tests.adya
(`jepsen/src/jepsen/tests/adya.clj:12-87`): for each key, exactly two
concurrent insert txns run — one holding an a-row id, the other a
b-row id ([key [a_id, b_id]] with one id None). Each client must read
both tables by predicate and insert only if both are empty; under
anti-dependency-cycle protection (serializability) at most one insert
per key can succeed. More than one ok insert for a key is a G2
(predicate-based anti-dependency cycle) violation."""

from __future__ import annotations

import itertools
import threading

from .. import generator as gen
from .. import independent
from ..checker import Checker


class _Ids:
    """Globally unique id source shared across key generators."""

    def __init__(self):
        self.lock = threading.Lock()
        self.n = 0

    def next(self) -> int:
        with self.lock:
            self.n += 1
            return self.n


def g2_gen():
    """Pairs of insert ops per key, ids globally unique
    (adya.clj:12-57)."""
    ids = _Ids()

    def fgen(k):
        return [
            gen.once(lambda test, ctx:
                     {"f": "insert", "value": [None, ids.next()]}),
            gen.once(lambda test, ctx:
                     {"f": "insert", "value": [ids.next(), None]}),
        ]
    return independent.concurrent_generator(2, itertools.count(), fgen)


class G2Checker(Checker):
    """At most one ok insert per key (adya.clj:59-87). History values
    are [k v] tuples (independent layer)."""

    def check(self, test, history, opts=None):
        counts: dict = {}
        from ..independent import KV
        for op in history:
            if op.f != "insert":
                continue
            v = op.value
            if isinstance(v, KV):
                k = v.k
            elif isinstance(v, (list, tuple)) and v:
                k = v[0]
            else:
                continue
            if op.is_ok:
                counts[k] = counts.get(k, 0) + 1
            else:
                counts.setdefault(k, 0)
        inserted = sum(1 for c in counts.values() if c > 0)
        illegal = {k: c for k, c in sorted(counts.items(),
                                           key=lambda kv: str(kv[0]))
                   if c > 1}
        return {"valid?": not illegal,
                "key-count": len(counts),
                "legal-count": inserted - len(illegal),
                "illegal-count": len(illegal),
                "illegal": illegal}


def g2_checker() -> Checker:
    return G2Checker()


def workload() -> dict:
    return {"checker": g2_checker(), "generator": g2_gen()}
