"""Long-fork anomaly workload (parallel snapshot isolation).

Capability parity with jepsen.tests.long-fork
(`jepsen/src/jepsen/tests/long_fork.clj:1-332`): write txns insert a
single unique key; read txns read that key's whole group of n keys.
Serializability requires a total order over reads of a group —
mutually incomparable reads (one sees x-not-y, another y-not-x) form a
long fork. The checker compares every read pair per group; multiple
writes to one key make the history uncheckable ("unknown").

Micro-ops use the txn algebra ([f k v] lists, `jepsen_tpu.txn`)."""

from __future__ import annotations

from typing import Optional

from .. import generator as gen
from .. import txn as txn_mod
from ..checker import UNKNOWN, Checker


def group_for(n: int, k: int) -> range:
    """The n-key group containing k (long_fork.clj:97-104)."""
    lower = k - (k % n)
    return range(lower, lower + n)


def read_txn_for(n: int, k: int) -> list:
    """A read txn over k's group, shuffled (long_fork.clj:106-112)."""
    ks = list(group_for(n, k))
    gen.RNG.shuffle(ks)
    return [[txn_mod.R, kk, None] for kk in ks]


class Generator(gen.Generator):
    """Single writes of fresh keys, then a group read from the same
    worker; plus random reads of other in-flight groups
    (long_fork.clj:114-154)."""

    def __init__(self, n: int, next_key: int = 0,
                 workers: Optional[dict] = None):
        self.n = n
        self.next_key = next_key
        self.workers = workers or {}  # thread -> last written key

    def op(self, test, ctx):
        process = ctx.some_free_process()
        if process is None:
            return (gen.PENDING, self)
        worker = ctx.process_to_thread(process)
        k = self.workers.get(worker)
        if k is not None:
            # we wrote a key; read its group and clear
            op = gen.fill_in_op(
                {"process": process, "f": "read",
                 "value": read_txn_for(self.n, k)}, ctx)
            return (op, Generator(self.n, self.next_key,
                                  {**self.workers, worker: None}))
        active = [v for v in self.workers.values() if v is not None]
        if active and gen.RNG.random() < 0.5:
            op = gen.fill_in_op(
                {"process": process, "f": "read",
                 "value": read_txn_for(self.n, gen.RNG.choice(active))},
                ctx)
            return (op, self)
        op = gen.fill_in_op(
            {"process": process, "f": "write",
             "value": [[txn_mod.W, self.next_key, 1]]}, ctx)
        return (op, Generator(self.n, self.next_key + 1,
                              {**self.workers, worker: self.next_key}))

    def update(self, test, ctx, event):
        return self


def generator(n: int) -> Generator:
    return Generator(n)


class IllegalHistory(Exception):
    def __init__(self, msg, **info):
        super().__init__(msg)
        self.info = {"msg": msg, **info}


def read_compare(a: dict, b: dict):
    """-1 if a dominates, 0 if equal, 1 if b dominates, None if
    incomparable (long_fork.clj:156-196). Values move away from None
    exactly once; distinct non-None values for one key are illegal."""
    if set(a) != set(b):
        raise IllegalHistory(
            "These reads did not query for the same keys, and therefore "
            "cannot be compared.", reads=[a, b])
    res = 0
    for k in a:
        va, vb = a[k], b[k]
        if va == vb:
            continue
        if vb is None:      # a bigger here
            if res > 0:
                return None
            res = -1
        elif va is None:    # b bigger here
            if res < 0:
                return None
            res = 1
        else:
            raise IllegalHistory(
                "These two read states contain distinct values for the "
                "same key; this checker assumes only one write occurs "
                "per key.", key=k, reads=[a, b])
    return res


def read_op_value_map(op) -> dict:
    return {m[1]: m[2] for m in (op.value or [])}


def find_forks(ops) -> list:
    """Mutually incomparable read pairs (long_fork.clj:208-217)."""
    forks = []
    for i in range(len(ops)):
        for j in range(i + 1, len(ops)):
            if read_compare(read_op_value_map(ops[i]),
                            read_op_value_map(ops[j])) is None:
                forks.append([ops[i], ops[j]])
    return forks


def is_read_txn(txn) -> bool:
    return all(txn_mod.is_read(m) for m in txn)


def is_write_txn(txn) -> bool:
    return len(txn) == 1 and txn_mod.is_write(txn[0])


def op_read_keys(op) -> frozenset:
    return frozenset(m[1] for m in (op.value or []))


def groups(n: int, read_ops) -> list:
    """Partition reads by key-group; wrong-width groups are illegal
    (long_fork.clj:225-239)."""
    by_group: dict = {}
    for op in read_ops:
        by_group.setdefault(op_read_keys(op), []).append(op)
    out = []
    for ks, ops in by_group.items():
        if len(ks) != n:
            raise IllegalHistory(
                f"Every read in this history should have observed "
                f"exactly {n} keys, but this read observed {len(ks)} "
                f"instead: {sorted(ks)}", op=ops[0])
        out.append(ops)
    return out


class LongForkChecker(Checker):
    """No key written twice; no mutually incomparable group reads
    (long_fork.clj:241-311)."""

    def __init__(self, n: int):
        self.n = n

    def check(self, test, history, opts=None):
        reads = [op for op in history
                 if op.is_ok and is_read_txn(op.value or [])]
        early = [r for r in reads
                 if not any(m[2] is not None for m in r.value)]
        late = [r for r in reads
                if all(m[2] is not None for m in r.value)]
        out = {"reads-count": len(reads),
               "early-read-count": len(early),
               "late-read-count": len(late)}
        # multiple writes to one key -> unknown (long_fork.clj:258-274)
        written: set = set()
        for op in history:
            if op.is_invoke and is_write_txn(op.value or []):
                k = op.value[0][1]
                if k in written:
                    return {**out, "valid?": UNKNOWN,
                            "error": ["multiple-writes", k]}
                written.add(k)
        try:
            forks = []
            for grp in groups(self.n, reads):
                forks.extend(find_forks(grp))
        except IllegalHistory as e:
            return {**out, "valid?": UNKNOWN, "error": e.info}
        if forks:
            return {**out, "valid?": False,
                    "forks": [[a.to_dict(), b.to_dict()]
                              for a, b in forks]}
        return {**out, "valid?": True}


def checker(n: int) -> Checker:
    return LongForkChecker(n)


def workload(n: int = 2) -> dict:
    """Checker + generator bundle; n = group size
    (long_fork.clj:313-332). The generator is client-scoped: unwrapped,
    some_free_process could hand a write txn to the nemesis."""
    return {"checker": checker(n),
            "generator": gen.clients(generator(n))}
