"""List-append workload package: generator + checker over transactions
of appends/reads on named lists (parity with
`jepsen/src/jepsen/tests/cycle/append.clj:11-55`; the checking engine
is `jepsen_tpu.elle.append`).

Clients must understand invocations like

    {"f": "txn", "value": [["r", 3, None], ["append", 3, 2], ["r", 3, None]]}

and complete them with reads filled in:

    {"f": "txn", "value": [["r", 3, [1]], ["append", 3, 2], ["r", 3, [1, 2]]]}
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Optional

from .. import store
from ..checker import Checker
from ..elle import append as elle_append


class AppendChecker(Checker):
    """Full checker for append/read histories; writes anomaly
    explanations under <store>/elle/ like the reference does
    (append.clj:17-22)."""

    def __init__(self, anomalies: Iterable[str] = ("G1", "G2"),
                 additional_graphs: Iterable[str] = ()):
        self.anomalies = _expand(anomalies)
        self.additional_graphs = tuple(additional_graphs)

    def check(self, test, history, opts=None):
        res = elle_append.check(history, anomalies=self.anomalies,
                                additional_graphs=self.additional_graphs)
        _dump_anomalies(test, opts, res)
        return res


def _expand(anomalies) -> tuple:
    """:G1 means G1a+G1b+G1c; :G2 implies G-single (wr.clj:44-46);
    always include the cheap structural checks."""
    out = {"internal", "dirty-update", "duplicate-elements",
           "incompatible-order"}
    for a in anomalies:
        if a == "G1":
            out |= {"G1a", "G1b", "G1c", "G0"}
        elif a == "G2":
            out |= {"G2", "G-single"}
        else:
            out.add(a)
    return tuple(sorted(out))


def _dump_anomalies(test, opts, res):
    """Write the browsable per-anomaly file tree the reference's elle
    integration produces (`jepsen/src/jepsen/tests/cycle.clj:9-16`
    passes `:directory`; elle writes one file per anomaly type): for
    each anomaly, `elle/<name>.json` (machine-readable cases) and
    `elle/<name>.txt` (one human-readable block per case — cycle,
    step-by-step explanation). Browsable next to linear.svg in the
    web UI's store browser."""
    if res.get("valid?") is True or not test or not test.get("store_root"):
        return
    try:
        comps = [c for c in ((opts or {}).get("subdirectory"), "elle")
                 if c is not None]
        d = store.path(test, *comps)
        os.makedirs(d, exist_ok=True)
        for name, cases in (res.get("anomalies") or {}).items():
            with open(os.path.join(d, f"{name}.json"), "w") as fh:
                json.dump(cases, fh, indent=2, default=repr)
            with open(os.path.join(d, f"{name}.txt"), "w") as fh:
                fh.write(f"{name} — {len(cases)} case(s)\n")
                fh.write("=" * 60 + "\n\n")
                for i, case in enumerate(cases):
                    fh.write(f"case {i}\n")
                    if isinstance(case, dict):
                        if case.get("cycle") is not None:
                            fh.write("  cycle: "
                                     + " -> ".join(
                                         f"T{t}" for t in case["cycle"])
                                     + "\n")
                        for s in case.get("steps") or []:
                            fh.write(f"  step: T{s.get('from')} "
                                     f"-{s.get('type')}-> "
                                     f"T{s.get('to')}\n")
                        if case.get("explanation"):
                            fh.write("  why:  "
                                     + str(case["explanation"]) + "\n")
                        for k, v in case.items():
                            if k not in ("cycle", "steps",
                                         "explanation"):
                                fh.write(f"  {k}: {v!r}\n")
                    else:
                        fh.write(f"  {case!r}\n")
                    fh.write("\n")
    except Exception:  # noqa: BLE001 — diagnostics must not mask results
        pass


def checker(anomalies: Iterable[str] = ("G1", "G2"),
            additional_graphs: Iterable[str] = ()) -> Checker:
    return AppendChecker(anomalies, additional_graphs)


def gen(key_count: int = 3, min_txn_length: int = 1,
        max_txn_length: int = 4, max_writes_per_key: int = 32,
        seed: Optional[int] = None):
    """The list-append txn generator (append.clj:28-31)."""
    return elle_append.AppendGen(
        key_count=key_count, min_txn_length=min_txn_length,
        max_txn_length=max_txn_length,
        max_writes_per_key=max_writes_per_key, seed=seed)


def workload(key_count: int = 3, min_txn_length: int = 1,
             max_txn_length: int = 4, max_writes_per_key: int = 32,
             anomalies: Iterable[str] = ("G1", "G2"),
             additional_graphs: Iterable[str] = (),
             seed: Optional[int] = None) -> dict:
    """A partial test: generator + checker (append.clj:33-55)."""
    return {"generator": gen(key_count, min_txn_length, max_txn_length,
                             max_writes_per_key, seed),
            "checker": checker(anomalies, additional_graphs)}
