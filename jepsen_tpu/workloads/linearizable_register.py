"""Linearizable-register workload over independent keys.

Capability parity with jepsen.tests.linearizable-register
(`jepsen/src/jepsen/tests/linearizable_register.clj:18-53`): clients
understand write / read / cas over [k v] tuple values; the workload
bundles a concurrent multi-key generator (2n threads per key, n of
them reserved for reads), randomized per-key op limits (so key
boundaries drift out of alignment), a process limit, and an
independent checker composing linearizability with a per-key timeline.

The linearizability algorithm defaults to the TPU competition path —
this workload is the BASELINE flagship config (100 keys x 2k ops)
generator.
"""

from __future__ import annotations

import itertools
from typing import Optional

from .. import checker as jchecker
from .. import generator as gen
from .. import independent, models
from ..checker import timeline


def w(test, ctx):
    return {"f": "write", "value": gen.RNG.randrange(5)}


def r(test, ctx):
    return {"f": "read", "value": None}


def cas(test, ctx):
    return {"f": "cas", "value": [gen.RNG.randrange(5),
                                  gen.RNG.randrange(5)]}


def workload(opts: dict) -> dict:
    """{"generator", "checker"} bundle. Options:

    nodes          list of nodes (only the count matters: 2n threads
                   serve each key, n of them reading)
    concurrency    total worker threads available; when fewer than 2n,
                   the per-key group shrinks to fit (the reference
                   would assert instead — independent.clj:118-125 —
                   which makes default "1n" CLI runs explode)
    model          model to check (default cas_register)
    algorithm      linearizable algorithm (default "competition")
    per_key_limit  max ops per key (randomized x0.9-1.0 per key)
    process_limit  max processes per key (default 20)
    """
    n = len(opts.get("nodes") or [])
    assert n > 0, "need at least one node"
    model = opts.get("model") or models.cas_register()
    per_key_limit = opts.get("per_key_limit")
    process_limit = opts.get("process_limit", 20)
    group = 2 * n
    if opts.get("concurrency"):
        group = max(1, min(group, int(opts["concurrency"])))
    readers = group // 2

    def fgen(k):
        if readers:
            g = gen.reserve(readers, r, gen.mix([w, cas, cas]))
        else:
            # a single-thread group still needs reads to witness state
            g = gen.mix([r, w, cas, cas])
        if per_key_limit:
            g = gen.limit(int((0.9 + gen.RNG.random() * 0.1)
                              * per_key_limit), g)
        return gen.process_limit(process_limit, g)

    return {
        "checker": independent.checker(jchecker.compose({
            "linear": jchecker.linearizable(
                model, algorithm=opts.get("algorithm", "competition")),
            "timeline": timeline.html(),
        })),
        "generator": independent.concurrent_generator(
            group, itertools.count(), fgen),
    }
