"""Sequential-consistency workload (tidb/src/tidb/sequential.clj:1-140,
also shipped by the cockroachdb suite).

A writer inserts a key's subkeys k_0 .. k_{n-1} in order, each in its
own transaction; a reader reads them in REVERSE order (k_{n-1} first).
Process order guarantees k_0 is visible before k_1, so a read vector
may be all-present, a prefix of nils followed by values (the writer
was mid-flight), or all-nil — but a nil AFTER a non-nil element
("trailing nil": we saw k_1 but not k_0) violates sequential
consistency.

The client contract: ops are
    {"f": "write", "value": k}          insert each subkey in order
    {"f": "read",  "value": [k, vs]}    read subkeys reversed; vs is
                                        the observed list (None for
                                        missing)
"""

from __future__ import annotations

from collections import deque
from typing import Optional

from .. import checker as jchecker
from .. import generator as gen

DEFAULT_KEY_COUNT = 5


def subkeys(key_count: int, k) -> list:
    """The subkeys for key k, in write order (sequential.clj:44-47)."""
    return [f"{k}_{i}" for i in range(key_count)]


def trailing_nil(coll) -> bool:
    """A nil anywhere after a non-nil element (sequential.clj:90-93)."""
    it = iter(coll)
    for x in it:
        if x is not None:
            break
    return any(x is None for x in it)


class SequentialChecker(jchecker.Checker):
    """Classify read vectors: all / some / none / bad
    (sequential.clj:95-117)."""

    def check(self, test, history, opts=None):
        key_count = test.get("key_count") or DEFAULT_KEY_COUNT
        reads = [op.value for op in history
                 if op.is_ok and op.f == "read"
                 and isinstance(op.value, (list, tuple))
                 and len(op.value) == 2]
        none = [r for r in reads if all(v is None for v in r[1])]
        some = [r for r in reads if any(v is None for v in r[1])]
        bad = [r for r in reads if trailing_nil(r[1])]
        all_ = [r for r in reads
                if list(r[1]) == subkeys(key_count, r[0])[::-1]]
        return {"valid?": not bad,
                "all-count": len(all_), "some-count": len(some),
                "none-count": len(none), "bad-count": len(bad),
                "bad": bad[:10]}


def checker() -> jchecker.Checker:
    return SequentialChecker()


class _Writes:
    """Sequential integer keys, logging the most recent into the shared
    ring (sequential.clj:119-128)."""

    def __init__(self, last_written: deque):
        self.k = -1
        self.last_written = last_written

    def __call__(self, test, ctx):
        self.k += 1
        self.last_written.append(self.k)
        return {"f": "write", "value": self.k}


class _Reads:
    """Read a randomly selected recently-written key
    (sequential.clj:130-136)."""

    def __init__(self, last_written: deque):
        self.last_written = last_written

    def __call__(self, test, ctx):
        if not self.last_written:
            return {"f": "read", "value": [0, []]}
        k = gen.RNG.choice(list(self.last_written))
        return {"f": "read", "value": [k, []]}


def generator(n_writers: int = 2):
    """n writer threads + readers over a 2n-deep recency buffer
    (sequential.clj:138-145)."""
    last_written: deque = deque(maxlen=2 * n_writers)
    return gen.reserve(n_writers, _Writes(last_written),
                       _Reads(last_written))


def workload(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    return {"checker": checker(),
            "generator": generator(opts.get("n_writers", 2)),
            "key_count": opts.get("key_count", DEFAULT_KEY_COUNT)}
