"""Causal-consistency workload: a causal order of ops on one register.

Capability parity with jepsen.tests.causal
(`jepsen/src/jepsen/tests/causal.clj:12-131`): a CausalRegister model
steps through write/read/read-init ops, each carrying a `position` and
a `link` to the previously-seen position; unlinked or out-of-order
ops are inconsistent. The workload issues the canonical 5-op causal
order (read-init, write 1, read, write 2, read) per key, one thread
group per key, under a partitioning nemesis.

The local Model protocol here is deliberately the checker-model one
(jepsen_tpu.models.Model) — the reference re-defines its own identical
protocol locally (causal.clj:12-26); this build reuses the shared one.
"""

from __future__ import annotations

import itertools
from typing import Optional

from .. import generator as gen
from .. import independent
from ..checker import Checker
from ..models import Inconsistent, Model


class CausalRegister(Model):
    """causal.clj:33-83."""

    def __init__(self, value=0, counter=0, last_pos=None):
        self.value = value
        self.counter = counter
        self.last_pos = last_pos

    def step(self, op):
        c = self.counter + 1
        v = op.value
        pos = op.extra.get("position")
        link = op.extra.get("link")
        if link != "init" and link != self.last_pos:
            return Inconsistent(
                f"Cannot link {link!r} to last-seen position "
                f"{self.last_pos!r}")
        if op.f == "write":
            if v == c:
                return CausalRegister(v, c, pos)
            return Inconsistent(
                f"expected value {c} attempting to write {v} instead")
        if op.f == "read-init":
            if self.counter == 0 and v not in (None, 0):
                return Inconsistent(f"expected init value 0, read {v}")
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return Inconsistent(
                f"can't read {v} from register {self.value}")
        if op.f == "read":
            if v is None or v == self.value:
                return CausalRegister(self.value, self.counter, pos)
            return Inconsistent(
                f"can't read {v} from register {self.value}")
        return Inconsistent(f"unknown op {op.f!r}")

    def __repr__(self):
        return f"CausalRegister({self.value!r})"

    def __eq__(self, other):
        return (isinstance(other, CausalRegister)
                and (self.value, self.counter, self.last_pos)
                == (other.value, other.counter, other.last_pos))

    def __hash__(self):
        return hash((self.value, self.counter, self.last_pos))


def causal_register() -> CausalRegister:
    return CausalRegister()


class CausalChecker(Checker):
    """Step the model through every ok op in issue order
    (causal.clj:88-110)."""

    def __init__(self, model: Model):
        self.model = model

    def check(self, test, history, opts=None):
        s = self.model
        for op in history:
            if not op.is_ok:
                continue
            s = s.step(op)
            if isinstance(s, Inconsistent):
                return {"valid?": False, "error": s.msg}
        return {"valid?": True, "model": s}


def check(model: Optional[Model] = None) -> Checker:
    return CausalChecker(model or causal_register())


def r(test, ctx):
    return {"f": "read", "value": None}


def ri(test, ctx):
    return {"f": "read-init", "value": None}


def cw1(test, ctx):
    return {"f": "write", "value": 1}


def cw2(test, ctx):
    return {"f": "write", "value": 2}


def workload(opts: dict) -> dict:
    """The canonical causal order (ri w1 r w2 r) per key, one thread
    per key, staggered, under a start/stop nemesis cycle
    (causal.clj:113-131)."""
    return {
        "checker": independent.checker(check(causal_register())),
        "generator": gen.time_limit(
            opts.get("time_limit", 60),
            gen.nemesis(
                gen.cycle([gen.sleep(10),
                           {"type": "info", "f": "start"},
                           gen.sleep(10),
                           {"type": "info", "f": "stop"}]),
                gen.stagger(1, independent.concurrent_generator(
                    1, itertools.count(),
                    # each step one-shot: bare fns would repeat forever
                    # and the sequence would never advance past ri
                    lambda k: [gen.once(ri), gen.once(cw1), gen.once(r),
                               gen.once(cw2), gen.once(r)]))),
        ),
    }
