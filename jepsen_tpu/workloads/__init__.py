"""Reusable workload packages: {generator, checker, (client
requirements)} bundles that DB test suites wire together.

Mirrors the reference's `jepsen.tests.*` namespaces (renamed to
`workloads` because `tests` collides with pytest conventions):

    reference namespace                          here
    ------------------------------------------   -----------------------
    jepsen.tests (noop-test, fakes)              jepsen_tpu.fakes
    jepsen.tests.linearizable-register           .linearizable_register
    jepsen.tests.bank                            .bank
    jepsen.tests.long-fork                       .long_fork
    jepsen.tests.causal                          .causal
    jepsen.tests.adya                            .adya
    jepsen.tests.cycle                           .cycle
    jepsen.tests.cycle.append                    .cycle_append
    jepsen.tests.cycle.wr                        .cycle_wr
    tidb.sequential / cockroachdb sequential     .sequential
    tidb.monotonic / faunadb monotonic           .monotonic

Each module exposes a `workload(**opts) -> dict` returning at least
{"generator": ..., "checker": ...}; suites merge that into their test
map and add a client.
"""

from . import (adya, bank, causal, causal_reverse, cycle, cycle_append,
               cycle_wr, linearizable_register, long_fork, monotonic,
               sequential, sets)

__all__ = ["adya", "bank", "causal", "causal_reverse", "cycle",
           "cycle_append", "cycle_wr", "linearizable_register",
           "long_fork", "monotonic", "sequential", "sets"]
