"""Generic cycle-detection checker: wraps any history -> DepGraph
analyzer into a Checker (parity with
`jepsen/src/jepsen/tests/cycle.clj:9-16`, whose engine is elle.core;
ours is `jepsen_tpu.elle.graph`)."""

from __future__ import annotations

from typing import Callable

from ..checker import Checker
from ..elle.graph import DepGraph


class CycleChecker(Checker):
    """Takes analyze_fn(history) -> DepGraph; reports the first cycle
    found over all edges as an anomaly."""

    def __init__(self, analyze_fn: Callable):
        self.analyze_fn = analyze_fn

    def check(self, test, history, opts=None):
        g: DepGraph = self.analyze_fn(history)
        cyc = g.find_cycle()
        if cyc is None:
            return {"valid?": True}
        return {"valid?": False,
                "cycles": [{"cycle": cyc,
                            "steps": g.explain_cycle(cyc)}]}


def checker(analyze_fn: Callable) -> Checker:
    return CycleChecker(analyze_fn)
