"""Strict-serializability reverse-order anomaly detection.

Capability parity with jepsen.tests.causal-reverse
(`jepsen/src/jepsen/tests/causal_reverse.clj:1-114`): writers blind-
insert distinct keys; readers read all keys in a txn. Replaying the
history we track, for each write w, the set of writes acknowledged
before w was invoked; any read observing w but missing one of those
prior writes shows T2 visible without T1 < T2."""

from __future__ import annotations

import itertools

from .. import checker as jchecker
from .. import generator as gen
from .. import independent
from ..checker import Checker


def graph(history) -> dict:
    """{written-value: set of values acked before its invocation}
    (causal_reverse.clj:21-48)."""
    completed: set = set()
    expected: dict = {}
    for op in history:
        if op.f != "write":
            continue
        if op.is_invoke:
            expected[op.value] = set(completed)
        elif op.is_ok:
            completed.add(op.value)
    return expected


def errors(history, expected: dict) -> list:
    """Reads that observe a write but miss one of its predecessors
    (causal_reverse.clj:50-73)."""
    out = []
    for op in history:
        if not (op.is_ok and op.f == "read"):
            continue
        seen = set(op.value or [])
        our_expected: set = set()
        for v in seen:
            our_expected |= expected.get(v, set())
        missing = our_expected - seen
        if missing:
            d = op.to_dict()
            d.pop("value", None)
            d["missing"] = sorted(missing)
            d["expected-count"] = len(our_expected)
            out.append(d)
    return out


class CausalReverseChecker(Checker):
    """causal_reverse.clj:75-84."""

    def check(self, test, history, opts=None):
        errs = errors(history, graph(history))
        return {"valid?": not errs, "errors": errs}


def checker() -> Checker:
    return CausalReverseChecker()


def workload(opts: dict) -> dict:
    """Per-key mixed blind writes (distinct values) and whole-set reads
    (causal_reverse.clj:86-114)."""
    n = len(opts.get("nodes") or []) or 1
    per_key_limit = opts.get("per_key_limit", 500)

    def fgen(k):
        # distinct write values per key; reads repeat (fn generators
        # repeat, map generators are one-shot — the reference passes a
        # bare map here, which emits a single read per key and carries
        # a TODO doubting itself; a repeating read is the intent)
        counter = itertools.count()

        def write_op(test, ctx):
            return {"f": "write", "value": next(counter)}

        def read_op(test, ctx):
            return {"f": "read", "value": None}

        return gen.limit(per_key_limit,
                         gen.stagger(1 / 100,
                                     gen.mix([read_op, write_op])))

    return {
        "checker": jchecker.compose({
            "perf": jchecker.perf(),
            "sequential": independent.checker(checker()),
        }),
        "generator": independent.concurrent_generator(
            n, itertools.count(), fgen),
    }
