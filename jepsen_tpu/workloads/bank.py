"""Bank-transfer workload: total balance must be conserved.

Capability parity with jepsen.tests.bank
(`jepsen/src/jepsen/tests/bank.clj:20-192`): transfer ops move a
random amount between distinct random accounts; read ops return the
full {account: balance} map. The checker validates every ok read —
unexpected accounts, nil balances, totals drifting from total-amount,
and (unless negative_balances is allowed) negative balances — with the
reference's error taxonomy, first/worst/last examples, and badness
ranking. The plotter draws total-balance-over-time per node.

Test map options: "accounts", "total-amount", "max-transfer",
(bank.clj:1-8)."""

from __future__ import annotations

from typing import Optional

from .. import checker as jchecker
from .. import generator as gen
from ..checker import Checker
from ..checker.plots import _plt, _save


def read(test, ctx):
    return {"f": "read", "value": None}


def transfer(test, ctx):
    accounts = test["accounts"]
    return {"f": "transfer",
            "value": {"from": gen.RNG.choice(accounts),
                      "to": gen.RNG.choice(accounts),
                      "amount": 1 + gen.RNG.randrange(
                          test["max-transfer"])}}


diff_transfer = gen.filter_(
    lambda op: op["value"]["from"] != op["value"]["to"], transfer)


def generator():
    """Mixed reads and distinct-account transfers (bank.clj:40-44)."""
    return gen.mix([diff_transfer, read])


def err_badness(test, err: dict):
    """Bigger numbers = more egregious errors (bank.clj:46-54)."""
    t = err["type"]
    if t == "unexpected-key":
        return len(err["unexpected"])
    if t == "nil-balance":
        return len(err["nils"])
    if t == "wrong-total":
        return abs((err["total"] - test["total-amount"])
                   / test["total-amount"])
    if t == "negative-value":
        return -sum(err["negative"])
    return 0


def check_op(accts: set, total, negative_balances: bool, op) -> Optional[dict]:
    """Errors in one read's balance map (bank.clj:56-86)."""
    value = op.value or {}
    ks = list(value.keys())
    balances = list(value.values())
    if not all(k in accts for k in ks):
        return {"type": "unexpected-key",
                "unexpected": [k for k in ks if k not in accts],
                "op": op}
    if any(b is None for b in balances):
        return {"type": "nil-balance",
                "nils": {k: v for k, v in value.items() if v is None},
                "op": op}
    if sum(balances) != total:
        return {"type": "wrong-total", "total": sum(balances), "op": op}
    if not negative_balances and any(b < 0 for b in balances):
        return {"type": "negative-value",
                "negative": [b for b in balances if b < 0],
                "op": op}
    return None


class BankChecker(Checker):
    """All reads sum to total-amount; balances non-negative unless
    allowed (bank.clj:88-121)."""

    def __init__(self, negative_balances: bool = False):
        self.negative_balances = negative_balances

    def check(self, test, history, opts=None):
        accts = set(test["accounts"])
        total = test["total-amount"]
        reads = [op for op in history if op.is_ok and op.f == "read"]
        errors: dict = {}
        for op in reads:
            err = check_op(accts, total, self.negative_balances, op)
            if err is not None:
                errors.setdefault(err["type"], []).append(err)
        first_error = None
        firsts = [v[0] for v in errors.values()]
        if firsts:
            first_error = min(firsts, key=lambda e: e["op"].index)
        out_errors = {}
        for typ, errs in errors.items():
            d = {"count": len(errs),
                 "first": errs[0],
                 "worst": max(errs,
                              key=lambda e: err_badness(test, e)),
                 "last": errs[-1]}
            if typ == "wrong-total":
                d["lowest"] = min(errs, key=lambda e: e["total"])
                d["highest"] = max(errs, key=lambda e: e["total"])
            out_errors[typ] = d
        return {"valid?": not errors,
                "read-count": len(reads),
                "error-count": sum(len(v) for v in errors.values()),
                "first-error": first_error,
                "errors": out_errors}


def checker(negative_balances: bool = False) -> Checker:
    return BankChecker(negative_balances)


class Plotter(Checker):
    """bank.png: total of all accounts over time, one series per node
    (bank.clj:123-176)."""

    def check(self, test, history, opts=None):
        reads = [op for op in history
                 if op.is_ok and op.f == "read"
                 and isinstance(op.process, int) and op.value]
        if not reads:
            return {"valid?": True}
        nodes = test.get("nodes") or []
        # crashed processes get fresh ids offset by concurrency, so map
        # process -> original worker thread first (interpreter assigns
        # node = nodes[thread % len(nodes)])
        conc = test.get("concurrency") or len(nodes) or 1
        by_node: dict = {}
        for op in reads:
            node = nodes[(op.process % conc) % len(nodes)] if nodes \
                else str(op.process)
            by_node.setdefault(node, []).append(
                (op.time / 1e9,
                 sum(v for v in op.value.values() if v is not None)))
        plt = _plt()
        fig, ax = plt.subplots(figsize=(10, 4))
        for node in sorted(by_node):
            xs, ys = zip(*by_node[node])
            ax.scatter(xs, ys, s=10, marker="x", label=str(node))
        ax.set_xlabel("Time (s)")
        ax.set_ylabel("Total of all accounts")
        ax.set_title(f"{test.get('name', '')} bank")
        ax.legend(loc="upper right", fontsize=8)
        _save(fig, test, opts, "bank.png")
        plt.close(fig)
        return {"valid?": True}


def plotter() -> Checker:
    return Plotter()


def workload(opts: Optional[dict] = None) -> dict:
    """Defaults + generator + checker bundle (bank.clj:178-192); merge
    the returned map into the test map (it carries accounts /
    total-amount / max-transfer keys the client and checker read)."""
    opts = opts or {}
    negative = opts.get("negative_balances", False)
    return {
        "max-transfer": 5,
        "total-amount": 100,
        "accounts": list(range(8)),
        "checker": jchecker.compose({"SI": checker(negative),
                                     "plot": plotter()}),
        "generator": generator(),
    }
