"""Kitchen-sink utilities (capability parity with jepsen.util,
jepsen/src/jepsen/util.clj — real-pmap, relative-time clock, retries,
majority math, interval-set rendering)."""

from __future__ import annotations

import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor
from contextlib import contextmanager
from typing import Any, Callable, Iterable, Optional, Sequence


def majority(n: int) -> int:
    """Smallest integer strictly greater than half of n
    (jepsen.util/majority parity: for 5 -> 3, for 0 -> 1)."""
    return n // 2 + 1


def minority(n: int) -> int:
    """Largest number of nodes that is still a minority."""
    return (n - 1) // 2


def minority_third(n: int) -> int:
    """Byzantine-fault threshold: largest f with 3f < n
    (jepsen.util/minority-third parity)."""
    return max(0, (n - 1) // 3)


def polysort_key(x):
    """Sort key tolerant of mixed types — ints first in numeric order,
    everything else by string (jepsen.util/polysort parity)."""
    if isinstance(x, int) and not isinstance(x, bool):
        return (0, x, "")
    return (1, 0, str(x))


def integer_interval_set_str(xs: Iterable) -> str:
    """Render a set of integers as compact interval notation, e.g.
    #{1-3 5 7-9} (jepsen.util/integer-interval-set-str parity). Non-integer
    elements fall back to plain rendering."""
    def key(x):
        if isinstance(x, int) and not isinstance(x, bool):
            return (0, x, "")
        return (1, 0, str(x))

    xs = sorted(xs, key=key)
    parts = []
    i = 0
    while i < len(xs):
        x = xs[i]
        if isinstance(x, int) and not isinstance(x, bool):
            j = i
            while (j + 1 < len(xs) and isinstance(xs[j + 1], int)
                   and xs[j + 1] == xs[j] + 1):
                j += 1
            if j > i:
                parts.append(f"{x}-{xs[j]}")
            else:
                parts.append(str(x))
            i = j + 1
        else:
            parts.append(str(x))
            i += 1
    return "#{" + " ".join(parts) + "}"


def safe_backend() -> Optional[str]:
    """The jax default-backend platform, determined WITHOUT risking a
    hung backend init.

    ``jax.default_backend()`` initializes the default backend on first
    call; when that default is a wedged accelerator runtime (the exact
    failure bench.py probes for in a subprocess), init *hangs* rather
    than raising — so callers on a hot path must never trigger it just
    to ask "am I on an accelerator?". This helper answers from safe
    sources only, in precedence order:

      1. the ``JEPSEN_TPU_PLATFORM`` env pin, if set;
      2. the already-initialized default backend, if any backend has
         been initialized in this process (then ``default_backend()``
         is a dict lookup, not an init);
      3. an explicit ``jax.config.jax_platforms`` /  ``JAX_PLATFORMS``
         pin (init would honor it, so the *name* is known without
         initializing);
      4. otherwise ``None`` — unknown; callers should take their
         conservative path (elle auto-routing defaults to host).
    """
    import os

    pin = os.environ.get("JEPSEN_TPU_PLATFORM")
    if pin:
        return pin.split(",")[0].strip() or None
    try:
        from jax._src import xla_bridge
        # read the post-init module global directly — NEVER
        # jax.default_backend(), which takes the backend-init lock and
        # deadlocks when another thread is mid-init (or hung in it)
        b = getattr(xla_bridge, "_default_backend", None)
        if b is not None:
            return str(b.platform)
    except Exception:  # noqa: BLE001 — private API moved / no jax
        pass
    try:
        import jax
        cfg = jax.config.jax_platforms  # None unless explicitly pinned
        if cfg:
            return str(cfg).split(",")[0].strip() or None
    except Exception:  # noqa: BLE001
        pass
    env = os.environ.get("JAX_PLATFORMS", "")
    if env:
        return env.split(",")[0].strip() or None
    return None


_backend_probe: dict = {"event": None, "lock": threading.Lock()}


def backend_ready(timeout: Optional[float] = None) -> bool:
    """Block until the jax default backend has initialized, up to
    `timeout` seconds (default $JEPSEN_TPU_INIT_TIMEOUT_S or 60).

    Backend init on a wedged accelerator runtime HANGS rather than
    raising, and this environment's site customization pins the
    accelerator platform process-wide — so any code path about to
    make its first device call must bound the wait. The init runs in
    a single shared DAEMON thread (expendable at interpreter exit; a
    hung non-daemon engine thread blocks shutdown forever — observed
    live). Returns True once `jax.devices()` has succeeded; False on
    timeout or init error — callers fall back to host engines.

    Fast path: if a default backend is already up, returns True
    without spawning anything."""
    import os

    try:
        from jax._src import xla_bridge
        if getattr(xla_bridge, "_default_backend", None) is not None:
            return True
    except Exception:  # noqa: BLE001 — private API moved
        pass
    if timeout is None:
        timeout = float(os.environ.get("JEPSEN_TPU_INIT_TIMEOUT_S",
                                       "60"))
    with _backend_probe["lock"]:
        ev = _backend_probe["event"]
        if ev is None:
            ev = threading.Event()
            _backend_probe["event"] = ev

            def probe():
                try:
                    import jax
                    jax.devices()
                    _backend_probe["ok"] = True
                except Exception:  # noqa: BLE001 — init raised: record
                    # the DEFINITIVE failure so later callers return
                    # False immediately instead of re-waiting timeouts
                    _backend_probe["ok"] = False
                finally:
                    ev.set()
            threading.Thread(target=probe, daemon=True,
                             name="jax-init-probe").start()
    return ev.wait(timeout) and bool(_backend_probe.get("ok"))


def backend_failed() -> bool:
    """True once the shared init probe has recorded a DEFINITIVE
    failure (jax.devices() raised). Lets pollers distinguish
    failed-fast from still-initializing instead of spinning out their
    full timeout."""
    return _backend_probe.get("ok") is False


def machine_fingerprint() -> str:
    """A short stable fingerprint of the host's ISA surface: arch +
    a hash of the CPU feature flags. XLA:CPU persists AOT results
    compiled against the COMPILE machine's features — replayed on a
    host missing one (observed live: +prefer-no-gather et al. when
    the repo moved machines) the loader warns of possible SIGILL.
    Scoping the cache by this fingerprint makes foreign entries
    invisible instead of dangerous."""
    import hashlib
    import platform

    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    digest = hashlib.sha256(flags.encode()).hexdigest()[:12]
    return f"{platform.machine()}-{digest}"


def enable_compilation_cache(path: Optional[str] = None
                             ) -> Optional[str]:
    """Point XLA's persistent compilation cache at a stable directory
    so kernel compiles survive process boundaries — the per-config
    compile tax (~2 s/bucket on cpu, 20-40 s on TPU) drops to a
    deserialization (~0.4 s measured on the register bucket).

    Default dir: ($JEPSEN_TPU_CACHE_DIR or ~/.cache/jepsen_tpu/xla)
    + a machine fingerprint segment, so AOT artifacts compiled on one
    host are never loaded on a different one (cross-host loads warn
    of possible SIGILL — see machine_fingerprint). An EXPLICIT `path`
    argument is honored verbatim — a caller shipping a pre-seeded
    cache dir owns that risk knowingly. A provenance.json in the dir
    records who compiled the entries. Opt out with
    JEPSEN_TPU_NO_CACHE=1. Returns the cache dir, or None when
    disabled or jax is unavailable.

    Known cosmetic residue: XLA:CPU AOT entries record the compiler's
    tuning pseudo-features (+prefer-no-gather/+prefer-no-scatter)
    next to real ISA bits, and the loader's host probe never lists
    them — so reloading an entry warns about exactly those two flags
    EVEN ON THE MACHINE THAT WROTE IT (verified: fresh dir, write and
    reload on one host, 32 warnings, only the prefer-no-* flags
    differ). That warning is benign; the fingerprint scoping is what
    prevents the real cross-ISA SIGILL case."""
    import json
    import os
    import platform

    if os.environ.get("JEPSEN_TPU_NO_CACHE"):
        return None
    fingerprint = machine_fingerprint()
    if path is None:
        base = (os.environ.get("JEPSEN_TPU_CACHE_DIR")
                or os.path.expanduser("~/.cache/jepsen_tpu/xla"))
        path = os.path.join(base, fingerprint)
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything: the WGL chunk kernels are small but hot
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
    except Exception:  # noqa: BLE001 — no jax / option renamed
        return None
    try:
        os.makedirs(path, exist_ok=True)
        prov = os.path.join(path, "provenance.json")
        if not os.path.exists(prov):
            with open(prov, "w") as f:
                json.dump({"host": platform.node(),
                           "machine": platform.machine(),
                           "fingerprint": fingerprint,
                           "jax": jax.__version__}, f)
    except OSError:
        pass  # cache still works without provenance
    return path


def real_pmap(f: Callable, coll: Sequence) -> list:
    """Apply f to every element in its own thread; wait for all; raise the
    most interesting exception if any failed (jepsen.util/real-pmap parity,
    util.clj:65-77 — 'interesting' = prefer non-interrupt exceptions)."""
    coll = list(coll)
    if not coll:
        return []
    results: list = [None] * len(coll)
    errors: list = [None] * len(coll)

    def run(i, x):
        try:
            results[i] = f(x)
        except BaseException as e:  # noqa: BLE001 — rethrown below
            errors[i] = e

    threads = [threading.Thread(target=run, args=(i, x), daemon=True)
               for i, x in enumerate(coll)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    errs = [e for e in errors if e is not None]
    if errs:
        # Prefer "interesting" exceptions over interrupts/cancellations.
        boring = (KeyboardInterrupt, SystemExit)
        interesting = [e for e in errs if not isinstance(e, boring)]
        raise (interesting[0] if interesting else errs[0])
    return results


def bounded_pmap(f: Callable, coll: Sequence, max_workers: int = 16) -> list:
    """pmap with a bounded worker pool (jepsen.util/bounded-pmap parity)."""
    coll = list(coll)
    if not coll:
        return []
    with ThreadPoolExecutor(max_workers=min(max_workers, len(coll))) as ex:
        return list(ex.map(f, coll))


# -- relative-time clock (jepsen.util/with-relative-time, util.clj:326-347).
# Process-global, like the reference's dynamic var: all worker threads share
# the test's time origin. --
_global_origin: Optional[int] = None


def linear_time_nanos() -> int:
    return _time.monotonic_ns()


@contextmanager
def with_relative_time():
    """Zero the test clock for the duration of the block."""
    global _global_origin
    prev = _global_origin
    _global_origin = linear_time_nanos()
    try:
        yield
    finally:
        _global_origin = prev


def relative_time_nanos() -> int:
    origin = _global_origin
    if origin is None:
        raise RuntimeError("relative_time_nanos outside with_relative_time")
    return linear_time_nanos() - origin


def sleep_nanos(dt: int) -> None:
    if dt > 0:
        _time.sleep(dt / 1e9)


class TimeoutError_(Exception):
    pass


def timeout(seconds: float, f: Callable, *args, default=TimeoutError_):
    """Run f in a thread with a timeout (jepsen.util/timeout macro parity).
    Returns default on timeout (or raises it if it's an exception class).
    The worker thread is abandoned, not killed — f should be interruptible
    or side-effect-safe."""
    result: list = []
    err: list = []

    def run():
        try:
            result.append(f(*args))
        except BaseException as e:  # noqa: BLE001
            err.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(seconds)
    if t.is_alive():
        if isinstance(default, type) and issubclass(default, BaseException):
            raise default(f"timed out after {seconds}s")
        return default
    if err:
        raise err[0]
    return result[0]


def await_fn(f: Callable, retry_interval: float = 1.0,
             timeout_s: float = 60.0, log_message: Optional[str] = None):
    """Poll f until it returns non-exceptionally (jepsen.util/await-fn
    parity, util.clj:383)."""
    deadline = _time.monotonic() + timeout_s
    last: Optional[BaseException] = None
    while _time.monotonic() < deadline:
        try:
            return f()
        except Exception as e:  # noqa: BLE001
            last = e
            _time.sleep(retry_interval)
    raise TimeoutError_(log_message or f"await_fn timed out after {timeout_s}s") \
        from last


def with_retry(f: Callable, retries: int = 5, backoff: float = 0.1):
    """Call f, retrying up to `retries` times with fixed backoff."""
    for attempt in range(retries + 1):
        try:
            return f()
        except Exception:
            if attempt == retries:
                raise
            _time.sleep(backoff)


def nemesis_intervals(history, fs_start=("start",), fs_stop=("stop",)):
    """Pair up nemesis start/stop events into [start-op stop-op] intervals
    (jepsen.util/nemesis-intervals parity, util.clj:736). The reference
    works over invoke/complete PAIRS: a start's invocation and completion
    are zipped against the closing stop's invocation and completion, so
    both [start-invoke stop-invoke] and [start-complete stop-complete]
    windows are produced — the fault may land anywhere between the start's
    invocation and completion, so the invocation-side window matters.
    Every start still open when a stop arrives is closed by that stop.
    Returns a list of (start_op, stop_op_or_None) over both event kinds."""
    intervals = []
    open_invokes: list = []
    open_completes: list = []
    for op in history:
        if op.process != "nemesis":
            continue
        if op.f in fs_start:
            (open_invokes if op.is_invoke else open_completes).append(op)
        elif op.f in fs_stop:
            if op.is_invoke:
                intervals.extend((s, op) for s in open_invokes)
                open_invokes = []
            else:
                intervals.extend((s, op) for s in open_completes)
                open_completes = []
    intervals.extend((s, None) for s in open_invokes + open_completes)
    return intervals


def rand_exp(rng, mean: float) -> float:
    """Exponentially distributed random value with the given mean."""
    return rng.expovariate(1.0 / mean) if mean > 0 else 0.0


class Multiset:
    """A tiny multiset (the reference leans on org.clojure/multiset for
    total-queue accounting, checker.clj:628-687)."""

    def __init__(self, items: Iterable = ()):
        self.counts: dict = {}
        for x in items:
            self.add(x)

    def add(self, x, n: int = 1):
        self.counts[x] = self.counts.get(x, 0) + n

    def __len__(self):
        return sum(self.counts.values())

    def __contains__(self, x):
        return self.counts.get(x, 0) > 0

    def __iter__(self):
        for x, c in self.counts.items():
            for _ in range(c):
                yield x

    def __eq__(self, other):
        return isinstance(other, Multiset) and self.counts == other.counts

    def __repr__(self):
        return f"Multiset({dict(self.counts)})"

    def intersect(self, other: "Multiset") -> "Multiset":
        m = Multiset()
        for x, c in self.counts.items():
            k = min(c, other.counts.get(x, 0))
            if k > 0:
                m.add(x, k)
        return m

    def minus(self, other: "Multiset") -> "Multiset":
        m = Multiset()
        for x, c in self.counts.items():
            k = c - other.counts.get(x, 0)
            if k > 0:
                m.add(x, k)
        return m

    def to_sorted_list(self):
        try:
            return sorted(self)
        except TypeError:
            return list(self)
