"""Redirect printed output into report files
(jepsen/src/jepsen/report.clj:7-16).

The reference's `report/to` macro rebinds *out* to a file around a
body; the Python shape is a context manager:

    with report.to(os.path.join(run_dir, "set.txt")):
        print(results["set"])
"""

from __future__ import annotations

import contextlib
import io
import os
import sys
from pprint import pprint

__all__ = ["to", "pprint"]


@contextlib.contextmanager
def to(filename: str):
    """Bind stdout to `filename` for the duration of the block,
    creating parent directories; announces the report path on exit
    (report.clj:7-16)."""
    parent = os.path.dirname(filename)
    if parent:
        os.makedirs(parent, exist_ok=True)
    buf = io.StringIO()
    old = sys.stdout
    sys.stdout = buf
    try:
        yield buf
    finally:
        sys.stdout = old
        with open(filename, "w") as fh:
            fh.write(buf.getvalue())
        print("Report written to", filename)
