"""Pure-functional generator DSL: what operations to run, and when.

Capability parity with jepsen.generator
(`jepsen/src/jepsen/generator.clj`). A generator is an immutable value
answering two questions (the `Generator` protocol, generator.clj:382-390):

    op(gen, test, ctx)            -> None                (exhausted)
                                   | (PENDING, gen')     (nothing *yet*)
                                   | (op_dict, gen')     (an operation)
    update(gen, test, ctx, event) -> gen'                (observe an event)

where `ctx` tracks virtual time, the set of free threads, and the
thread→process map (generator.clj:453-464). Because generators are pure
values, the scheduler (generator/interpreter.py) is single-threaded and
deterministic given an RNG seed — the reference moved to this design
because its mutable predecessor "was plagued by race conditions"
(generator.clj:23-31).

Base lifts (generator.clj:545-620): None is exhausted; a dict emits one
op (fields filled from ctx); a callable is invoked for a fresh generator
each op; a list/tuple is a sequence of generators run back to back.

Ops here are plain dicts ({"type","f","value","process","time"}); the
interpreter journals them into `jepsen_tpu.history.Op` records. Special
op types: "sleep" (worker naps), "log" (worker logs), "pending".

Randomness goes through the module RNG so tests can pin it
(`with_seed`, mirroring generator/test.clj:31-48's fixed rand).
"""

from __future__ import annotations

import random as _random
from contextlib import contextmanager
from dataclasses import dataclass, replace
from functools import lru_cache
from typing import Any, Callable, Optional

PENDING = "pending"
NEMESIS = "nemesis"

RNG = _random.Random()


@contextmanager
def with_seed(seed: int):
    """Pin the DSL's randomness (generator/test.clj pins rand-seed 45100)."""
    state = RNG.getstate()
    RNG.seed(seed)
    try:
        yield
    finally:
        RNG.setstate(state)


def secs_to_nanos(s) -> int:
    return int(s * 1_000_000_000)


# ---------------------------------------------------------------------------
# Context
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Context:
    """Scheduler context: virtual time, free threads, thread→process map
    (generator.clj:453-464). Threads are NEMESIS plus ints [0, n)."""

    time: int
    free_threads: frozenset
    workers: dict  # thread -> process

    def sorted_free_threads(self) -> list:
        # deterministic order regardless of PYTHONHASHSEED
        return sorted(self.free_threads, key=str)

    def free_processes(self) -> list:
        return [self.workers[t] for t in self.sorted_free_threads()]

    def some_free_process(self):
        """A *random* free process — uniform choice prevents thread
        starvation (generator.clj:66-77 "Fair sets")."""
        if not self.free_threads:
            return None
        ts = self.sorted_free_threads()
        return self.workers[ts[RNG.randrange(len(ts))]]

    def all_threads(self) -> list:
        return list(self.workers)

    def all_processes(self) -> list:
        return list(self.workers.values())

    def process_to_thread(self, process):
        for t, p in self.workers.items():
            if p == process:
                return t
        return None

    def thread_to_process(self, thread):
        return self.workers.get(thread)

    def next_process(self, thread):
        """Replacement process id for a crashed process on `thread`
        (generator.clj:519-527): old process + count of numeric
        processes. Nemesis never changes."""
        if isinstance(thread, int):
            return (self.workers[thread]
                    + sum(1 for p in self.all_processes()
                          if isinstance(p, int)))
        return thread

    def restrict(self, pred: Callable[[Any], bool]) -> "Context":
        """Context visible to a thread-restricted generator
        (on-threads-context, generator.clj:846-862)."""
        return Context(
            time=self.time,
            free_threads=frozenset(t for t in self.free_threads if pred(t)),
            workers={t: p for t, p in self.workers.items() if pred(t)})

    def busy_thread(self, thread) -> "Context":
        return replace(self,
                       free_threads=self.free_threads - {thread})

    def free_thread(self, thread) -> "Context":
        return replace(self,
                       free_threads=self.free_threads | {thread})


def context(test: dict) -> Context:
    """Initial context for a test (generator.clj:453-464): `concurrency`
    worker threads plus the nemesis."""
    threads = [NEMESIS] + list(range(test.get("concurrency", 1)))
    return Context(time=0,
                   free_threads=frozenset(threads),
                   workers={t: t for t in threads})


def fill_in_op(op: dict, ctx: Context):
    """Fill :time, :process, :type from context; PENDING when no process
    is free (generator.clj:531-543)."""
    p = ctx.some_free_process()
    if p is None:
        return PENDING
    out = dict(op)
    out.setdefault("time", ctx.time)
    out.setdefault("process", p)
    out.setdefault("type", "invoke")
    return out


# ---------------------------------------------------------------------------
# Protocol dispatch over base types (generator.clj:545-620)
# ---------------------------------------------------------------------------

class Generator:
    """Base class for combinator generators."""

    def op(self, test, ctx):
        raise NotImplementedError

    def update(self, test, ctx, event):
        return self


def op(gen, test, ctx):
    """Ask `gen` for an operation: None | (PENDING, gen') | (op, gen')."""
    if gen is None:
        return None
    if isinstance(gen, Generator):
        return gen.op(test, ctx)
    if isinstance(gen, dict):
        o = fill_in_op(gen, ctx)
        return (o, gen if o is PENDING else None)
    if callable(gen):
        x = _call_fn_gen(gen, test, ctx)
        if x is None:
            return None
        res = op([x, gen], test, ctx)
        return res
    if isinstance(gen, (list, tuple)):
        # a sequence of generators, run in order
        i = 0
        gen = list(gen)
        while i < len(gen):
            res = op(gen[i], test, ctx)
            if res is None:
                i += 1
                continue
            o, g2 = res
            rest = gen[i + 1:]
            return (o, [g2] + rest if rest else g2)
        return None
    raise TypeError(f"don't know how to generate ops from {gen!r}")


def update(gen, test, ctx, event):
    """Inform `gen` that an event happened; returns the evolved gen."""
    if gen is None:
        return None
    if isinstance(gen, Generator):
        return gen.update(test, ctx, event)
    if isinstance(gen, dict) or callable(gen):
        return gen
    if isinstance(gen, (list, tuple)):
        gen = list(gen)
        if not gen:
            return None
        return [update(gen[0], test, ctx, event)] + gen[1:]
    raise TypeError(f"don't know how to update {gen!r}")


@lru_cache(maxsize=1024)
def _fn_gen_arity(f) -> int:
    try:
        import inspect
        sig = inspect.signature(f)
        return len([p for p in sig.parameters.values()
                    if p.default is inspect.Parameter.empty
                    and p.kind in (p.POSITIONAL_ONLY,
                                   p.POSITIONAL_OR_KEYWORD)])
    except (TypeError, ValueError):
        return 0


def _call_fn_gen(f, test, ctx):
    """Call a function generator with (test, ctx) if it accepts them,
    else with no args (generator.clj:557-563 checks arity)."""
    return f(test, ctx) if _fn_gen_arity(f) == 2 else f()


# ---------------------------------------------------------------------------
# Validation wrappers
# ---------------------------------------------------------------------------

class InvalidOp(Exception):
    def __init__(self, problems, res, ctx):
        super().__init__(
            "Generator produced an invalid [op, gen'] tuple: "
            + "; ".join(problems) + f"\nresult: {res!r}\ncontext: {ctx!r}")
        self.problems = problems


class Validate(Generator):
    """Checks well-formedness of emitted ops (generator.clj:622-676)."""

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        problems = []
        if not (isinstance(res, tuple) and len(res) == 2):
            problems = ["should return a tuple of two elements"]
        else:
            o = res[0]
            if o is not PENDING:
                if not isinstance(o, dict):
                    problems.append("should be PENDING or a dict")
                else:
                    if o.get("type") not in ("invoke", "info", "sleep", "log"):
                        problems.append(
                            "type should be invoke, info, sleep, or log")
                    if not isinstance(o.get("time"), (int, float)):
                        problems.append("time should be a number")
                    if o.get("process") is None:
                        problems.append("no process")
                    elif o["process"] not in ctx.free_processes():
                        problems.append(
                            f"process {o['process']!r} is not free")
        if problems:
            raise InvalidOp(problems, res, ctx)
        return (res[0], Validate(res[1]))

    def update(self, test, ctx, event):
        return Validate(update(self.gen, test, ctx, event))


def validate(gen):
    return Validate(gen)


class FriendlyExceptions(Generator):
    """Attaches generator + context to exceptions (generator.clj:678-718)."""

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        try:
            res = op(self.gen, test, ctx)
        except Exception as e:
            raise RuntimeError(
                f"Generator threw {type(e).__name__} when asked for an "
                f"operation.\nGenerator: {self.gen!r}\nContext: {ctx!r}"
            ) from e
        if res is None:
            return None
        return (res[0], FriendlyExceptions(res[1]))

    def update(self, test, ctx, event):
        try:
            g = update(self.gen, test, ctx, event)
        except Exception as e:
            raise RuntimeError(
                f"Generator threw {type(e).__name__} when updated with "
                f"{event!r}.\nGenerator: {self.gen!r}\nContext: {ctx!r}"
            ) from e
        return FriendlyExceptions(g)


def friendly_exceptions(gen):
    return FriendlyExceptions(gen)


class Trace(Generator):
    """Logs op/update calls (generator.clj:720-763)."""

    def __init__(self, k, gen, logger=None):
        import logging
        self.k = k
        self.gen = gen
        self.logger = logger or logging.getLogger("jepsen_tpu.generator")

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        self.logger.info("%s op -> %r", self.k,
                         None if res is None else res[0])
        if res is None:
            return None
        return (res[0], Trace(self.k, res[1], self.logger))

    def update(self, test, ctx, event):
        self.logger.info("%s update %r", self.k, event)
        return Trace(self.k, update(self.gen, test, ctx, event), self.logger)


def trace(k, gen):
    return Trace(k, gen)


# ---------------------------------------------------------------------------
# Transformation combinators
# ---------------------------------------------------------------------------

class Map(Generator):
    """Transform ops with f (generator.clj:766-789)."""

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        return (o if o is PENDING else self.f(o), Map(self.f, g2))

    def update(self, test, ctx, event):
        return Map(self.f, update(self.gen, test, ctx, event))


def map_(f, gen):
    return Map(f, gen)


def f_map(fmap: dict, gen):
    """Rewrite op :f values through a mapping — used when composing
    nemeses (generator.clj:790-796)."""
    def transform(o):
        o = dict(o)
        o["f"] = fmap.get(o.get("f"), o.get("f"))
        return o
    return Map(transform, gen)


class Filter(Generator):
    """Pass only ops matching f; PENDING/None bypass
    (generator.clj:798-818)."""

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        gen = self.gen
        while True:
            res = op(gen, test, ctx)
            if res is None:
                return None
            o, g2 = res
            if o is PENDING or self.f(o):
                return (o, Filter(self.f, g2))
            gen = g2

    def update(self, test, ctx, event):
        return Filter(self.f, update(self.gen, test, ctx, event))


def filter_(f, gen):
    return Filter(f, gen)


class IgnoreUpdates(Generator):
    """(generator.clj:820-826)"""

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        return op(self.gen, test, ctx)

    def update(self, test, ctx, event):
        return self


def ignore_updates(gen):
    return IgnoreUpdates(gen)


class OnUpdate(Generator):
    """Custom update handler (generator.clj:828-843)."""

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        return (res[0], OnUpdate(self.f, res[1]))

    def update(self, test, ctx, event):
        return self.f(self, test, ctx, event)


def on_update(f, gen):
    return OnUpdate(f, gen)


class OnThreads(Generator):
    """Restrict a generator to threads satisfying f
    (generator.clj:864-886)."""

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx.restrict(self.f))
        if res is None:
            return None
        return (res[0], OnThreads(self.f, res[1]))

    def update(self, test, ctx, event):
        if self.f(ctx.process_to_thread(event.get("process"))):
            return OnThreads(self.f, update(self.gen, test,
                                            ctx.restrict(self.f), event))
        return self


def on_threads(f, gen):
    return OnThreads(f, gen)


on = on_threads


def soonest_op_map(m1: Optional[dict], m2: Optional[dict]) -> Optional[dict]:
    """Pick whichever candidate op occurs sooner; ties break randomly
    proportional to weight (generator.clj:888-934)."""
    if m1 is None:
        return m2
    if m2 is None:
        return m1
    op1, op2 = m1["op"], m2["op"]
    if op1 is PENDING:
        return m2
    if op2 is PENDING:
        return m1
    t1, t2 = op1.get("time"), op2.get("time")
    if t1 == t2:
        w1 = m1.get("weight", 1)
        w2 = m2.get("weight", 1)
        pick = m1 if RNG.randrange(w1 + w2) < w1 else m2
        return {**pick, "weight": w1 + w2}
    return m1 if t1 < t2 else m2


class Any(Generator):
    """Ops from whichever sub-generator is soonest; updates go to all
    (generator.clj:936-957)."""

    def __init__(self, gens):
        self.gens = list(gens)

    def op(self, test, ctx):
        soonest = None
        for i, g in enumerate(self.gens):
            res = op(g, test, ctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1], "i": i})
        if soonest is None:
            return None
        gens = list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return (soonest["op"], Any(gens))

    def update(self, test, ctx, event):
        return Any([update(g, test, ctx, event) for g in self.gens])


def any_(*gens):
    if not gens:
        return None
    if len(gens) == 1:
        return gens[0]
    return Any(gens)


class EachThread(Generator):
    """An independent copy of the generator per thread
    (generator.clj:959-1006)."""

    def __init__(self, fresh_gen, gens: Optional[dict] = None):
        self.fresh_gen = fresh_gen
        self.gens = gens or {}

    def op(self, test, ctx):
        soonest = None
        for thread in ctx.sorted_free_threads():
            g = self.gens.get(thread, self.fresh_gen)
            tctx = Context(time=ctx.time,
                           free_threads=frozenset([thread]),
                           workers={thread: ctx.workers[thread]})
            res = op(g, test, tctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1], "thread": thread})
        if soonest is not None:
            gens = dict(self.gens)
            gens[soonest["thread"]] = soonest["gen"]
            return (soonest["op"], EachThread(self.fresh_gen, gens))
        if len(ctx.free_threads) != len(ctx.workers):
            return (PENDING, self)  # busy threads may still need ops
        return None  # every thread exhausted

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread(event.get("process"))
        if thread is None:
            return self
        g = self.gens.get(thread, self.fresh_gen)
        tctx = Context(time=ctx.time,
                       free_threads=ctx.free_threads & {thread},
                       workers={thread: ctx.workers.get(thread)})
        gens = dict(self.gens)
        gens[thread] = update(g, test, tctx, event)
        return EachThread(self.fresh_gen, gens)


def each_thread(gen):
    return EachThread(gen)


class Reserve(Generator):
    """Dedicated thread ranges per generator + a default
    (generator.clj:1008-1090)."""

    def __init__(self, ranges, gens):
        self.ranges = [frozenset(r) for r in ranges]  # per-gen thread sets
        self.all_ranges = frozenset().union(*self.ranges) if ranges \
            else frozenset()
        self.gens = list(gens)  # len(ranges) + 1 (default)

    def op(self, test, ctx):
        soonest = None
        for i, threads in enumerate(self.ranges):
            rctx = ctx.restrict(lambda t, s=threads: t in s)
            res = op(self.gens[i], test, rctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1],
                              "weight": len(threads), "i": i})
        dctx = ctx.restrict(lambda t: t not in self.all_ranges)
        res = op(self.gens[-1], test, dctx)
        if res is not None:
            soonest = soonest_op_map(
                soonest, {"op": res[0], "gen": res[1],
                          "weight": len(dctx.workers),
                          "i": len(self.ranges)})
        if soonest is None:
            return None
        gens = list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return (soonest["op"], Reserve(self.ranges, gens))

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread(event.get("process"))
        i = len(self.ranges)
        for j, r in enumerate(self.ranges):
            if thread in r:
                i = j
                break
        gens = list(self.gens)
        gens[i] = update(gens[i], test, ctx, event)
        return Reserve(self.ranges, gens)


def reserve(*args):
    """reserve(5, write_gen, 10, cas_gen, read_gen): thread counts with
    their generators, then a default for the remaining threads."""
    *pairs, default = args
    assert len(pairs) % 2 == 0
    ranges, gens = [], []
    n = 0
    for i in range(0, len(pairs), 2):
        count, g = pairs[i], pairs[i + 1]
        ranges.append(set(range(n, n + count)))
        gens.append(g)
        n += count
    return Reserve(ranges, gens + [default])


def clients(client_gen, nemesis_gen=None):
    """Restrict to client threads; optionally route nemesis ops too
    (generator.clj:1093-1103)."""
    only_clients = on_threads(lambda t: t != NEMESIS, client_gen)
    if nemesis_gen is None:
        return only_clients
    return any_(only_clients, nemesis(nemesis_gen))


def nemesis(nemesis_gen, client_gen=None):
    """Restrict to the nemesis thread (generator.clj:1105-1115)."""
    only_nemesis = on_threads(lambda t: t == NEMESIS, nemesis_gen)
    if client_gen is None:
        return only_nemesis
    return any_(only_nemesis, clients(client_gen))


class Mix(Generator):
    """Uniform random mixture; ignores updates (generator.clj:1117-1154)."""

    def __init__(self, gens, i=None):
        self.gens = list(gens)
        self.i = i  # chosen lazily so construction stays RNG-free

    def op(self, test, ctx):
        gens, i = self.gens, self.i
        if i is None and gens:
            i = RNG.randrange(len(gens))
        while gens:
            res = op(gens[i], test, ctx)
            if res is not None:
                o, g2 = res
                gens = list(gens)
                gens[i] = g2
                return (o, Mix(gens, RNG.randrange(len(gens))))
            gens = gens[:i] + gens[i + 1:]
            if not gens:
                return None
            i = RNG.randrange(len(gens))
        return None

    def update(self, test, ctx, event):
        return self


def mix(gens):
    return Mix(list(gens))


class Limit(Generator):
    """At most `remaining` ops (generator.clj:1156-1170).

    Deviation: the reference decrements on PENDING results too (harmless
    there because callers discard the post-PENDING generator); here
    PENDING never consumes the budget, matching the docstring."""

    def __init__(self, remaining, gen):
        self.remaining = remaining
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining <= 0:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        n = self.remaining if o is PENDING else self.remaining - 1
        return (o, Limit(n, g2))

    def update(self, test, ctx, event):
        return Limit(self.remaining, update(self.gen, test, ctx, event))


def limit(remaining, gen):
    return Limit(remaining, gen)


def once(gen):
    return Limit(1, gen)


def log(msg):
    """One :log op (generator.clj:1177-1181)."""
    return {"type": "log", "value": msg}


class Repeat(Generator):
    """Repeat the (unevolved) generator forever or `remaining` times
    (generator.clj:1183-1211)."""

    def __init__(self, remaining, gen):
        self.remaining = remaining  # -1 = infinite
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining == 0:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, _ = res
        n = self.remaining
        if o is not PENDING and n > 0:
            n -= 1
        return (o, Repeat(n, self.gen))

    def update(self, test, ctx, event):
        return Repeat(self.remaining, update(self.gen, test, ctx, event))


def repeat(arg, gen=None):
    if gen is None:
        return Repeat(-1, arg)
    assert arg >= 0
    return Repeat(arg, gen)


class Cycle(Generator):
    """Reset a finite generator once exhausted (generator.clj:1213-1237)."""

    def __init__(self, remaining, original, gen):
        self.remaining = remaining
        self.original = original
        self.gen = gen

    def op(self, test, ctx):
        remaining, gen = self.remaining, self.gen
        while remaining != 0:
            res = op(gen, test, ctx)
            if res is not None:
                return (res[0], Cycle(remaining, self.original, res[1]))
            remaining = remaining - 1 if remaining > 0 else remaining
            if gen is self.original and res is None:
                # original is itself exhausted: avoid spinning forever
                return None
            gen = self.original
        return None

    def update(self, test, ctx, event):
        return Cycle(self.remaining, self.original,
                     update(self.gen, test, ctx, event))


def cycle(arg, gen=None):
    if gen is None:
        return Cycle(-1, arg, arg)
    return Cycle(arg, gen, gen)


class ProcessLimit(Generator):
    """Ops from at most n distinct processes (generator.clj:1239-1265)."""

    def __init__(self, n, procs, gen):
        self.n = n
        self.procs = frozenset(procs)
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return (o, ProcessLimit(self.n, self.procs, g2))
        procs = self.procs | frozenset(ctx.all_processes())
        if len(procs) > self.n:
            return None
        return (o, ProcessLimit(self.n, procs, g2))

    def update(self, test, ctx, event):
        return ProcessLimit(self.n, self.procs,
                            update(self.gen, test, ctx, event))


def process_limit(n, gen):
    return ProcessLimit(n, set(), gen)


class TimeLimit(Generator):
    """Ops for `limit` nanos after the first op (generator.clj:1267-1291)."""

    def __init__(self, limit_nanos, cutoff, gen):
        self.limit = limit_nanos
        self.cutoff = cutoff
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return (o, TimeLimit(self.limit, self.cutoff, g2))
        cutoff = self.cutoff
        if cutoff is None:
            cutoff = o["time"] + self.limit
        if o["time"] >= cutoff:
            return None
        return (o, TimeLimit(self.limit, cutoff, g2))

    def update(self, test, ctx, event):
        return TimeLimit(self.limit, self.cutoff,
                         update(self.gen, test, ctx, event))


def time_limit(dt_secs, gen):
    return TimeLimit(secs_to_nanos(dt_secs), None, gen)


class Stagger(Generator):
    """Schedule ops at uniformly random intervals averaging dt — a
    *total* rate over all threads (generator.clj:1293-1328)."""

    def __init__(self, dt, next_time, gen):
        self.dt = dt  # 2 * mean interval, nanos
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return (o, self)
        next_time = self.next_time if self.next_time is not None \
            else ctx.time
        if next_time <= o["time"]:
            return (o, Stagger(self.dt, o["time"] + RNG.randrange(
                max(1, self.dt)), g2))
        o = {**o, "time": next_time}
        return (o, Stagger(self.dt, next_time + RNG.randrange(
            max(1, self.dt)), g2))

    def update(self, test, ctx, event):
        return Stagger(self.dt, self.next_time,
                       update(self.gen, test, ctx, event))


def stagger(dt_secs, gen):
    return Stagger(secs_to_nanos(2 * dt_secs), None, gen)


class Delay(Generator):
    """Emit ops exactly dt apart (catching up if behind)
    (generator.clj:1368-1385)."""

    def __init__(self, dt, next_time, gen):
        self.dt = dt
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return (o, Delay(self.dt, self.next_time, g2))
        next_time = self.next_time if self.next_time is not None else o["time"]
        o = {**o, "time": max(o["time"], next_time)}
        return (o, Delay(self.dt, o["time"] + self.dt, g2))

    def update(self, test, ctx, event):
        return Delay(self.dt, self.next_time,
                     update(self.gen, test, ctx, event))


def delay(dt_secs, gen):
    return Delay(secs_to_nanos(dt_secs), None, gen)


def sleep(dt_secs):
    """One :sleep op — the receiving worker naps for dt seconds
    (generator.clj:1397-1402)."""
    return {"type": "sleep", "value": dt_secs}


class Synchronize(Generator):
    """Wait for every worker to be free before starting
    (generator.clj:1404-1423)."""

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        if ctx.free_threads == frozenset(ctx.workers):
            return op(self.gen, test, ctx)
        return (PENDING, self)

    def update(self, test, ctx, event):
        return Synchronize(update(self.gen, test, ctx, event))


def synchronize(gen):
    return Synchronize(gen)


def phases(*gens):
    """Run each generator to completion in turn (generator.clj:1425-1430)."""
    return [synchronize(g) for g in gens]


def then(a, b):
    """b, then (synchronize a). Argument order matches the reference's
    ->> composition (generator.clj:1432-1442)."""
    return [b, synchronize(a)]


class UntilOk(Generator):
    """Yield ops until one completes :ok (generator.clj:1444-1483)."""

    def __init__(self, gen, done=False, active=frozenset()):
        self.gen = gen
        self.done = done
        self.active = frozenset(active)

    def op(self, test, ctx):
        if self.done:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return (o, UntilOk(g2, self.done, self.active))
        return (o, UntilOk(g2, self.done, self.active | {o.get("process")}))

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        p = event.get("process")
        if p in self.active:
            t = event.get("type")
            if t == "ok":
                return UntilOk(g2, True, self.active - {p})
            if t in ("info", "fail"):
                return UntilOk(g2, self.done, self.active - {p})
        return UntilOk(g2, self.done, self.active)


def until_ok(gen):
    return UntilOk(gen)


class FlipFlop(Generator):
    """Alternate between generators; stop when any is exhausted
    (generator.clj:1485-1501)."""

    def __init__(self, gens, i=0):
        self.gens = list(gens)
        self.i = i

    def op(self, test, ctx):
        res = op(self.gens[self.i], test, ctx)
        if res is None:
            return None
        o, g2 = res
        gens = list(self.gens)
        gens[self.i] = g2
        return (o, FlipFlop(gens, (self.i + 1) % len(gens)))

    def update(self, test, ctx, event):
        # DELIBERATE divergence from the reference: its flip-flop
        # ignores updates outright (generator.clj:1485-1501 "Updates
        # are ignored."), so a stateful child (e.g. until-ok) nested
        # inside never sees completions and generates forever. Here
        # every child sees every event — the pure-update contract the
        # rest of this DSL honors.
        return FlipFlop([update(g, test, ctx, event) for g in self.gens],
                        self.i)


def flip_flop(a, b):
    return FlipFlop([a, b])


class CycleTimes(Generator):
    """Rotate between generators on a repeating schedule
    (generator.clj:1503-1581)."""

    def __init__(self, period, t0, intervals, cutoffs, gens):
        self.period = period
        self.t0 = t0
        self.intervals = intervals
        self.cutoffs = cutoffs
        self.gens = list(gens)

    def op(self, test, ctx):
        now = ctx.time
        t0 = self.t0 if self.t0 is not None else now
        in_period = (now - t0) % self.period
        cycle_start = now - in_period
        i = 0
        while i < len(self.cutoffs) and in_period >= self.cutoffs[i]:
            i += 1
        t = cycle_start + sum(self.intervals[:i])
        # The reference loops until a generator's op lands inside its
        # window (t grows one interval per step, so ops scheduled in the
        # future terminate the loop); bound it defensively.
        for _ in range(10_000):
            g = self.gens[i]
            interval = self.intervals[i]
            t_end = t + interval
            res = op(g, test, replace(ctx, time=max(now, t)))
            if res is None:
                return None
            o, g2 = res
            gens = list(self.gens)
            gens[i] = g2
            nxt = CycleTimes(self.period, t0, self.intervals,
                             self.cutoffs, gens)
            if o is PENDING:
                return (PENDING, nxt)
            if o["time"] < t_end:
                return (o, nxt)
            i = (i + 1) % len(self.gens)
            t = t_end
        return (PENDING, self)

    def update(self, test, ctx, event):
        return CycleTimes(self.period, self.t0, self.intervals, self.cutoffs,
                          [update(g, test, ctx, event) for g in self.gens])


def cycle_times(*specs):
    """cycle_times(5, gen_a, 10, gen_b): 5 s of a, 10 s of b, repeat."""
    assert specs and len(specs) % 2 == 0
    intervals = [secs_to_nanos(specs[i]) for i in range(0, len(specs), 2)]
    gens = [specs[i] for i in range(1, len(specs), 2)]
    cutoffs = []
    acc = 0
    for iv in intervals:
        acc += iv
        cutoffs.append(acc)
    return CycleTimes(sum(intervals), None, intervals, cutoffs[:-1], gens)


def concat(*gens):
    """Sequence of generators (generator.clj:776-781)."""
    return list(gens)
