"""Deterministic virtual-time generator simulation (no threads, no wall
clock) — capability parity with jepsen.generator.test
(`jepsen/src/jepsen/generator/test.clj:50-80`): `simulate` runs a
generator against a completion function under a virtual clock, `quick` /
`perfect` / `perfect_info` / `imperfect` model standard executions, and
randomness is pinned to RAND_SEED (test.clj:44-48 pins 45100) so op
sequences are exact values tests can assert on.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Callable, Optional

from . import (NEMESIS, PENDING, Context, secs_to_nanos, with_seed)
from . import context as make_context
from . import op as gen_op
from . import update as gen_update
from . import validate

RAND_SEED = 45100
PERFECT_LATENCY = 10  # nanos (test.clj:118-120)

DEFAULT_TEST: dict = {}


def n_nemesis_context(n: int) -> Context:
    """n worker threads plus the nemesis (test.clj:16-24)."""
    return make_context({"concurrency": n})


def default_context() -> Context:
    return n_nemesis_context(2)


def invocations(history):
    return [o for o in history if o.get("type") == "invoke"]


def simulate(gen, complete_fn: Callable, ctx: Optional[Context] = None,
             test: Optional[dict] = None):
    """Simulate the op series from `gen`, with `complete_fn(ctx, invoke)`
    producing each invocation's completion (test.clj:50-80). Returns the
    full simulated history as a list of op dicts."""
    ctx = ctx or default_context()
    test = test if test is not None else DEFAULT_TEST
    with with_seed(RAND_SEED):
        ops: list = []
        in_flight: list = []  # completions, sorted by time
        gen = validate(gen)
        while True:
            res = gen_op(gen, test, ctx)
            if res is None:
                return ops + [o for o in in_flight
                              if not o.get("_silent")]
            invoke, gen2 = res
            if invoke is not PENDING and (
                    not in_flight
                    or invoke["time"] <= in_flight[0]["time"]):
                # Apply the invocation: clock forward, thread busy.
                thread = ctx.process_to_thread(invoke["process"])
                ctx = replace(ctx, time=max(ctx.time, invoke["time"]),
                              free_threads=ctx.free_threads - {thread})
                gen = gen_update(gen2, test, ctx, invoke)
                if invoke["type"] in ("sleep", "log"):
                    # the worker naps for `value` seconds / logs; these
                    # never enter the history but do consume the thread,
                    # and the worker echoes the op back unchanged
                    # (interpreter.py:117-124, goes_in_history :162)
                    dt = secs_to_nanos(invoke.get("value") or 0) \
                        if invoke["type"] == "sleep" else 0
                    complete = {**invoke,
                                "time": invoke["time"] + dt,
                                "_silent": True}
                else:
                    complete = complete_fn(ctx, invoke)
                    ops.append(invoke)
                in_flight = sorted(in_flight + [complete],
                                   key=lambda o: o["time"])
            else:
                # Complete something before the next invocation; the
                # speculative invoke is discarded and re-asked next loop.
                assert in_flight, "generator pending and nothing in flight"
                done = in_flight[0]
                thread = ctx.process_to_thread(done["process"])
                ctx = replace(ctx, time=max(ctx.time, done["time"]),
                              free_threads=ctx.free_threads | {thread})
                silent = done.pop("_silent", False)
                gen = gen_update(gen, test, ctx, done)
                if silent:
                    # waking from a sleep/log: updates the generator
                    # (the interpreter passes the echoed op to update
                    # too) but never enters the history
                    in_flight = in_flight[1:]
                    continue
                if thread != NEMESIS and done.get("type") == "info":
                    workers = dict(ctx.workers)
                    workers[thread] = ctx.next_process(thread)
                    ctx = replace(ctx, workers=workers)
                ops.append(done)
                in_flight = in_flight[1:]


def quick_ops(gen, ctx=None):
    """Every op completes :ok instantly with zero latency."""
    return simulate(gen, lambda c, inv: {**inv, "type": "ok"}, ctx)


def quick(gen, ctx=None):
    return invocations(quick_ops(gen, ctx))


def perfect_star(gen, ctx=None):
    """Every op completes :ok after PERFECT_LATENCY ns; full history."""
    return simulate(
        gen,
        lambda c, inv: {**inv, "type": "ok",
                        "time": inv["time"] + PERFECT_LATENCY},
        ctx)


def perfect(gen, ctx=None):
    return invocations(perfect_star(gen, ctx))


def perfect_info(gen, ctx=None):
    """Every op crashes :info after PERFECT_LATENCY ns; invocations."""
    return invocations(simulate(
        gen,
        lambda c, inv: {**inv, "type": "info",
                        "time": inv["time"] + PERFECT_LATENCY},
        ctx))


def imperfect(gen, ctx=None):
    """Threads cycle fail -> info -> ok completions (test.clj:160-178);
    full history."""
    state: dict = {}
    nxt = {None: "fail", "fail": "info", "info": "ok", "ok": "fail"}

    def complete(c, inv):
        t = c.process_to_thread(inv["process"])
        state[t] = nxt[state.get(t)]
        return {**inv, "type": state[t],
                "time": inv["time"] + PERFECT_LATENCY}

    return simulate(gen, complete, ctx)
