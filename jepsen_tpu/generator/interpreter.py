"""The scheduler hot loop: evaluates a pure generator against real
clients and a nemesis, journaling a history.

Capability parity with jepsen.generator.interpreter
(`jepsen/src/jepsen/generator/interpreter.clj`): one OS thread per worker
(a worker per client thread plus the nemesis), each fed through a
size-1 mailbox queue; a single-threaded scheduler loop that polls a
shared completion queue, asks the generator for ops, dispatches them,
retimestamps events with the relative-time clock, reassigns crashed
processes, and collects the history (interpreter.clj:181-310).

Workers apply ops via the test's Client (one fresh client per process
unless Reusable — ClientWorker, interpreter.clj:33-67) or Nemesis.
Worker crashes become :info completions with the exception attached
(interpreter.clj:141-160).
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time as _time
import traceback
from dataclasses import replace
from typing import Any, Optional

from .. import client as jclient
from .. import util
from . import (NEMESIS, PENDING, Context)
from . import context as make_context
from . import friendly_exceptions
from . import op as gen_op
from . import update as gen_update
from . import validate as gen_validate

log = logging.getLogger("jepsen_tpu.interpreter")

MAX_PENDING_INTERVAL_S = 0.001  # 1000 µs (interpreter.clj:166-170)


class Worker:
    """Lifecycle protocol for stateful workers (interpreter.clj:19-31).
    All calls on one Worker happen on a single thread."""

    def open(self, test: dict, wid) -> "Worker":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def close(self, test: dict) -> None:
        return None


class ClientWorker(Worker):
    """Wraps a Client; opens a fresh one for each new process unless the
    client is Reusable (interpreter.clj:33-67)."""

    def __init__(self, node: str):
        self.node = node
        self.process = None
        self.client: Optional[jclient.Client] = None

    def invoke(self, test, op):
        if self.process != op.get("process") and not (
                self.client is not None
                and jclient.is_validate_reusable(self.client, test)):
            # New process, new client
            self.close(test)
            try:
                self.client = jclient.validate(test["client"]).open(
                    test, self.node)
                self.process = op.get("process")
            except Exception as e:  # noqa: BLE001
                log.warning("Error opening client: %s", e)
                self.client = None
                return {**op, "type": "fail",
                        "error": ["no-client", str(e)]}
        return self.client.invoke(test, op)

    def close(self, test):
        if self.client is not None:
            self.client.close(test)
            self.client = None


class NemesisWorker(Worker):
    def invoke(self, test, op):
        return test["nemesis"].invoke(test, op)


class ClientNemesisWorker(Worker):
    """Spawns per-id workers: clients for integer ids (round-robin over
    nodes), the nemesis otherwise (interpreter.clj:78-95)."""

    def open(self, test, wid):
        if isinstance(wid, int):
            nodes = test.get("nodes") or [None]
            return ClientWorker(nodes[wid % len(nodes)])
        return NemesisWorker()


def client_nemesis_worker() -> ClientNemesisWorker:
    return ClientNemesisWorker()


def _worker_loop(test, worker: Worker, wid, inbox: _queue.Queue,
                 out: _queue.Queue):
    """Worker thread body (interpreter.clj:99-164)."""
    try:
        while True:
            op = inbox.get()
            t = op.get("type")
            if t == "exit":
                return
            if t == "sleep":
                _time.sleep(op["value"])
                out.put(op)
                continue
            if t == "log":
                log.info("%s", op["value"])
                out.put(op)
                continue
            try:
                out.put(worker.invoke(test, op))
            except Exception as e:  # noqa: BLE001
                log.warning("Process %r crashed: %s", op.get("process"), e)
                out.put({**op, "type": "info",
                         "exception": traceback.format_exc(),
                         "error": f"indeterminate: {e}"})
    finally:
        worker.close(test)


class _WorkerHandle:
    def __init__(self, test, worker_factory, wid, completions):
        self.id = wid
        self.inbox: _queue.Queue = _queue.Queue(maxsize=1)
        worker = worker_factory.open(test, wid)
        # convey the spawning thread's control bindings (remote, ssh
        # config) into the worker, as Clojure's binding conveyance does
        # for the reference's worker futures — the nemesis runs control
        # actions from its worker thread (interpreter.clj:99-116)
        from .. import control
        self.thread = threading.Thread(
            target=control.bound_fn(_worker_loop),
            args=(test, worker, wid, self.inbox, completions),
            name=f"jepsen-worker-{wid}", daemon=True)
        self.thread.start()


def run(test: dict):
    """Evaluate all ops from test["generator"], returning the history as
    a list of op dicts (interpreter.clj:181-310). The caller wraps this
    with the relative-time clock (util.with_relative_time)."""
    from .. import fleet as _fleet
    status = _fleet.get_default()
    ctx = make_context(test)
    completions: _queue.Queue = _queue.Queue()
    factory = client_nemesis_worker()
    workers = {wid: _WorkerHandle(test, factory, wid, completions)
               for wid in ctx.all_threads()}
    gen = gen_validate(friendly_exceptions(test.get("generator")))
    history: list = []
    outstanding = 0
    poll_timeout = 0.0

    def goes_in_history(op):
        return op.get("type") not in ("sleep", "log")

    try:
        while True:
            # Prefer completions: they're latency-sensitive.
            op2 = None
            try:
                op2 = completions.get(block=poll_timeout > 0,
                                      timeout=poll_timeout or None)
            except _queue.Empty:
                op2 = None
            if op2 is not None:
                thread = ctx.process_to_thread(op2.get("process"))
                now = util.relative_time_nanos()
                op2 = {**op2, "time": now}

                ctx = replace(ctx, time=now,
                              free_threads=ctx.free_threads | {thread})
                gen = gen_update(gen, test, ctx, op2)
                if thread != NEMESIS and op2.get("type") == "info":
                    workers_map = dict(ctx.workers)
                    workers_map[thread] = ctx.next_process(thread)
                    ctx = replace(ctx, workers=workers_map)
                if goes_in_history(op2):
                    history.append(op2)
                    if status.enabled:
                        status.op_event(invoked=False)
                outstanding -= 1
                poll_timeout = 0.0
                continue


            now = util.relative_time_nanos()
            ctx = replace(ctx, time=now)
            res = gen_op(gen, test, ctx)
            if res is None:
                if outstanding > 0:
                    poll_timeout = MAX_PENDING_INTERVAL_S
                    continue
                break
            op, gen2 = res
            if op is PENDING:
                # NB: the post-PENDING generator is discarded, exactly as
                # the reference recurs with the pre-op gen
                # (interpreter.clj:264-265)
                poll_timeout = MAX_PENDING_INTERVAL_S
                continue
            if now < op["time"]:
                # Not time yet; wait for either a completion or the
                # op's scheduled time.
                poll_timeout = min((op["time"] - now) / 1e9,
                                   MAX_PENDING_INTERVAL_S)
                continue
            thread = ctx.process_to_thread(op.get("process"))
            workers[thread].inbox.put(op)
            ctx = replace(ctx, time=op["time"],
                          free_threads=ctx.free_threads - {thread})
            gen = gen_update(gen2, test, ctx, op)
            if goes_in_history(op):
                history.append(op)
                if status.enabled:
                    status.op_event(invoked=True)
                    if thread == NEMESIS:
                        status.nemesis_event(
                            op.get("f"),
                            active=_fleet.nemesis_opens_window(
                                op.get("f")))
            outstanding += 1
            poll_timeout = 0.0
    finally:
        for w in workers.values():
            w.inbox.put({"type": "exit"})
        for w in workers.values():
            w.thread.join(timeout=10)
    return history
