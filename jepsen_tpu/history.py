"""Operation histories: the core data structure of the framework.

A history is an ordered sequence of operations. Each operation is either an
*invocation* (a client started something) or a *completion* (it finished
:ok, failed cleanly :fail, or ended in an unknown state :info). Checkers
consume histories and decide whether they are consistent with a model.

This mirrors the reference's op-map shape
(`jepsen/src/jepsen/core.clj:328-353` documents the test map; ops are maps
`{:type :invoke/:ok/:fail/:info, :process, :f, :value, :time, :index}`) and
the knossos history utilities the reference calls (`history/index` at
`jepsen/src/jepsen/core.clj:228`, invoke/complete pairing at
`jepsen/src/jepsen/checker/timeline.clj:38-57`).

Design difference from the reference: histories here are stored
struct-of-arrays from day one — parallel numpy columns for
type/f/process/time/index plus an object sidecar for values — so that the
TPU checkers (`jepsen_tpu.ops`) can encode them into device tensors without
a per-op Python traversal.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

# Op types
INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

_TYPE_CODES = {INVOKE: 0, OK: 1, FAIL: 2, INFO: 3}
_TYPE_NAMES = {v: k for k, v in _TYPE_CODES.items()}


@dataclass
class Op:
    """A single operation event.

    Fields mirror the reference op maps. `value` is arbitrary (often an int,
    a [k v] tuple for independent tests, or a list of micro-ops for
    transactional workloads). `time` is relative nanoseconds since test
    start. `index` is the position in the history (assigned by
    `History.index`).
    """

    type: str  # invoke | ok | fail | info
    f: Any = None  # operation function: :read, :write, :cas, ...
    process: Any = None  # logical process id, or :nemesis
    value: Any = None
    time: int = -1
    index: int = -1
    error: Any = None
    extra: dict = field(default_factory=dict)

    # -- predicates (knossos.op parity: invoke?/ok?/fail?/info?, used e.g.
    #    at jepsen/src/jepsen/checker.clj:157-159) --
    @property
    def is_invoke(self) -> bool:
        return self.type == INVOKE

    @property
    def is_ok(self) -> bool:
        return self.type == OK

    @property
    def is_fail(self) -> bool:
        return self.type == FAIL

    @property
    def is_info(self) -> bool:
        return self.type == INFO

    def with_(self, **kw) -> "Op":
        return replace(self, **kw)

    def to_dict(self) -> dict:
        d = {
            "type": self.type,
            "f": self.f,
            "process": self.process,
            "value": self.value,
            "time": self.time,
            "index": self.index,
        }
        if self.error is not None:
            d["error"] = self.error
        if self.extra:
            d.update(self.extra)
        return d

    @staticmethod
    def from_dict(d: dict) -> "Op":
        known = {"type", "f", "process", "value", "time", "index", "error"}
        return Op(
            type=d["type"],
            f=d.get("f"),
            process=d.get("process"),
            value=d.get("value"),
            time=d.get("time", -1),
            index=d.get("index", -1),
            error=d.get("error"),
            extra={k: v for k, v in d.items() if k not in known},
        )


def invoke(process, f, value, time=-1, **extra) -> Op:
    return Op(INVOKE, f=f, process=process, value=value, time=time, extra=extra)


def ok(process, f, value, time=-1, **extra) -> Op:
    return Op(OK, f=f, process=process, value=value, time=time, extra=extra)


def fail(process, f, value, time=-1, **extra) -> Op:
    return Op(FAIL, f=f, process=process, value=value, time=time, extra=extra)


def info(process, f, value, time=-1, **extra) -> Op:
    return Op(INFO, f=f, process=process, value=value, time=time, extra=extra)


class History:
    """An indexed sequence of Ops with struct-of-arrays access.

    Supports list-like iteration/indexing plus columnar views used by the
    tensor encoders. Mutation is append-only (`append`); most pipeline
    stages produce new History objects.
    """

    __slots__ = ("ops",)

    def __init__(self, ops: Optional[Iterable] = None):
        self.ops: list[Op] = []
        if ops is not None:
            for o in ops:
                self.append(o)

    def append(self, op) -> None:
        if isinstance(op, dict):
            op = Op.from_dict(op)
        if not isinstance(op, Op):
            raise TypeError(f"not an Op: {op!r}")
        self.ops.append(op)

    # -- sequence protocol --
    def __len__(self) -> int:
        return len(self.ops)

    def __iter__(self) -> Iterator[Op]:
        return iter(self.ops)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return History(self.ops[i])
        return self.ops[i]

    def __eq__(self, other) -> bool:
        if isinstance(other, History):
            return self.ops == other.ops
        if isinstance(other, list):
            return self.ops == other
        return NotImplemented

    def __repr__(self) -> str:
        return f"History({len(self.ops)} ops)"

    # -- transforms --
    def index(self) -> "History":
        """Assign sequential :index to every op (knossos history/index
        parity; the reference indexes every history before checking,
        jepsen/src/jepsen/core.clj:228)."""
        return History(op.with_(index=i) for i, op in enumerate(self.ops))

    def filter(self, pred: Callable[[Op], bool]) -> "History":
        return History(op for op in self.ops if pred(op))

    def map(self, f: Callable[[Op], Op]) -> "History":
        return History(f(op) for op in self.ops)

    @property
    def invocations(self) -> "History":
        return self.filter(lambda o: o.is_invoke)

    @property
    def oks(self) -> "History":
        return self.filter(lambda o: o.is_ok)

    @property
    def client_ops(self) -> "History":
        return self.filter(lambda o: o.process != "nemesis")

    def pairs(self) -> list[tuple[Op, Optional[Op]]]:
        """Pair each invocation with its completion (or None if it never
        completed). Completion matching is per-process FIFO — each process
        has at most one outstanding op, matching the interpreter's
        invariant (reference: jepsen/src/jepsen/checker/timeline.clj:38-57).
        Non-invoke ops without a pending invocation (e.g. nemesis :info
        markers) are returned as (op, None) pairs too.
        """
        out: list[tuple[Op, Optional[Op]]] = []
        pending: dict[Any, int] = {}  # process -> slot in out
        for op in self.ops:
            if op.is_invoke:
                pending[op.process] = len(out)
                out.append((op, None))
            else:
                slot = pending.pop(op.process, None)
                if slot is None:
                    out.append((op, None))
                else:
                    inv, _ = out[slot]
                    out[slot] = (inv, op)
        return out

    def complete(self) -> "History":
        """Knossos `history/complete` parity: fill each invocation's value
        from its :ok completion (reads invoke with value=None and complete
        with the observed value), and mark invocations whose op completed
        :fail with extra {"fails?": True} so downstream passes can drop
        both halves. Returns a new indexed history."""
        comp: dict[int, Op] = {}
        for inv, c in self.pairs():
            if inv.is_invoke and c is not None:
                comp[id(inv)] = c
        new = []
        for op in self.ops:
            c = comp.get(id(op))
            if c is not None:
                if c.is_ok and op.value is None:
                    op = op.with_(value=c.value)
                elif c.is_fail:
                    op = op.with_(extra={**op.extra, "fails?": True})
            new.append(op)
        return History(new).index()

    # -- struct-of-arrays columns --
    def columns(self):
        """Return (type_codes, f_objs, process_objs, times, indexes) as numpy
        arrays / object arrays. Cheap columnar access for encoders."""
        n = len(self.ops)
        types = np.empty(n, dtype=np.int8)
        times = np.empty(n, dtype=np.int64)
        idxs = np.empty(n, dtype=np.int64)
        fs = np.empty(n, dtype=object)
        procs = np.empty(n, dtype=object)
        for i, op in enumerate(self.ops):
            types[i] = _TYPE_CODES[op.type]
            times[i] = op.time
            idxs[i] = op.index
            fs[i] = op.f
            procs[i] = op.process
        return types, fs, procs, times, idxs

    # -- serialization --
    def to_jsonl(self, path: str) -> None:
        with open(path, "w") as fh:
            for op in self.ops:
                fh.write(json.dumps(op.to_dict(), default=str) + "\n")

    @staticmethod
    def from_jsonl(path: str) -> "History":
        h = History()
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    h.append(json.loads(line))
        return h

    @staticmethod
    def from_edn(path: str) -> "History":
        """Replay a reference-produced history.edn (one op map per prn
        line, store.clj:338-346) or a checker_test.clj-style vector of
        op maps. See jepsen_tpu.edn for the reader's scope."""
        from . import edn
        with open(path) as fh:
            return edn.load_history(fh.read())


def strip_nemesis(history: History) -> History:
    """Client ops only — checkers generally ignore nemesis ops."""
    return history.filter(lambda o: o.process != "nemesis")
