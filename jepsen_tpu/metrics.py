"""Search telemetry: metrics registry for the checker kernels.

The round-5 scatter-lean rework happened because someone hand-profiled
the TPU kernels in a notebook and discovered per-round cost was
serialized memory-op latency — none of which was visible from the
framework. The kernels already compute rich per-chunk device stats
(the packed poll summary in ops/wgl32.py / ops/wgln.py carries
frontier count, memo hits, explored totals, backlog depth) but the
host driver used to discard everything but the stop condition. This
module is the sink those numbers flow into:

  * `Counter` / `Gauge` / `Histogram` — classic instruments with
    label support, thread-safe (the competition checker runs engines
    in threads that all record into one registry);
  * `Timeseries` — an append-only per-run series of dict points; the
    WGL drivers append one point per device chunk (the poll summary
    plus host-side poll latency), so a whole search's trajectory is
    reconstructable after the fact;
  * exporters — JSONL (one line per instrument / series point) and
    Prometheus text exposition, both file- and string-oriented so the
    bench can persist them into its artifact tree and a scrape
    endpoint can serve them unchanged. The JSONL line schemas are a
    CONTRACT: scripts/telemetry_lint.py validates persisted artifacts
    against them (tier-1-gated), so evolve them additively.

Zero-cost when disabled: the module default is a `NullRegistry` whose
instruments are shared no-op singletons — a disabled `counter().inc()`
is one attribute lookup and an empty method call, no locks, no dict
traffic, and the kernel drivers skip point construction entirely.
Enable globally with JEPSEN_TPU_METRICS=1, per-call with the
`metrics=` kwarg on `ops.wgl.check`, or ambiently via `use()` /
`set_default()`.

    reg = metrics.Registry()
    with metrics.use(reg):
        res = wgl.check(model, history)
    reg.export_jsonl(path)          # per-chunk timeseries + counters
    reg.prometheus_text()           # scrape-format snapshot

Checker phase spans ride the existing `trace.Tracer` (same trace.jsonl
format clients use) — see ops/wgl.py and checker.Linearizable; this
module only carries numbers.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Iterator, Optional

# Histogram default buckets: poll/kernel latencies span ~100 µs (warm
# cpu fast-path chunks) to minutes (cold accelerator compiles).
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                   10.0, 30.0, 60.0, 120.0)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: tuple) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


class Counter:
    """Monotonic counter, optionally labeled."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: dict = {}

    def inc(self, n: float = 1, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0) + n

    def value(self, **labels) -> float:
        return self._values.get(_label_key(labels), 0)

    def samples(self) -> list:
        with self._lock:
            return [(k, v) for k, v in self._values.items()]


class Gauge(Counter):
    """Last-write-wins instantaneous value."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = v


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations <= its upper bound, +Inf implicit)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: tuple = DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._states: dict = {}  # label key -> [bucket counts, sum, n]

    def observe(self, v: float, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            st = self._states.get(k)
            if st is None:
                st = self._states[k] = [[0] * len(self.buckets), 0.0, 0]
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    st[0][i] += 1
            st[1] += v
            st[2] += 1

    def count(self, **labels) -> int:
        st = self._states.get(_label_key(labels))
        return st[2] if st else 0

    def sum(self, **labels) -> float:
        st = self._states.get(_label_key(labels))
        return st[1] if st else 0.0

    def samples(self) -> list:
        with self._lock:
            return [(k, [list(st[0]), st[1], st[2]])
                    for k, st in self._states.items()]


class Timeseries:
    """Append-only series of dict points; each point gets a wall-clock
    `t` stamp unless the caller provides one. The WGL drivers append
    one point per device chunk."""

    kind = "series"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._points: list = []

    def append(self, point: dict) -> None:
        p = dict(point)
        p.setdefault("t", time.time())
        with self._lock:
            self._points.append(p)

    @property
    def points(self) -> list:
        with self._lock:
            return list(self._points)

    @property
    def last(self) -> Optional[dict]:
        """The most recent point (None when empty) — the live view a
        status panel or scraper wants without copying the series."""
        with self._lock:
            return dict(self._points[-1]) if self._points else None

    def __len__(self) -> int:
        return len(self._points)

    def trim(self, keep: int) -> int:
        """Drop all but the newest `keep` points; returns how many
        were dropped. Long-lived recorders (the service plane)
        rotate their series with this — bench/test runs never call
        it, so their exports stay complete."""
        with self._lock:
            dropped = max(0, len(self._points) - max(0, int(keep)))
            if dropped:
                del self._points[:dropped]
        return dropped


class _NullInstrument:
    """Shared no-op stand-in for every instrument kind: all recording
    methods swallow their arguments without taking a lock."""

    kind = "null"
    name = help = ""
    buckets = ()
    points: list = []
    last = None

    def inc(self, n: float = 1, **labels) -> None:
        pass

    def set(self, v: float, **labels) -> None:
        pass

    def observe(self, v: float, **labels) -> None:
        pass

    def append(self, point: dict) -> None:
        pass

    def value(self, **labels) -> float:
        return 0

    def count(self, **labels) -> int:
        return 0

    def sum(self, **labels) -> float:
        return 0.0

    def samples(self) -> list:
        return []

    def trim(self, keep: int) -> int:
        return 0

    def __len__(self) -> int:
        return 0


_NULL_INSTRUMENT = _NullInstrument()


class Registry:
    """Thread-safe instrument registry with get-or-create semantics.
    `enabled` is a plain attribute the hot paths read once per call —
    a disabled registry hands out the shared null instrument."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._instruments: dict = {}

    def _get(self, cls, name: str, help: str, **kw):
        if not self.enabled:
            return _NULL_INSTRUMENT
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name, help, **kw)
            elif type(inst) is not cls:
                # exact-type check: Gauge subclasses Counter, and a
                # counter() call must not silently hand back a gauge
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{inst.kind}, requested {cls.kind}")
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def series(self, name: str, help: str = "") -> Timeseries:
        return self._get(Timeseries, name, help)

    def instruments(self) -> list:
        with self._lock:
            return list(self._instruments.values())

    # -- exporters ----------------------------------------------------
    def snapshot(self) -> dict:
        """Plain-data view of every instrument (for results/JSON)."""
        out: dict = {}
        for inst in self.instruments():
            if inst.kind in ("counter", "gauge"):
                out[inst.name] = {
                    "kind": inst.kind,
                    "values": {(_label_str(k) or "total"): v
                               for k, v in inst.samples()}}
            elif inst.kind == "histogram":
                out[inst.name] = {
                    "kind": inst.kind, "buckets": list(inst.buckets),
                    "values": {(_label_str(k) or "total"):
                               {"bucket_counts": st[0], "sum": st[1],
                                "count": st[2]}
                               for k, st in inst.samples()}}
            else:
                out[inst.name] = {"kind": "series",
                                  "points": inst.points}
        return out

    def export_jsonl(self, path: str) -> int:
        """One JSON line per counter/gauge/histogram labelset and per
        series point; returns the line count."""
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        n = 0
        with open(path, "w") as fh:
            for inst in self.instruments():
                if inst.kind == "series":
                    for p in inst.points:
                        fh.write(json.dumps(
                            {"type": "sample", "series": inst.name,
                             **p}) + "\n")
                        n += 1
                elif inst.kind == "histogram":
                    for k, st in inst.samples():
                        fh.write(json.dumps(
                            {"type": "histogram", "name": inst.name,
                             "labels": dict(k),
                             "buckets": list(inst.buckets),
                             "bucket_counts": st[0], "sum": st[1],
                             "count": st[2]}) + "\n")
                        n += 1
                else:
                    for k, v in inst.samples():
                        fh.write(json.dumps(
                            {"type": inst.kind, "name": inst.name,
                             "labels": dict(k), "value": v}) + "\n")
                        n += 1
        return n

    def prometheus_text(self) -> str:
        """Prometheus text exposition format. Series export their LAST
        point's numeric fields as `<series>_<field>` gauges — the live
        view a scraper wants; history rides the JSONL exporter."""
        lines: list = []

        def emit(name, kind, help):
            if help:
                lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} {kind}")

        for inst in self.instruments():
            name = _prom_name(inst.name)
            if inst.kind in ("counter", "gauge"):
                emit(name, inst.kind, inst.help)
                for k, v in inst.samples():
                    lines.append(f"{name}{_label_str(k)} {_prom_num(v)}")
            elif inst.kind == "histogram":
                emit(name, "histogram", inst.help)
                for k, st in inst.samples():
                    base = dict(k)
                    for ub, c in zip(inst.buckets, st[0]):
                        lbl = _label_str(_label_key(
                            {**base, "le": _prom_num(ub)}))
                        lines.append(f"{name}_bucket{lbl} {c}")
                    lbl = _label_str(_label_key({**base, "le": "+Inf"}))
                    lines.append(f"{name}_bucket{lbl} {st[2]}")
                    lines.append(f"{name}_sum{_label_str(k)} "
                                 f"{_prom_num(st[1])}")
                    lines.append(f"{name}_count{_label_str(k)} {st[2]}")
            else:
                last = inst.last
                if last is None:
                    continue
                for field, v in sorted(last.items()):
                    if isinstance(v, bool) or not isinstance(
                            v, (int, float)):
                        continue
                    # one TYPE-declared family per derived gauge: a
                    # strict exposition parser requires sample names
                    # to match their declared family
                    fname = f"{name}_{_prom_name(field)}"
                    emit(fname, "gauge",
                         inst.help or "last point of a run timeseries")
                    lines.append(f"{fname} {_prom_num(v)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def export_prometheus(self, path: str) -> str:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        text = self.prometheus_text()
        with open(path, "w") as fh:
            fh.write(text)
        return path


class NullRegistry(Registry):
    """The disabled registry: hands out the shared null instrument
    from every accessor, exports nothing."""

    def __init__(self):
        super().__init__(enabled=False)


NULL = NullRegistry()


def _prom_name(name: str) -> str:
    return "".join(c if (c.isalnum() or c in "_:") else "_"
                   for c in name)


def _prom_num(v) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


# -- ambient default registry ------------------------------------------------
# A plain module global (NOT thread-local): the competition checker's
# engine threads must all see the registry the caller installed.
_default: Registry = (
    Registry() if os.environ.get("JEPSEN_TPU_METRICS", "")
    not in ("", "0") else NULL)


def get_default() -> Registry:
    """The ambient registry — NULL unless JEPSEN_TPU_METRICS=1 was set
    at import or a caller installed one via set_default()/use()."""
    return _default


def set_default(reg: Optional[Registry]) -> Registry:
    """Install `reg` (None -> the shared NULL) as the ambient default;
    returns the previous one."""
    global _default
    prev = _default
    _default = reg if reg is not None else NULL
    return prev


@contextlib.contextmanager
def use(reg: Registry) -> Iterator[Registry]:
    """Scoped ambient registry (restores the previous on exit)."""
    prev = set_default(reg)
    try:
        yield reg
    finally:
        set_default(prev)
