"""Service-level objectives over the checker-as-a-service plane.

ROADMAP item 1 names a hard target — "a sustained stream of mixed
requests at p50 < 1 s warm, admission-to-verdict" — and until this
module nothing MEASURED it: the service plane (service.py) stamps
every request's phase walls into `kind="service-request"` ledger
records, and this module turns those records into evaluated
objectives, error budgets, and multi-window burn-rate alerts — the
same treatment the kernels already get from the occupancy/regression
planes, applied to the serving path.

Objectives are declarative (`Objective`): each one names a per-request
"good" predicate (latency under a threshold, or decided-at-all for
availability) and a target fraction (the SLO level — p50 < 1 s is
"50% of warm requests under 1 s", availability 0.99 is "99% of
requests decided"). Evaluation over ROLLING WINDOWS from the ledger:

  * `good_frac`   fraction of applicable requests that were good
  * `met`         good_frac >= target_frac (None when the window has
                  fewer than `min_events` applicable requests — an
                  empty window abstains, never alarms)
  * `burn_rate`   bad_frac / (1 - target_frac): 1.0 means the window
                  consumed exactly its error budget; >1 is burning
  * budget        over the LONGEST window: allowed bad fraction,
                  fraction of it spent, fraction remaining

A **burn alert** fires when every populated window burns past
`burn_x` (env JEPSEN_TPU_SLO_BURN_X, default 2.0) — the classic
multi-window gate: the short window catches the burn fast, the long
window confirms it is not a blip. Alerts are published as structured
fleet faults (`fleet.record_fault`, stage="slo") so they land on the
live RunStatus and the `fleet_faults` series, plus a linted `slo`
metrics series point per objective and one `kind="slo"` ledger record
per evaluation (scripts/telemetry_lint.py validates both). The doctor
correlates them (rule D011 slo-burn names the dominant phase of the
slowest requests); `/status.json` carries an `slo` block and web.py
renders the auto-refreshing `/slo` panel.

Admission rejections (cause "preflight" / "quota") are excluded from
every objective: they are client-shaped 4xx-class outcomes, not
service failures — a flood of infeasible requests must not burn the
availability budget. Thresholds are env-tunable so the CI box can
scale them (`JEPSEN_TPU_SLO_WARM_P50_S` etc.); schemas are documented
in doc/OBSERVABILITY.md "Service & SLO plane".
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

from . import fleet
from . import ledger as ledger_mod
from . import metrics as metrics_mod

SCHEMA = 1

# Rolling evaluation windows, seconds, short-to-long (env:
# comma-separated JEPSEN_TPU_SLO_WINDOWS). The defaults are CI-scale
# — a production deployment would run e.g. "300,3600".
DEFAULT_WINDOWS_S = (60.0, 600.0)

# A window with fewer applicable requests than this abstains (met =
# None, no burn contribution): two requests cannot represent a p95.
MIN_EVENTS = 4

# Burn-rate gate: every populated window must burn past this multiple
# of the error budget before the alert fires.
DEFAULT_BURN_X = 2.0

# Admission outcomes that never count against an objective. "shed" is
# the backpressure loop closing: a burn alert sheds new arrivals
# (service.Service), and counting those 503s against availability
# would make the shed itself deepen the burn that caused it.
_ADMISSION_CAUSES = ("preflight", "quota", "malformed-request", "shed")


def burn_threshold() -> float:
    """The multi-window burn gate (env JEPSEN_TPU_SLO_BURN_X) — one
    definition shared with the doctor's D011 rule."""
    try:
        return float(os.environ.get("JEPSEN_TPU_SLO_BURN_X",
                                    DEFAULT_BURN_X))
    except ValueError:
        return DEFAULT_BURN_X


def windows_from_env() -> tuple:
    val = os.environ.get("JEPSEN_TPU_SLO_WINDOWS", "")
    if not val:
        return DEFAULT_WINDOWS_S
    try:
        wins = tuple(sorted(float(w) for w in val.split(",") if w))
        return wins or DEFAULT_WINDOWS_S
    except ValueError:
        return DEFAULT_WINDOWS_S


@dataclass(frozen=True)
class Objective:
    """One declarative objective.

    `threshold_s` None makes it an availability objective (good =
    the request DECIDED: verdict True or False, not "unknown");
    otherwise good = the request's latency (`phase` key inside the
    record's `phases` block when set, else the top-level `field`)
    landed under the threshold. `warm_only` restricts the objective
    to warm-hit requests (the ROADMAP p50 target is a WARM target —
    cold compiles are the warm pool's business, not the SLO's).
    `target_frac` is the SLO level: the fraction of applicable
    requests that must be good."""

    name: str
    description: str
    target_frac: float
    threshold_s: Optional[float] = None
    field: str = "wall_s"
    phase: Optional[str] = None
    warm_only: bool = False

    def value(self, rec: dict) -> Optional[float]:
        """The measured latency this objective judges (None for
        availability objectives or records without the field)."""
        if self.threshold_s is None:
            return None
        if self.phase is not None:
            v = (rec.get("phases") or {}).get(self.phase)
        else:
            v = rec.get(self.field)
        return float(v) if isinstance(v, (int, float)) else None

    def good(self, rec: dict) -> Optional[bool]:
        """True/False when the record is applicable, None to exclude
        it from this objective entirely."""
        if rec.get("cause") in _ADMISSION_CAUSES:
            return None
        if self.warm_only and not rec.get("warm_hit"):
            return None
        if self.threshold_s is None:
            v = rec.get("verdict")
            return v is True or v is False
        val = self.value(rec)
        if val is None:
            return None
        return val <= self.threshold_s


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def default_objectives() -> tuple:
    """The ROADMAP item-1 objectives, thresholds env-scaled so the CI
    box can widen them (`JEPSEN_TPU_SLO_WARM_P50_S`,
    `JEPSEN_TPU_SLO_QUEUE_P95_S`, `JEPSEN_TPU_SLO_AVAILABILITY`)."""
    return (
        Objective(
            name="warm-p50",
            description="warm admission-to-verdict p50 under target",
            target_frac=0.5,
            threshold_s=_env_float("JEPSEN_TPU_SLO_WARM_P50_S", 1.0),
            field="wall_s", warm_only=True),
        Objective(
            name="queue-wait-p95",
            description="queue wait p95 under target",
            target_frac=0.95,
            threshold_s=_env_float("JEPSEN_TPU_SLO_QUEUE_P95_S", 0.5),
            phase="queue_wait_s"),
        Objective(
            name="availability",
            description="fraction of requests decided (not unknown)",
            target_frac=_env_float("JEPSEN_TPU_SLO_AVAILABILITY",
                                   0.99)),
    )


def _percentile(vals: list, p: float) -> Optional[float]:
    if not vals:
        return None
    vals = sorted(vals)
    return round(vals[min(len(vals) - 1,
                          int(p * (len(vals) - 1) + 0.5))], 6)


class Engine:
    """Evaluate objectives over rolling ledger windows and publish
    the results into the telemetry planes."""

    def __init__(self, ledger: Optional[ledger_mod.Ledger] = None,
                 objectives: Optional[tuple] = None,
                 windows_s: Optional[tuple] = None,
                 burn_x: Optional[float] = None,
                 min_events: int = MIN_EVENTS):
        self.ledger = ledger
        self.objectives = tuple(objectives if objectives is not None
                                else default_objectives())
        self.windows_s = tuple(sorted(windows_s if windows_s
                                      is not None
                                      else windows_from_env()))
        self.burn_x = burn_x if burn_x is not None else burn_threshold()
        self.min_events = int(min_events)

    def records(self, now: Optional[float] = None) -> list:
        """The service-request records inside the longest window."""
        led = self.ledger if self.ledger is not None \
            else ledger_mod.get_default()
        now = now if now is not None else time.time()
        try:
            return led.query(kind="service-request",
                             since=now - max(self.windows_s))
        except Exception:  # noqa: BLE001 — a torn ledger evaluates
            return []      # as "no data", never a crashed engine

    def evaluate(self, now: Optional[float] = None,
                 records: Optional[list] = None) -> dict:
        """One evaluation report over the rolling windows. Pure host
        arithmetic over already-recorded records — unit-testable with
        fabricated ones."""
        now = now if now is not None else time.time()
        recs = records if records is not None else self.records(now)
        long_w = max(self.windows_s)
        objectives: list = []
        alerts: list = []
        for obj in self.objectives:
            wins: list = []
            populated: list = []
            for w in self.windows_s:
                in_w = [r for r in recs
                        if isinstance(r.get("t"), (int, float))
                        and r["t"] >= now - w]
                goods: list = []
                vals: list = []
                for r in in_w:
                    g = obj.good(r)
                    if g is None:
                        continue
                    goods.append(g)
                    v = obj.value(r)
                    if v is not None:
                        vals.append(v)
                n = len(goods)
                bad = sum(1 for g in goods if not g)
                allowed = 1.0 - obj.target_frac
                entry: dict = {"window_s": w, "n": n, "bad": bad}
                if n >= self.min_events:
                    good_frac = round(1.0 - bad / n, 4)
                    entry["good_frac"] = good_frac
                    entry["met"] = good_frac >= obj.target_frac
                    entry["burn_rate"] = round(
                        (bad / n) / max(allowed, 1e-9), 3)
                    if obj.threshold_s is not None:
                        entry["observed"] = _percentile(
                            vals, obj.target_frac)
                    else:
                        entry["observed"] = good_frac
                    populated.append(entry)
                else:
                    entry["good_frac"] = None
                    entry["met"] = None
                    entry["burn_rate"] = None
                wins.append(entry)
            longest = wins[-1]
            allowed = 1.0 - obj.target_frac
            # the effective gate caps at the objective's maximum
            # possible burn (1/allowed): a p50 objective tops out at
            # 2x, and "everything is bad" must still alert
            gate = min(self.burn_x,
                       round(1.0 / max(allowed, 1e-9), 3))
            burn_alert = bool(populated) and all(
                e["burn_rate"] >= gate for e in populated)
            spent = (min(10.0, round(longest["burn_rate"], 3))
                     if longest.get("burn_rate") is not None else None)
            row = {
                "name": obj.name,
                "description": obj.description,
                "target_frac": obj.target_frac,
                "threshold_s": obj.threshold_s,
                "warm_only": obj.warm_only,
                "windows": wins,
                "met": longest["met"],
                "burn_alert": burn_alert,
                "budget": {
                    "allowed_frac": round(allowed, 4),
                    # spent/remaining are fractions OF THE BUDGET
                    # (burn_rate over the long window IS the spend
                    # rate; capped so a total outage reads 10x, not
                    # infinity)
                    "spent_frac": spent,
                    "remaining_frac": (max(0.0, round(1.0 - spent, 3))
                                       if spent is not None else None),
                },
            }
            objectives.append(row)
            if burn_alert:
                worst = max(e["burn_rate"] for e in populated)
                alerts.append({
                    "objective": obj.name,
                    "burn_rate": worst,
                    "windows_s": [e["window_s"] for e in populated],
                    "summary": f"{obj.name} burning at {worst}x the "
                               f"error budget across "
                               f"{len(populated)} window(s)"})
        met_vals = [o["met"] for o in objectives]
        return {"schema": SCHEMA, "t": round(now, 3),
                "windows_s": list(self.windows_s),
                "window_s": long_w,
                "burn_x": self.burn_x,
                "requests": len(recs),
                "objectives": objectives,
                "alerts": alerts,
                "met": (None if all(m is None for m in met_vals)
                        else all(m is not False for m in met_vals)
                        and not alerts)}

    def publish(self, report: dict, mx=None, led=None) -> None:
        """Land one evaluation in the telemetry planes: `slo` series
        points + counters, burn alerts as structured fleet faults,
        and one `kind="slo"` ledger record. Never raises — the
        objectives outrank their accounting."""
        global _CHECKED, _ALERTS, _LAST_REPORT
        with _LOCK:
            _CHECKED += 1
            _ALERTS += len(report.get("alerts") or [])
            _LAST_REPORT = report
        try:
            mx = mx if mx is not None else metrics_mod.get_default()
            if mx.enabled:
                series = mx.series(
                    "slo", "objective evaluations of the service "
                           "SLO engine (rolling-window burn rates)")
                for row in report.get("objectives") or []:
                    longest = (row.get("windows") or [{}])[-1]
                    if longest.get("good_frac") is None:
                        continue  # empty window: nothing to plot
                    series.append({
                        "objective": row["name"],
                        "window_s": longest["window_s"],
                        "good_frac": longest["good_frac"],
                        "target_frac": row["target_frac"],
                        "met": bool(longest["met"]),
                        "burn_rate": longest["burn_rate"],
                        "burn_alert": bool(row.get("burn_alert")),
                        "observed": longest.get("observed"),
                        "budget_remaining":
                            (row.get("budget") or {}).get(
                                "remaining_frac")})
                mx.counter("slo_evaluations_total",
                           "SLO engine evaluations").inc()
                for a in report.get("alerts") or []:
                    mx.counter("slo_burn_alerts_total",
                               "multi-window SLO burn alerts").inc(
                        objective=str(a.get("objective")))
        except Exception:  # noqa: BLE001
            pass
        for a in report.get("alerts") or []:
            try:
                fleet.record_fault({
                    "type": "slo-burn",
                    "error": str(a.get("summary")),
                    "stage": "slo", "device": None,
                    "key_index": None}, mx=mx)
            except Exception:  # noqa: BLE001
                pass
        try:
            led = led if led is not None else (
                self.ledger if self.ledger is not None
                else ledger_mod.get_default())
            compact_objs = []
            for row in report.get("objectives") or []:
                longest = (row.get("windows") or [{}])[-1]
                if longest.get("burn_rate") is None:
                    continue
                compact_objs.append({
                    "name": row["name"],
                    "met": bool(longest["met"]),
                    "good_frac": longest["good_frac"],
                    "burn_rate": longest["burn_rate"],
                    "budget_remaining":
                        (row.get("budget") or {}).get(
                            "remaining_frac")})
            alerts = [str(a.get("objective"))
                      for a in report.get("alerts") or []]
            led.record({
                "kind": "slo", "name": "slo-eval",
                "verdict": ("unknown" if report.get("met") is None
                            else bool(report["met"])),
                "windows_s": list(report.get("windows_s") or []),
                "burn_x": report.get("burn_x"),
                "requests": report.get("requests"),
                "objectives": compact_objs,
                "burn_alerts": alerts})
        except Exception:  # noqa: BLE001
            pass

    def evaluate_and_publish(self, now: Optional[float] = None,
                             records: Optional[list] = None,
                             mx=None, led=None) -> dict:
        report = self.evaluate(now=now, records=records)
        self.publish(report, mx=mx, led=led)
        return report


# -- in-process evaluation history for /status.json --------------------------
# (the preflight/doctor snapshot pattern: the serving process answers
# its own slo block; a mirror from another process keeps its own)
_LOCK = threading.Lock()
_CHECKED = 0
_ALERTS = 0
_LAST_REPORT: Optional[dict] = None


def compact_report(report: dict) -> Optional[dict]:
    """The bounded projection of one evaluation that rides
    /status.json and the /slo panel."""
    if not isinstance(report, dict):
        return None
    objs = []
    for row in report.get("objectives") or []:
        longest = (row.get("windows") or [{}])[-1]
        objs.append({
            "name": row.get("name"),
            "target_frac": row.get("target_frac"),
            "threshold_s": row.get("threshold_s"),
            "window_s": longest.get("window_s"),
            "n": longest.get("n"),
            "good_frac": longest.get("good_frac"),
            "observed": longest.get("observed"),
            "met": longest.get("met"),
            "burn_rate": longest.get("burn_rate"),
            "burn_alert": bool(row.get("burn_alert")),
            "budget_remaining":
                (row.get("budget") or {}).get("remaining_frac")})
    return {"t": report.get("t"), "met": report.get("met"),
            "requests": report.get("requests"),
            "objectives": objs,
            "alerts": [{"objective": a.get("objective"),
                        "burn_rate": a.get("burn_rate")}
                       for a in report.get("alerts") or []]}


def snapshot() -> dict:
    """The `/status.json` `slo` block: evaluations run in this
    process, alert totals, and the last evaluation compactly."""
    with _LOCK:
        checked = _CHECKED
        alerts = _ALERTS
        last = _LAST_REPORT
    return {"checked": checked,
            "alerts_total": alerts,
            "burning": [a.get("objective")
                        for a in (last or {}).get("alerts") or []],
            "last": compact_report(last) if last else None}


def last_report() -> Optional[dict]:
    with _LOCK:
        return _LAST_REPORT


def _reset() -> None:
    """Test isolation: clear the in-process evaluation history."""
    global _CHECKED, _ALERTS, _LAST_REPORT
    with _LOCK:
        _CHECKED = 0
        _ALERTS = 0
        _LAST_REPORT = None


def evaluate_store(store_root: str, **kw) -> dict:
    """One-shot evaluation over a store's ledger (the /slo panel's
    out-of-process fallback and the CLI path) — read-only: no series
    points, no fleet faults, no ledger record."""
    return Engine(ledger_mod.Ledger(store_root), **kw).evaluate()
