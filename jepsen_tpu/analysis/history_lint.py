"""Pre-search history analyzer: well-formedness before the device burns.

Knossos-style WGL search (Lowe 2017) and Elle-style cycle search
(Kingsbury & Alvaro 2020) are only sound on well-formed histories. A
single process with two concurrent invokes, an unmatched completion,
or a value outside the encoded alphabet silently corrupts the
op/arg/process tensors `ops/encode.py` builds — and the device search
then returns a confident garbage verdict. This pass runs before every
search and turns that failure mode into a diagnosis.

Rule catalog (doc/STATIC_ANALYSIS.md has the full prose):

  H001 double-invoke      a process invoked again while an op was
                          still outstanding (breaks the one-pending-
                          op-per-process invariant `History.pairs` and
                          `linprep.prepare` rely on)
  H002 unmatched-complete an :ok/:fail completion with no pending
                          invocation for that process
  H003 time-regression    a later op carries a smaller timestamp than
                          an earlier one (among ops with real times)
  H004 negative-time      a timestamp below the -1 "unset" sentinel
  H005 index-disorder     duplicate or decreasing :index values; in
                          strict mode (post `History.index()`) also
                          gaps
  H006 unknown-op         an op's (f, value) is rejected from EVERY
                          model state reachable under the history's
                          alphabet — the op can never linearize, which
                          almost always means the value is outside the
                          model's domain (requires `model=`)
  H007 crashed-pairing    ops by a process AFTER its :info crash
                          (processes must be relabeled, as the
                          interpreter does), or an :info completion
                          with no pending invocation (warn: `linprep`
                          tolerates these as markers)
  H008 encoding           the history/model cannot be encoded within
                          kernel limits (`EncodingUnsupported`),
                          surfaced with the offending op's coordinates

Severities: "error" rules gate (fast-fail the checker as unknown);
"warn" rules only report. All structural rules are vectorized numpy
over `History.columns()` — the pass is O(n log n) and runs on every
checker invocation, including per-key fan-out sub-histories.

Entry points:

  analyze(history, model=None)  -> full report dict
  gate(history, where=...)      -> None when clean, else a checker-
                                   style {"valid?": "unknown", ...}
                                   fast-fail result (recorded into the
                                   ambient metrics/fleet planes)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from ..history import History

UNKNOWN = "unknown"

RULES = {
    "H001": "double-invoke",
    "H002": "unmatched-complete",
    "H003": "time-regression",
    "H004": "negative-time",
    "H005": "index-disorder",
    "H006": "unknown-op",
    "H007": "crashed-pairing",
    "H008": "encoding",
}

# Rules that fast-fail a linearizability check. H006/H008 need a model
# and are advisory (an out-of-alphabet *read* is often a genuine
# non-linearizable observation the search itself must judge).
GATE_RULES = ("H001", "H002", "H003", "H004", "H005", "H007")

# Elle histories legitimately omit invocations (the reference Elle
# accepts completion-only txn lists), so the elle gate drops the
# pairing rules and keeps the clock/index ones.
ELLE_GATE_RULES = ("H001", "H003", "H004", "H005")

# The independent fan-out gate sees the WHOLE multi-key history;
# merged per-key streams may legitimately carry per-key clocks (the
# repo's own synthetic multi-key histories do), so global time
# monotonicity is not required here — each per-key subhistory still
# passes through the full checker gate downstream.
INDEPENDENT_GATE_RULES = ("H001", "H002", "H004", "H005", "H007")

# Cap diagnostics per rule; one summary entry reports the overflow.
MAX_PER_RULE = 16


@dataclass
class Diagnostic:
    """One analyzer finding, pointing at an exact op."""

    rule: str           # rule id, e.g. "H001"
    op_index: int       # the op's :index when assigned, else position
    position: int       # position in the analyzed history
    process: object     # the op's process (None for summary entries)
    message: str
    severity: str = "error"   # "error" gates; "warn" only reports
    value: object = None

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "name": RULES.get(self.rule, "?"),
             "op_index": self.op_index, "position": self.position,
             "process": self.process, "message": self.message,
             "severity": self.severity}
        if self.value is not None:
            d["value"] = self.value
        return d


def _diag(history: History, pos: int, rule: str, msg: str,
          severity: str = "error") -> Diagnostic:
    op = history[pos]
    idx = op.index if op.index is not None and op.index >= 0 else pos
    return Diagnostic(rule=rule, op_index=int(idx), position=int(pos),
                      process=op.process, message=msg,
                      severity=severity, value=op.value)


def _cap(history: History, positions, rule: str, fmt, diags: list,
         severity: str = "error") -> None:
    """Append up to MAX_PER_RULE diagnostics for `positions`, plus one
    summary entry when the rule fired more often."""
    positions = list(positions)
    for pos in positions[:MAX_PER_RULE]:
        diags.append(_diag(history, int(pos), rule, fmt(int(pos)),
                           severity=severity))
    if len(positions) > MAX_PER_RULE:
        diags.append(Diagnostic(
            rule=rule, op_index=-1, position=-1, process=None,
            severity=severity,
            message=f"... and {len(positions) - MAX_PER_RULE} more "
                    f"{RULES[rule]} findings (suppressed)"))


def lint_structure(history: History,
                   rules: Sequence[str] = tuple(RULES),
                   strict_index: bool = False) -> list:
    """The vectorized structural pass (H001-H005, H007). Returns a
    list of Diagnostics; model-dependent rules live in `lint_model`."""
    n = len(history)
    diags: list = []
    if n == 0:
        return diags
    rules = set(rules)
    types, _fs, procs, times, idxs = history.columns()
    is_inv = types == 0
    is_ok = types == 1
    is_fail = types == 2
    is_info = types == 3

    # -- per-process pairing rules (H001/H002/H007) -------------------
    if rules & {"H001", "H002", "H007"}:
        pid_of: dict = {}
        pid = np.empty(n, dtype=np.int64)
        for i, p in enumerate(procs):
            key = (type(p).__name__, p)  # 1 and "1" are different procs
            pid[i] = pid_of.setdefault(key, len(pid_of))
        order = np.lexsort((np.arange(n), pid))  # by process, stable
        start = np.empty(n, dtype=bool)
        start[0] = True
        ps = pid[order]
        start[1:] = ps[1:] != ps[:-1]
        gidx = np.cumsum(start) - 1

        def seg_cumsum(vals_sorted):
            """Within-group inclusive cumsum over the sorted domain."""
            cs = np.cumsum(vals_sorted)
            offsets = (cs - vals_sorted)[start]
            return cs - offsets[gidx]

        delta = np.where(is_inv, 1, -1).astype(np.int64)[order]
        depth_after = seg_cumsum(delta)
        depth_before = depth_after - delta

        if "H001" in rules:
            bad = is_inv[order] & (depth_before >= 1)
            _cap(history, order[bad], "H001",
                 lambda p: f"process {history[p].process!r} invoked "
                           "while an op was still outstanding", diags)
        if "H002" in rules:
            bad = (is_ok | is_fail)[order] & (depth_before <= 0)
            _cap(history, order[bad], "H002",
                 lambda p: f"{history[p].type} completion for process "
                           f"{history[p].process!r} with no pending "
                           "invocation", diags)
        if "H007" in rules:
            crashed = is_info[order].astype(np.int64)
            crashed_before = seg_cumsum(crashed) - crashed
            bad = crashed_before >= 1
            _cap(history, order[bad], "H007",
                 lambda p: f"op by process {history[p].process!r} "
                           "after its :info crash (crashed processes "
                           "must be relabeled)", diags)
            # info completion with nothing pending: linprep tolerates
            # these as markers, so warn rather than gate
            bad = is_info[order] & (depth_before <= 0)
            _cap(history, order[bad], "H007",
                 lambda p: f":info completion for process "
                           f"{history[p].process!r} with no pending "
                           "invocation", diags, severity="warn")

    # -- clock rules (H003/H004) --------------------------------------
    if "H004" in rules:
        bad = np.flatnonzero(times < -1)
        _cap(history, bad, "H004",
             lambda p: f"negative timestamp {history[p].time}", diags)
    if "H003" in rules:
        has_t = times >= 0
        if has_t.any():
            lo = np.iinfo(np.int64).min
            run = np.maximum.accumulate(np.where(has_t, times, lo))
            prev = np.empty(n, dtype=np.int64)
            prev[0] = lo
            prev[1:] = run[:-1]
            bad = np.flatnonzero(has_t & (times < prev))
            _cap(history, bad, "H003",
                 lambda p: f"timestamp {history[p].time} regresses "
                           "below an earlier op's", diags)

    # -- index rule (H005) --------------------------------------------
    if "H005" in rules:
        assigned = idxs >= 0
        if assigned.any():
            lo = np.iinfo(np.int64).min
            run = np.maximum.accumulate(np.where(assigned, idxs, lo))
            prev = np.empty(n, dtype=np.int64)
            prev[0] = lo
            prev[1:] = run[:-1]
            bad = np.flatnonzero(assigned & (idxs <= prev))
            _cap(history, bad, "H005",
                 lambda p: f"index {history[p].index} duplicates or "
                           "regresses an earlier op's", diags)
            if strict_index and not len(bad):
                want = np.arange(n)
                gaps = np.flatnonzero(assigned & (idxs != want))
                _cap(history, gaps[:1], "H005",
                     lambda p: f"index {history[p].index} at position "
                               f"{p}: history is not densely indexed "
                               "(run History.index())", diags)
    return diags


def lint_model(history: History, model,
               max_states: int = 1 << 14) -> list:
    """Model-dependent rules (H006/H008): encode the history's op
    alphabet against the model's reachable state space and flag ops no
    reachable state accepts. Skipped silently when the structural pass
    would already make `linprep.prepare` raise."""
    from ..models.core import Model
    from ..ops.encode import EncodingUnsupported, _hashable, build_table
    from ..ops.linprep import prepare

    diags: list = []
    if model is None or not isinstance(model, Model):
        return diags
    try:
        ops = prepare(history)
    except ValueError:
        return diags  # structural rules own this failure
    if not ops:
        return diags
    key_of: dict = {}
    alphabet: list = []
    codes: list = []
    for o in ops:
        # the same alphabet key encode() uses, so H006 advisories
        # classify ops exactly as the encoder will
        k = (o.f, _hashable(o.value))
        c = key_of.get(k)
        if c is None:
            c = key_of[k] = len(alphabet)
            alphabet.append(o.as_op())
        codes.append(c)
    op_counts: dict = {}
    for o in ops:
        op_counts[o.f] = op_counts.get(o.f, 0) + 1
    try:
        table, _states = build_table(model, alphabet,
                                     max_states=max_states,
                                     op_counts=op_counts)
    except EncodingUnsupported as e:
        diags.append(Diagnostic(
            rule="H008",
            op_index=e.op_index if e.op_index is not None else -1,
            position=-1, process=e.process, value=e.value,
            message=f"encoding unsupported: {e}", severity="warn"))
        return diags
    dead = ~np.any(table >= 0, axis=0)  # column accepted by no state
    flagged = 0
    for o, c in zip(ops, codes):
        if dead[c]:
            flagged += 1
            if flagged > MAX_PER_RULE:
                continue
            diags.append(Diagnostic(
                rule="H006", op_index=o.orig_index, position=o.inv,
                process=o.process, value=o.value, severity="warn",
                message=f"op ({o.f!r}, {o.value!r}) is rejected from "
                        "every reachable model state — value outside "
                        "the model alphabet?"))
    if flagged > MAX_PER_RULE:
        diags.append(Diagnostic(
            rule="H006", op_index=-1, position=-1, process=None,
            severity="warn",
            message=f"... and {flagged - MAX_PER_RULE} more "
                    "unknown-op findings (suppressed)"))
    return diags


def analyze(history: History, model=None,
            rules: Sequence[str] = tuple(RULES),
            strict_index: bool = False) -> dict:
    """Full analyzer report over `history`.

    Returns {"ok": <no error-severity findings>, "valid":
    True|"unknown", "anomalies": [diag dicts], "op_count", and
    "rule_counts"}. `model` enables the H006/H008 alphabet rules."""
    diags = lint_structure(history, rules=rules,
                           strict_index=strict_index)
    if model is not None and ("H006" in rules or "H008" in rules):
        diags += lint_model(history, model)
    counts: dict = {}
    for d in diags:
        counts[d.rule] = counts.get(d.rule, 0) + 1
    errors = [d for d in diags if d.severity == "error"]
    return {
        "ok": not errors,
        "valid": True if not errors else UNKNOWN,
        "anomalies": [d.to_dict() for d in diags],
        "op_count": len(history),
        "rule_counts": counts,
    }


def gate(history: History, where: str = "checker",
         rules: Sequence[str] = GATE_RULES,
         metrics=None, status=None) -> Optional[dict]:
    """The checker-side fast-fail: run the structural gate rules and
    return None when the history is well-formed, else a checker-style
    result

        {"valid?": "unknown", "cause": "malformed-history",
         "anomalies": [...], "analyzer": {...}}

    so `checker.Linearizable` / `elle.*` / `independent` can return a
    diagnosis instead of burning device time on garbage tensors. The
    verdict and findings are recorded into the ambient metrics
    registry (`history_lint` series + counters) and the live
    `fleet.RunStatus`."""
    from .. import fleet as _fleet
    from .. import metrics as _metrics

    diags = [d for d in lint_structure(history, rules=rules)
             if d.severity == "error"]
    mx = metrics if metrics is not None else _metrics.get_default()
    if not diags:
        if mx.enabled:
            mx.counter("history_lint_checks_total",
                       "pre-search history analyzer runs").inc(
                where=where, verdict="clean")
        return None
    counts: dict = {}
    for d in diags:
        counts[d.rule] = counts.get(d.rule, 0) + 1
    if mx.enabled:
        mx.counter("history_lint_checks_total",
                   "pre-search history analyzer runs").inc(
            where=where, verdict="malformed")
        for rule, c in counts.items():
            mx.counter("history_lint_anomalies_total",
                       "structural anomalies found by the history "
                       "analyzer").inc(c, rule=rule, where=where)
        first = {k: (v if isinstance(v, (str, int, float, bool,
                                         type(None))) else repr(v))
                 for k, v in diags[0].to_dict().items()}
        mx.series("history_lint",
                  "malformed-history gate events").append(
            {"where": where, "op_count": len(history),
             "rule_counts": counts,
             # JSON-safe copy: op values/processes can be arbitrary
             # objects (KV tuples), and export_jsonl has no default=
             "first": first})
    st = status if status is not None else _fleet.get_default()
    if st.enabled:
        st.fault({"type": "MalformedHistory",
                  "error": f"{sum(counts.values())} anomalies "
                           f"({', '.join(sorted(counts))}) "
                           f"at {where}",
                  "stage": f"history-lint/{where}"})
    return {
        "valid?": UNKNOWN,
        "cause": "malformed-history",
        "anomalies": [d.to_dict() for d in diags],
        "analyzer": {"where": where, "op_count": len(history),
                     "rule_counts": counts},
    }


def self_check() -> dict:
    """Tier-1 self-check: every gate rule must fire on its seeded
    malformed history and stay silent on a clean one. Returns
    {"ok": bool, "failures": [...]}; wired as a test and usable from
    the CLI (`python -m jepsen_tpu.analysis.history_lint`)."""
    from ..history import info, invoke, ok

    failures: list = []

    def expect(name, hist, rule, should_fire=True):
        rep = analyze(hist, rules=tuple(RULES), strict_index=False)
        fired = rule in rep["rule_counts"]
        if fired != should_fire:
            failures.append(f"{name}: rule {rule} "
                            f"{'missing' if should_fire else 'spurious'}")

    clean = History([invoke(0, "write", 1, time=0),
                     ok(0, "write", 1, time=1),
                     invoke(1, "read", None, time=2),
                     ok(1, "read", 1, time=3)]).index()
    for r in GATE_RULES:
        expect("clean", clean, r, should_fire=False)
    expect("double-invoke",
           History([invoke(0, "write", 1, time=0),
                    invoke(0, "write", 2, time=1)]).index(), "H001")
    expect("unmatched-complete",
           History([ok(0, "write", 1, time=0)]).index(), "H002")
    expect("time-regression",
           History([invoke(0, "write", 1, time=5),
                    ok(0, "write", 1, time=2)]).index(), "H003")
    expect("negative-time",
           History([invoke(0, "write", 1, time=-7)]).index(), "H004")
    h = History([invoke(0, "write", 1, time=0),
                 ok(0, "write", 1, time=1)])
    h = History([op.with_(index=3) for op in h])
    expect("index-disorder", h, "H005")
    expect("crashed-pairing",
           History([invoke(0, "write", 1, time=0),
                    info(0, "write", 1, time=1),
                    invoke(0, "write", 2, time=2)]).index(), "H007")
    return {"ok": not failures, "failures": failures}


def main(argv=None) -> int:
    import json
    import sys
    res = self_check()
    print(json.dumps(res, indent=2))
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    import sys
    sys.exit(main())
