"""Static + runtime analysis for the checker pipeline.

Two planes, both cheap enough to run always:

  * `history_lint`  — a vectorized well-formedness pass over histories,
                      run BEFORE every WGL/Elle search. The device
                      kernels assume well-formed input (one outstanding
                      op per process, monotone clocks, values inside
                      the encoded alphabet); a malformed history used
                      to silently corrupt the encoded tensors and
                      return a garbage verdict. Now it fast-fails as
                      `{"valid?": "unknown", "anomalies": [...]}` with
                      rule ids and exact op coordinates.
  * `jaxlint`       — an AST linter over the kernel modules
                      (`jepsen_tpu/ops/`, `jepsen_tpu/elle/`) for the
                      classic JAX footguns: host syncs inside jitted
                      regions, Python branches on tracers, per-call
                      `jax.jit` construction, closure captures that
                      force retraces, implicit dtype promotion, and
                      Python loops that belong in `lax` control flow.
                      `scripts/jax_lint.py` is the CLI; CI keeps the
                      tree lint-clean.
  * `guards`        — runtime budget guards: a context manager that
                      counts XLA compilations (via `jax.monitoring`)
                      and the framework's own host<->device transfers
                      during a checker run, and asserts budgets (e.g.
                      re-checking a same-shape history must not
                      recompile). Used by tests and `bench.py`.
  * `preflight`     — the static kernel-plan & capacity analyzer
                      (admission control): enumerates, WITHOUT
                      executing, the ladder buckets / kernel variants
                      / Elle route a check would take, costs each
                      plan node via tracing+lowering-only
                      `Lowered.cost_analysis`, and returns a
                      `feasible | degrade | infeasible` verdict
                      (rules P001-P006). Infeasible requests
                      fast-fail as `{"valid?": "unknown", "cause":
                      "preflight"}` before any backend compile or
                      device byte. CLI: `python -m jepsen_tpu
                      preflight`.

Rule catalogs and allowlist syntax: doc/STATIC_ANALYSIS.md.
"""

from . import guards, history_lint, jaxlint, preflight  # noqa: F401

__all__ = ["history_lint", "jaxlint", "guards", "preflight"]
