"""Runtime lock-order witness + contention profiler (opt-in).

`threadlint` is the static half of the concurrency plane: it reasons
about the lock-acquisition order it can SEE in nested `with` blocks.
This module is the runtime half — the FreeBSD witness(4) idea: observe
the order locks are ACTUALLY taken in, process-wide, and fail the run
the first time two locks are ever taken in both orders (a potential
deadlock that static analysis across call boundaries can miss), while
profiling per-lock hold times and contention for the doctor's D016
lock-contention rule.

Zero-cost contract (the CompileGuard idiom, taken one step further):
with `JEPSEN_TPU_LOCKWATCH` unset, the factories return **plain**
`threading.Lock()` / `threading.RLock()` objects — there is no
wrapper in the lock path at all, not even a truthiness check. The
disabled-mode test proves this by type identity plus the module event
counter staying zero. Enabled (`JEPSEN_TPU_LOCKWATCH=1`), they return
a `WatchedLock` that:

  * times every acquire (wait_s = contention) and hold (hold_s);
  * maintains a per-thread held-lock stack and a process-wide
    acquisition-order graph: acquiring B while holding A adds edge
    A->B; if B->...->A already exists, that is an observed
    **lock-order cycle** — recorded, emitted as a `lockwatch` series
    `event="cycle"` point, and (by default) raised as
    `LockOrderViolation`, an AssertionError, at the acquire site
    (`JEPSEN_TPU_LOCKWATCH_STRICT=0` downgrades to record-only);
  * samples `lockwatch` series points (lock label, event
    acquire/release/cycle, hold_s, wait_s — schema enforced by
    scripts/telemetry_lint.py), throttled per lock so a hot service
    lock does not flood the registry;
  * speaks the `Condition` protocol (`_release_save` /
    `_acquire_restore` / `_is_owned`), so
    `threading.Condition(lockwatch.rlock("service"))` works and
    `Condition.wait` correctly unwinds the witness hold.

`report()` returns the graph + per-lock stats; `bank(ledger)` writes
one `kind="lockwatch"` ledger record (edge list, cycle bool, per-lock
hold/wait p95) that `/status.json` and the doctor read. Reentrant
re-acquires of one RLock add no edges (not a cycle). The smokes run
with the witness on and assert zero cycles.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

ENV = "JEPSEN_TPU_LOCKWATCH"
STRICT_ENV = "JEPSEN_TPU_LOCKWATCH_STRICT"

# per-lock series sampling floor: a hot lock's acquire/release would
# otherwise emit kHz points; the lockwatch series keeps ~4 Hz per lock
# (cycle events are never throttled)
_SAMPLE_EVERY_S = 0.25
# wait above this counts the acquire as contended (and samples it)
_CONTENDED_S = 0.001
# bounded reservoir per lock for the p95s
_RESERVOIR = 512

# the witness event counter: the disabled-mode test proves zero
# overhead by this staying 0 (no wrapper ever constructed or hit)
_EVENTS = 0

_STATE_LOCK = threading.Lock()
_EDGES: dict = {}       # (outer, inner) -> count
_CYCLES: list = []      # [{"locks": [...], "thread": name}]
_STATS: dict = {}       # label -> _LockStats
_TLS = threading.local()


class LockOrderViolation(AssertionError):
    """Two locks were observed taken in both orders — a potential
    deadlock. Raised at the acquire completing the cycle (strict
    mode, the default when the witness is on)."""


class _LockStats:
    __slots__ = ("acquires", "contended", "waits", "holds",
                 "hold_max", "wait_max", "last_sample")

    def __init__(self):
        self.acquires = 0
        self.contended = 0
        self.waits = deque(maxlen=_RESERVOIR)
        self.holds = deque(maxlen=_RESERVOIR)
        self.hold_max = 0.0
        self.wait_max = 0.0
        self.last_sample = 0.0


def enabled() -> bool:
    return os.environ.get(ENV, "") not in ("", "0")


def strict() -> bool:
    return os.environ.get(STRICT_ENV, "") not in ("0",)


def lock(label: str):
    """A mutex for `label`: plain `threading.Lock()` when the witness
    is off (zero overhead — no wrapper in the path), watched when on."""
    if not enabled():
        return threading.Lock()
    return WatchedLock(threading.Lock(), label)


def rlock(label: str):
    """Reentrant variant of `lock()` (see there)."""
    if not enabled():
        return threading.RLock()
    return WatchedLock(threading.RLock(), label)


# ---------------------------------------------------------------------------
# witness core
# ---------------------------------------------------------------------------

def _held() -> list:
    h = getattr(_TLS, "held", None)
    if h is None:
        h = _TLS.held = []
    return h


def _emit(label: str, event: str, hold_s: float, wait_s: float) -> None:
    try:
        from .. import metrics as _metrics
        mx = _metrics.get_default()
        if mx.enabled:
            mx.series("lockwatch",
                      "witnessed lock acquire/release/cycle samples"
                      ).append({"lock": label, "event": event,
                                "hold_s": round(hold_s, 6),
                                "wait_s": round(wait_s, 6)})
    except Exception:  # noqa: BLE001 — profiling never breaks locking
        pass


def _reachable(graph_from: str, graph_to: str) -> bool:
    """Path graph_from -> ... -> graph_to in _EDGES (caller holds
    _STATE_LOCK)."""
    seen: set = set()
    stack = [graph_from]
    while stack:
        n = stack.pop()
        if n == graph_to:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(b for (a, b) in _EDGES if a == n)
    return False


def _note_acquire(label: str, wait_s: float) -> Optional[dict]:
    """Record one (non-reentrant) acquisition. Returns the cycle dict
    when this acquire closed an order cycle, else None."""
    global _EVENTS
    held = _held()
    for entry in held:
        if entry[0] == label:         # reentrant re-acquire: no edge
            entry[2] += 1
            return None
    cycle = None
    now = time.monotonic()
    with _STATE_LOCK:
        _EVENTS += 1
        st = _STATS.get(label)
        if st is None:
            st = _STATS[label] = _LockStats()
        st.acquires += 1
        st.waits.append(wait_s)
        st.wait_max = max(st.wait_max, wait_s)
        contended = wait_s >= _CONTENDED_S
        if contended:
            st.contended += 1
        for entry in held:
            edge = (entry[0], label)
            if edge not in _EDGES and entry[0] != label \
                    and _reachable(label, entry[0]):
                cycle = {"locks": [label, entry[0]],
                         "edge": list(edge),
                         "thread": threading.current_thread().name}
                _CYCLES.append(cycle)
            _EDGES[edge] = _EDGES.get(edge, 0) + 1
        sample = contended and now - st.last_sample >= _SAMPLE_EVERY_S
        if sample:
            st.last_sample = now
    held.append([label, now, 1])
    if cycle is not None:
        _emit(label, "cycle", 0.0, wait_s)
    elif sample:
        _emit(label, "acquire", 0.0, wait_s)
    return cycle


def _note_release(label: str, full: bool = False) -> None:
    """Record one release (`full` pops every recursion level — the
    Condition `_release_save` path)."""
    global _EVENTS
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] != label:
            continue
        held[i][2] -= 1
        if full:
            held[i][2] = 0
        if held[i][2] > 0:
            return
        _, t0, _n = held.pop(i)
        now = time.monotonic()
        hold_s = now - t0
        with _STATE_LOCK:
            _EVENTS += 1
            st = _STATS.get(label)
            if st is None:
                st = _STATS[label] = _LockStats()
            st.holds.append(hold_s)
            st.hold_max = max(st.hold_max, hold_s)
            sample = now - st.last_sample >= _SAMPLE_EVERY_S
            if sample:
                st.last_sample = now
        if sample:
            _emit(label, "release", hold_s, 0.0)
        return


class WatchedLock:
    """An instrumented Lock/RLock (see module docstring). Only exists
    on the lock path when JEPSEN_TPU_LOCKWATCH is set."""

    __slots__ = ("_inner", "label")

    def __init__(self, inner, label: str):
        self._inner = inner
        self.label = str(label)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        t0 = time.monotonic()
        got = self._inner.acquire(blocking, timeout)
        if not got:
            return got
        cycle = _note_acquire(self.label, time.monotonic() - t0)
        if cycle is not None and strict():
            _note_release(self.label)
            self._inner.release()
            raise LockOrderViolation(
                f"lock-order cycle: acquiring {self.label!r} while "
                f"holding {cycle['edge'][0]!r}, but the witness has "
                f"already seen {self.label!r} held before "
                f"{cycle['edge'][0]!r} — two threads on opposite "
                "orders deadlock (set JEPSEN_TPU_LOCKWATCH_STRICT=0 "
                "to record without raising)")
        return got

    def release(self) -> None:
        _note_release(self.label)
        self._inner.release()

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def locked(self) -> bool:
        inner = self._inner
        if hasattr(inner, "locked"):
            return inner.locked()
        return bool(inner._is_owned())

    # -- Condition protocol (threading.Condition(lock) support) -------
    def _release_save(self):
        _note_release(self.label, full=True)
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        t0 = time.monotonic()
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        _note_acquire(self.label, time.monotonic() - t0)

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        # plain Lock: mirror Condition's own probe
        if inner.acquire(False):
            inner.release()
            return False
        return True


# ---------------------------------------------------------------------------
# reporting / banking
# ---------------------------------------------------------------------------

def _p95(samples) -> Optional[float]:
    vals = sorted(samples)
    if not vals:
        return None
    return round(vals[min(len(vals) - 1,
                          int(0.95 * (len(vals) - 1) + 0.5))], 6)


def report() -> dict:
    """The witness state: per-lock stats, the acquisition-order edge
    list, and every observed cycle."""
    with _STATE_LOCK:
        locks = {}
        for label, st in sorted(_STATS.items()):
            locks[label] = {
                "acquires": st.acquires,
                "contended": st.contended,
                "wait_p95_s": _p95(st.waits),
                "wait_max_s": round(st.wait_max, 6),
                "hold_p95_s": _p95(st.holds),
                "hold_max_s": round(st.hold_max, 6),
            }
        return {"enabled": enabled(),
                "locks": locks,
                "edges": sorted([list(e) for e in _EDGES]),
                "cycles": [dict(c) for c in _CYCLES],
                "cycle": bool(_CYCLES)}


def bank(led=None) -> Optional[str]:
    """One `kind="lockwatch"` ledger record of the current witness
    state (schema checked by scripts/telemetry_lint.py). Returns the
    record id (None when the witness is off or the ledger declines)."""
    if not enabled():
        return None
    if led is None:
        from .. import ledger as ledger_mod
        led = ledger_mod.get_default()
    rep = report()
    if not rep["locks"]:
        return None
    try:
        return led.record({
            "kind": "lockwatch",
            "name": f"lockwatch:{os.getpid()}",
            "edges": rep["edges"],
            "cycle": rep["cycle"],
            "cycles": rep["cycles"],
            "locks": rep["locks"]})
    except Exception:  # noqa: BLE001 — witness banking never fails
        return None   # the run


def reset() -> None:
    """Clear the process-wide witness state (tests)."""
    global _EVENTS
    with _STATE_LOCK:
        _EDGES.clear()
        _CYCLES.clear()
        _STATS.clear()
        _EVENTS = 0
    _TLS.held = []


def events() -> int:
    """Witness events recorded so far (the disabled-mode zero-overhead
    proof reads this)."""
    return _EVENTS
