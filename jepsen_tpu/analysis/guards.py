"""Runtime compile/transfer guards for checker runs.

The static linter (`jaxlint`) catches footguns it can see; this module
catches the ones only the runtime reveals — a shape-bucketing bug that
recompiles a "same-shape" re-check, a poll loop that starts syncing
per round. A `CompileGuard` wraps any block of checker work and
counts:

  * **compilations** — every XLA backend compile, observed through
    `jax.monitoring`'s `/jax/core/compile/backend_compile_duration`
    event (cache hits fire nothing, so the count IS the cache-miss
    count);
  * **host<->device transfers** — the framework's own transfer points
    (`ops/wgl.py`'s const upload + per-chunk poll, `elle/tpu.py`'s
    kernel I/O) report through `note_transfer()`. This is cooperative
    by design: `jax.transfer_guard` is inert on the CPU backend where
    tier-1 runs, while the framework's transfer points are exactly the
    ones with latency budgets (each device->host poll is a ~75 ms
    round-trip on a tunneled v5e).

Budgets are asserted on exit:

    with guards.CompileGuard(max_compiles=0):
        wgl.check(model, history)       # same shape as a prior check
        wgl.check(model, history2)      # must be all cache hits

raises `BudgetExceeded` (an AssertionError) naming the counts. Used
by `tests/test_analysis.py` and opt-in by `bench.py`
(JEPSEN_TPU_BENCH_COMPILE_BUDGET). Zero-cost when no guard is active: the
module keeps a plain list of active guards, and both the monitoring
listener and `note_transfer` return immediately on empty.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# Active guards (a stack: guards may nest). Plain list — appends and
# removals take the module lock; the hot-path emptiness check doesn't.
_ACTIVE: list = []
_LOCK = threading.Lock()
_LISTENER_INSTALLED = False


class BudgetExceeded(AssertionError):
    """A guard's compile/transfer budget was exceeded."""


def _on_duration(name: str, secs: float, **_kw) -> None:
    if name != COMPILE_EVENT or not _ACTIVE:
        return
    for g in list(_ACTIVE):
        g._record_compile(secs)


def _install_listener() -> bool:
    """Register the module's jax.monitoring listener once per process.
    Returns False when jax is unavailable (counts stay zero)."""
    global _LISTENER_INSTALLED
    with _LOCK:
        if _LISTENER_INSTALLED:
            return True
        try:
            import jax.monitoring as _mon
        except Exception:  # noqa: BLE001 — no jax: guard is inert
            return False
        _mon.register_event_duration_secs_listener(_on_duration)
        _LISTENER_INSTALLED = True
        return True


def note_transfer(direction: str, nbytes: int = 0,
                  what: str = "") -> None:
    """Report one host<->device transfer from an instrumented
    framework transfer point. `direction` is "h2d" or "d2h". No-op
    (one truthiness check) when no guard is active."""
    if not _ACTIVE:
        return
    for g in list(_ACTIVE):
        g._record_transfer(direction, nbytes, what)


class CompileGuard:
    """Context manager counting compiles + framework transfers, with
    budget asserts on exit (see module docstring).

    Counts are process-global while active (the competition checker
    runs engines in threads; their compiles all count). `report()`
    returns the counts as a plain dict; on exit with budgets exceeded
    (and no in-flight exception) raises BudgetExceeded."""

    def __init__(self, max_compiles: Optional[int] = None,
                 max_d2h: Optional[int] = None,
                 max_h2d: Optional[int] = None,
                 name: str = "compile-guard"):
        self.name = name
        self.max_compiles = max_compiles
        self.max_d2h = max_d2h
        self.max_h2d = max_h2d
        self.compiles = 0
        self.compile_s = 0.0
        self.d2h = 0
        self.h2d = 0
        self.d2h_bytes = 0
        self.h2d_bytes = 0
        self.active = False
        self._t0: Optional[float] = None
        self._lock = threading.Lock()

    # -- recording (called from the module hooks) ---------------------
    def _record_compile(self, secs: float) -> None:
        with self._lock:
            self.compiles += 1
            self.compile_s += float(secs)

    def _record_transfer(self, direction: str, nbytes: int,
                         _what: str) -> None:
        with self._lock:
            if direction == "d2h":
                self.d2h += 1
                self.d2h_bytes += int(nbytes)
            else:
                self.h2d += 1
                self.h2d_bytes += int(nbytes)

    # -- context protocol ---------------------------------------------
    def __enter__(self) -> "CompileGuard":
        _install_listener()
        self._t0 = time.monotonic()
        self.active = True
        with _LOCK:
            _ACTIVE.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        with _LOCK:
            try:
                _ACTIVE.remove(self)
            except ValueError:
                pass
        self.active = False
        if exc_type is not None:
            return  # don't mask the in-flight exception
        over = self.over_budget()
        if over:
            raise BudgetExceeded(
                f"{self.name}: {'; '.join(over)} — report: "
                f"{self.report()}")

    def over_budget(self) -> list:
        """The list of violated budgets (empty when within budget)."""
        over = []
        if self.max_compiles is not None \
                and self.compiles > self.max_compiles:
            over.append(f"{self.compiles} compiles > budget "
                        f"{self.max_compiles}")
        if self.max_d2h is not None and self.d2h > self.max_d2h:
            over.append(f"{self.d2h} device->host transfers > budget "
                        f"{self.max_d2h}")
        if self.max_h2d is not None and self.h2d > self.max_h2d:
            over.append(f"{self.h2d} host->device transfers > budget "
                        f"{self.max_h2d}")
        return over

    def report(self) -> dict:
        return {
            "name": self.name,
            "compiles": self.compiles,
            "compile_s": round(self.compile_s, 4),
            "d2h": self.d2h, "d2h_bytes": self.d2h_bytes,
            "h2d": self.h2d, "h2d_bytes": self.h2d_bytes,
            "wall_s": (round(time.monotonic() - self._t0, 4)
                       if self._t0 is not None else None),
            "budgets": {"compiles": self.max_compiles,
                        "d2h": self.max_d2h, "h2d": self.max_h2d},
        }


def assert_no_recompile(name: str = "no-recompile") -> CompileGuard:
    """Sugar for the common budget: a block that must be all jit
    cache hits (e.g. re-checking a same-shape history)."""
    return CompileGuard(max_compiles=0, name=name)
