"""Thread-safety lint for the service host plane (AST-based, stdlib).

The checker kernels are guarded by three static planes (history_lint,
jaxlint, preflight) — but the part of the tree that now carries
production semantics is *threaded host code*: the service worker pool,
the autopilot supervisor, the watchdog monitor, replica heartbeats,
SSE streams, the streamed fan-out workers. Past concurrency fixes
("all `_stats` mutations lock-protected", "heartbeat/terminal
ordering") were each found by hand. This linter mechanizes them the
way Eraser's lockset analysis and FreeBSD's witness checker do, as
static rules:

  T001 unlocked-shared-write   a `self.X` written both from a
                               thread context (a method reachable
                               from `Thread(target=...)` / `Timer` /
                               a callback, transitively) and from
                               other methods, with at least one of
                               those writes not under `with
                               self._lock` — the Eraser condition
  T002 lock-order-inversion    the per-module lock-acquisition graph
                               (built from nested `with` blocks,
                               Condition aliases resolved to their
                               underlying lock) contains a cycle —
                               two code paths that can deadlock
  T003 blocking-call-under-lock  `time.sleep`, a thread `.join`, a
                               socket/subprocess call, a ledger
                               `.record`, an Event `.wait`, or an
                               XLA compile inside a `with lock:`
                               body — every other thread on that
                               lock stalls for the full blocking
                               call (`Condition.wait` is exempt: it
                               releases the lock)
  T004 unjoined-thread         `threading.Thread(...)` started with
                               no `daemon=` flag and no reachable
                               `.join()` / `.daemon =` / return path
                               — a leaked non-daemon thread blocks
                               interpreter exit
  T005 check-then-act          an unlocked `if` on shared state
                               (membership, `.is_set()`, `is None`)
                               whose body then writes that same
                               state unlocked — the window between
                               check and act races (double-checked
                               locking, where the WRITE is locked,
                               passes)
  T006 global-write-in-thread  a module-level global rebound or
                               mutated from a thread-context
                               function without a module lock
  T007 signature-toctou        `index_signature()` computed AFTER
                               the data read it is meant to version
                               — a concurrent append between read
                               and signature aliases the stale read
                               under the fresh signature forever
                               (signature-before-read heals next
                               poll; this order never does)
  T008 loop-capture-in-thread  a closure created inside a loop,
                               referencing the loop variable, handed
                               to a thread/timer/executor — every
                               thread sees the LAST iteration's
                               value (bind it as a default arg)

Scope notes. "Thread context" is resolved per module to a fixpoint:
methods/functions referenced by `Thread(target=...)`,
`threading.Timer`, or `target=`/`callback=` keyword arguments, plus
everything they call through `self.` or bare names. A write counts
as locked when an enclosing `with` acquires a lock-ish expression
(name ending in lock/mutex/cv/cond, a class attribute assigned from
`threading.Lock/RLock/Condition/Semaphore` or
`analysis.lockwatch.lock/rlock`), or when the enclosing method's
name ends in `_locked` (the tree's "caller holds the lock"
convention). `Condition(self._lock)` aliases to the underlying lock,
so `with self._cv:` guards the same state as `with self._lock:` and
never produces a false T002 cycle against it.

Allowlist: `# threadlint: ok(T001)` (or `ok(T001,T005)`, or a bare
`# threadlint: ok`) on the flagged line or the line directly above
suppresses the finding; a file-level `# threadlint: ok-file(T004)`
within the first 20 lines suppresses named rules module-wide (never
a bare form). Every allowlist is a reviewable decision with a
written justification; CI keeps the tree clean
(`scripts/thread_lint.py`). Runtime twin: `analysis.lockwatch`, the
witness that observes the ACTUAL acquisition order under
JEPSEN_TPU_LOCKWATCH=1.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Optional

RULES = {
    "T001": "unlocked-shared-write",
    "T002": "lock-order-inversion",
    "T003": "blocking-call-under-lock",
    "T004": "unjoined-thread",
    "T005": "check-then-act",
    "T006": "global-write-in-thread",
    "T007": "signature-toctou",
    "T008": "loop-capture-in-thread",
}

_ALLOW_RE = re.compile(r"#\s*threadlint:\s*ok(?:\(([^)]*)\))?")
_ALLOW_FILE_RE = re.compile(r"#\s*threadlint:\s*ok-file\(([^)]*)\)")
# ok-file must sit in the module header, a visible reviewable banner
_ALLOW_FILE_SCAN_LINES = 20

# lock-ish name suffixes: the last dotted segment (underscores
# stripped) must END in one of these for a `with X:` to count as a
# lock acquisition — `self._lock`, `qlock`, `_LOCK`, `self._ev_cv`
_LOCK_SUFFIXES = ("lock", "mutex", "cv", "cond", "condition")

# threading constructors whose result is a lock-ish attribute; the
# lockwatch factories keep instrumented trees recognizable
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore", "lock", "rlock"}
_EVENT_CTORS = {"Event"}

# container-mutation method names that count as writes for T001/T005
_MUTATORS = {"append", "appendleft", "add", "update", "pop", "popleft",
             "clear", "remove", "discard", "extend", "insert",
             "setdefault", "set"}

# receivers whose .join is a thread join, not str.join
_JOINISH_RE = re.compile(
    r"(thread|worker|monitor|proc|^t\d*$|^th\d*$)", re.IGNORECASE)
# receivers whose .wait blocks while holding the lock (Events); cv /
# cond receivers are exempt — Condition.wait releases the lock
_EVENTISH_RE = re.compile(r"(ev|event|stop|done|ready)$", re.IGNORECASE)
_LEDGERISH_RE = re.compile(r"(led|ledger)", re.IGNORECASE)

_THREAD_HANDOFF_FUNCS = {"Thread", "Timer", "submit", "call_later",
                         "spawn", "start_new_thread"}
_THREAD_HANDOFF_KWARGS = {"target", "callback", "on_done", "on_event"}


@dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{RULES[self.rule]}] {self.message}")


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------

def _walk_own(fn_node):
    """Walk a function body WITHOUT descending into nested defs or
    lambdas — each is its own analysis unit with its own thread/lock
    context."""
    body = fn_node.body if isinstance(fn_node.body, list) \
        else [fn_node.body]
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


def _dotted(node) -> Optional[str]:
    """`self._lock` -> "self._lock"; `mod.obj.qlock` -> dotted string;
    None for anything that is not a plain dotted name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_seg(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1].lstrip("_").lower()


def _is_lockish_name(dotted: Optional[str]) -> bool:
    if not dotted:
        return False
    seg = _last_seg(dotted)
    return any(seg == s or seg.endswith(s) for s in _LOCK_SUFFIXES)


def _ctor_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _self_attr(node) -> Optional[str]:
    """`self.X` -> "X" (one level only)."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _param_names(node) -> set:
    a = node.args
    names = [x.arg for x in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


# ---------------------------------------------------------------------------
# module index: analysis units, classes, thread-context fixpoint
# ---------------------------------------------------------------------------

class _Unit:
    """One analysis unit: a def (method, function, or nested def)."""

    __slots__ = ("node", "name", "cls", "parents", "thread_ctx")

    def __init__(self, node, name, cls, parents):
        self.node = node
        self.name = name
        self.cls = cls              # owning _ClassInfo or None
        self.parents = parents      # enclosing unit chain
        self.thread_ctx = False


class _ClassInfo:
    __slots__ = ("node", "name", "lock_attrs", "aliases", "event_attrs",
                 "methods", "spawns_threads")

    def __init__(self, node):
        self.node = node
        self.name = node.name
        self.lock_attrs: set = set()
        self.aliases: dict = {}     # cv attr -> underlying lock attr
        self.event_attrs: set = set()
        self.methods: dict = {}     # name -> _Unit
        self.spawns_threads = False


class _Index(ast.NodeVisitor):
    def __init__(self):
        self.units: list = []
        self.by_name: dict = {}       # bare name -> [_Unit]
        self.classes: list = []
        self.module_globals: set = set()
        self._cls_stack: list = []
        self._unit_stack: list = []

    def visit_Module(self, node):
        for stmt in node.body:
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        self.module_globals.add(tgt.id)
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                self.module_globals.add(stmt.target.id)
        self.generic_visit(node)

    def visit_ClassDef(self, node):
        ci = _ClassInfo(node)
        self.classes.append(ci)
        self._cls_stack.append(ci)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _enter(self, node, name):
        cls = self._cls_stack[-1] if self._cls_stack else None
        u = _Unit(node, name, cls, list(self._unit_stack))
        self.units.append(u)
        self.by_name.setdefault(name, []).append(u)
        # a def directly in the class body is a method
        if cls is not None and not self._unit_stack:
            cls.methods[name] = u
        self._unit_stack.append(u)
        self.generic_visit(node)
        self._unit_stack.pop()

    def visit_FunctionDef(self, node):
        self._enter(node, node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._enter(node, "<lambda>")

    def visit_Call(self, node):
        cls = self._cls_stack[-1] if self._cls_stack else None
        name = _ctor_name(node)
        if name in ("Thread", "Timer") and cls is not None:
            cls.spawns_threads = True
        # lock/cv/event attribute discovery: self.X = Lock()/...
        self.generic_visit(node)

    def visit_Assign(self, node):
        cls = self._cls_stack[-1] if self._cls_stack else None
        if cls is not None and isinstance(node.value, ast.Call):
            ctor = _ctor_name(node.value)
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                if ctor in _LOCK_CTORS:
                    cls.lock_attrs.add(attr)
                    if ctor == "Condition" and node.value.args:
                        under = _self_attr(node.value.args[0])
                        if under is not None:
                            cls.aliases[attr] = under
                elif ctor in _EVENT_CTORS:
                    cls.event_attrs.add(attr)
        self.generic_visit(node)


def _thread_handoff_targets(tree) -> list:
    """AST nodes handed to a thread/timer/executor anywhere in the
    module: `Thread(target=X)`, `Timer(t, X)`, `submit(X, ...)`,
    `callback=X` — the thread-context seeds."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _ctor_name(node)
        if fname in _THREAD_HANDOFF_FUNCS:
            if fname == "Timer" and len(node.args) >= 2:
                out.append(node.args[1])
            if fname in ("submit", "spawn", "start_new_thread") \
                    and node.args:
                out.append(node.args[0])
        for kw in node.keywords:
            if kw.arg in _THREAD_HANDOFF_KWARGS:
                out.append(kw.value)
    return out


def _resolve_thread_ctx(idx: _Index, tree) -> None:
    """Mark thread-context units to a fixpoint: handoff seeds, then
    everything they call via `self.m()` or bare `f()`."""
    seeds: list = []
    for ref in _thread_handoff_targets(tree):
        attr = _self_attr(ref)
        if attr is not None:
            for cls in idx.classes:
                if attr in cls.methods:
                    seeds.append(cls.methods[attr])
        elif isinstance(ref, ast.Name):
            seeds.extend(idx.by_name.get(ref.id, []))
        elif isinstance(ref, ast.Lambda):
            for u in idx.units:
                if u.node is ref:
                    seeds.append(u)

    work = list(seeds)
    while work:
        u = work.pop()
        if u.thread_ctx:
            continue
        u.thread_ctx = True
        for sub in _walk_own(u.node):
            if not isinstance(sub, ast.Call):
                continue
            attr = _self_attr(sub.func)
            if attr is not None and u.cls is not None \
                    and attr in u.cls.methods:
                work.append(u.cls.methods[attr])
            elif isinstance(sub.func, ast.Name):
                work.extend(idx.by_name.get(sub.func.id, []))
        # nested defs inherit the thread context of their parent
        # (they run on the same thread unless handed off again)
        for other in idx.units:
            if other.parents and other.parents[-1] is u:
                work.append(other)


# ---------------------------------------------------------------------------
# per-unit traversal with a held-locks stack
# ---------------------------------------------------------------------------

def _canonical_lock(expr, cls: Optional[_ClassInfo]) -> Optional[str]:
    """The canonical dotted name a `with` item acquires, or None when
    it is not a lock acquisition. Condition attrs alias to their
    underlying lock."""
    if isinstance(expr, ast.Call):
        return None  # `with Lock():` — a fresh lock guards nothing
    dotted = _dotted(expr)
    if dotted is None:
        return None
    attr = _self_attr(expr)
    if cls is not None and attr is not None:
        if attr in cls.aliases:
            return f"self.{cls.aliases[attr]}"
        if attr in cls.lock_attrs:
            return dotted
    if _is_lockish_name(dotted):
        if cls is not None and attr is not None \
                and attr in cls.aliases:
            return f"self.{cls.aliases[attr]}"
        return dotted
    return None


class _Site:
    """One interesting site observed during a unit traversal."""

    __slots__ = ("node", "held", "kind", "extra")

    def __init__(self, node, held, kind, extra=None):
        self.node = node
        self.held = tuple(held)     # lock names held at this site
        self.kind = kind
        self.extra = extra


def _traverse(unit: _Unit, on_site, lock_edges: dict) -> None:
    """Statement-ordered walk of one unit, maintaining the held-lock
    stack. `on_site(site)` receives writes/reads/ifs/calls;
    `lock_edges[(outer, inner)] = node` accumulates the acquisition
    graph."""
    held: list = []
    cls = unit.cls

    def visit(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                lk = _canonical_lock(item.context_expr, cls)
                if lk is not None:
                    for h in held:
                        if h != lk:
                            lock_edges.setdefault((h, lk), node)
                    held.append(lk)
                    acquired.append(lk)
            for stmt in node.body:
                visit(stmt)
            for lk in reversed(acquired):
                held.pop()
            return
        on_site(_Site(node, held, "node"))
        for child in ast.iter_child_nodes(node):
            visit(child)

    body = unit.node.body if isinstance(unit.node.body, list) \
        else [unit.node.body]
    for stmt in body:
        visit(stmt)


# ---------------------------------------------------------------------------
# write/read collection for T001 / T005 / T006
# ---------------------------------------------------------------------------

def _self_write_target(node) -> Optional[str]:
    """The self attribute a statement/expression writes, if any."""
    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            attr = _self_attr(tgt)
            if attr is not None:
                return attr
            if isinstance(tgt, ast.Subscript):
                attr = _self_attr(tgt.value)
                if attr is not None:
                    return attr
    elif isinstance(node, ast.AugAssign):
        attr = _self_attr(node.target)
        if attr is not None:
            return attr
        if isinstance(node.target, ast.Subscript):
            return _self_attr(node.target.value)
    elif isinstance(node, ast.Delete):
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                attr = _self_attr(tgt.value)
                if attr is not None:
                    return attr
    elif isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _MUTATORS:
        return _self_attr(node.func.value)
    return None


def _global_write_target(node, module_globals: set,
                         local_names: set) -> Optional[str]:
    """The module global a statement rebinds or mutates, if any."""
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        tgts = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for tgt in tgts:
            if isinstance(tgt, ast.Subscript) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id in module_globals \
                    and tgt.value.id not in local_names:
                return tgt.value.id
    if isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _MUTATORS \
            and isinstance(node.func.value, ast.Name) \
            and node.func.value.id in module_globals \
            and node.func.value.id not in local_names:
        return node.func.value.id
    return None


# ---------------------------------------------------------------------------
# the linter
# ---------------------------------------------------------------------------

def lint_source(src: str, path: str = "<string>") -> list:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, 0, "T001",
                        f"syntax error prevents linting: {e.msg}")]
    idx = _Index()
    idx.visit(tree)
    _resolve_thread_ctx(idx, tree)
    findings: list = []

    def add(node, rule, msg):
        findings.append(Finding(path, getattr(node, "lineno", 0),
                                getattr(node, "col_offset", 0),
                                rule, msg))

    # per-class write ledgers for T001:
    #   writes[cls][field] = [(unit, node, locked, thread_ctx)]
    writes: dict = {}
    lock_edges: dict = {}     # (outer, inner) -> first with-node

    for unit in idx.units:
        cls = unit.cls
        in_init = unit.name == "__init__" or any(
            p.name == "__init__" for p in unit.parents)
        held_locked_method = unit.name.endswith("_locked") or any(
            p.name.endswith("_locked") for p in unit.parents)
        local_names = _param_names(unit.node) if not isinstance(
            unit.node, ast.Lambda) else set()
        for sub in _walk_own(unit.node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                tgts = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                for tgt in tgts:
                    for nm in ast.walk(tgt):
                        if isinstance(nm, ast.Name):
                            local_names.add(nm.id)
        has_global_decl = {
            name for sub in _walk_own(unit.node)
            if isinstance(sub, ast.Global) for name in sub.names}

        sites: list = []
        _traverse(unit, sites.append, lock_edges)

        for site in sites:
            node = site.node
            locked = bool(site.held) or held_locked_method
            # ---- write collection (T001 / T006) --------------------
            wt = _self_write_target(node)
            if wt is not None and cls is not None and not in_init \
                    and wt not in cls.lock_attrs \
                    and wt not in cls.aliases:
                # Event .set()/.clear() are internally synchronized
                is_event_mut = (isinstance(node, ast.Call)
                                and wt in cls.event_attrs)
                if not is_event_mut:
                    writes.setdefault(cls.name, {}).setdefault(
                        wt, []).append(
                        (unit, node, locked, unit.thread_ctx))
            if unit.thread_ctx and not locked:
                gname = None
                if isinstance(node, (ast.Assign, ast.AugAssign)) \
                        and not isinstance(node, ast.AugAssign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name) \
                                and tgt.id in has_global_decl:
                            gname = tgt.id
                if gname is None:
                    gname = _global_write_target(
                        node, idx.module_globals, local_names)
                if gname is not None:
                    add(node, "T006",
                        f"module global `{gname}` mutated from "
                        f"thread context ({unit.name}) without a "
                        "module lock — concurrent threads tear the "
                        "update")

            # ---- T003: blocking call under a held lock -------------
            if site.held and isinstance(node, ast.Call):
                _t003(node, add)

            # ---- T005: check-then-act ------------------------------
            if isinstance(node, ast.If) and not locked \
                    and cls is not None and not in_init \
                    and (cls.spawns_threads or cls.lock_attrs) \
                    and unit.name != "__init__":
                _t005(node, cls, add)

        # ---- T004: threads without daemon/join ---------------------
        _t004(unit, add)

        # ---- T007: signature computed after the read ---------------
        _t007(unit, add)

        # ---- T008: loop-variable capture into a thread -------------
        _t008(unit, add)

    # ---- T001: co-written fields with an unlocked write ------------
    for cls in idx.classes:
        for field, ws in writes.get(cls.name, {}).items():
            thread_ws = [w for w in ws if w[3]]
            other_ws = [w for w in ws if not w[3]]
            if not thread_ws or not other_ws:
                continue
            unlocked = [w for w in ws if not w[2]]
            if not unlocked:
                continue
            t_names = sorted({w[0].name for w in thread_ws})
            o_names = sorted({w[0].name for w in other_ws})
            for unit, node, _lk, t_ctx in unlocked:
                where = "thread context" if t_ctx else "caller context"
                add(node, "T001",
                    f"self.{field} written here ({unit.name}, "
                    f"{where}) without holding the class lock — "
                    f"also written from thread context {t_names} "
                    f"and caller context {o_names}; one side "
                    "unlocked is the Eraser race condition")

    # ---- T002: cycles in the acquisition graph ---------------------
    _t002(lock_edges, add)

    seen: set = set()
    uniq: list = []
    for f in findings:
        k = (f.path, f.line, f.col, f.rule)
        if k not in seen:
            seen.add(k)
            uniq.append(f)
    return _apply_allowlist(uniq, src)


# ---------------------------------------------------------------------------
# individual rule bodies
# ---------------------------------------------------------------------------

def _t002(lock_edges: dict, add) -> None:
    graph: dict = {}
    for (a, b) in lock_edges:
        graph.setdefault(a, set()).add(b)

    def reachable(frm: str, to: str) -> bool:
        seen, stack = set(), [frm]
        while stack:
            n = stack.pop()
            if n == to:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(graph.get(n, ()))
        return False

    for (a, b), node in sorted(lock_edges.items(),
                               key=lambda kv: kv[1].lineno):
        if a != b and reachable(b, a):
            add(node, "T002",
                f"lock-order inversion: `{a}` is acquired before "
                f"`{b}` here, but another path acquires `{b}` "
                f"before `{a}` — two threads on opposite paths "
                "deadlock; pick ONE global order")


def _t003(node: ast.Call, add) -> None:
    f = node.func
    dotted = _dotted(f)
    if isinstance(f, ast.Attribute):
        recv = _dotted(f.value) or ""
        seg = _last_seg(recv) if recv else ""
        if f.attr == "sleep" and seg == "time":
            add(node, "T003",
                "time.sleep under a held lock stalls every thread "
                "waiting on that lock for the full sleep — sleep "
                "outside, or use Condition.wait (which releases)")
        elif f.attr == "join" and not isinstance(f.value,
                                                 ast.Constant) \
                and (_JOINISH_RE.search(seg)
                     or _JOINISH_RE.search(recv)):
            add(node, "T003",
                f"{recv}.join under a held lock: if the joined "
                "thread needs this lock to finish, this is a "
                "deadlock; join after releasing")
        elif f.attr == "wait" and _EVENTISH_RE.search(seg) \
                and not _is_lockish_name(recv):
            add(node, "T003",
                f"{recv}.wait under a held lock blocks while "
                "HOLDING it (Event.wait does not release, unlike "
                "Condition.wait) — waiters that need the lock to "
                "set the event deadlock")
        elif f.attr in ("record", "record_result") \
                and _LEDGERISH_RE.search(seg):
            add(node, "T003",
                f"ledger {f.attr} under a held lock: the append "
                "takes an exclusive flock + fsync-ordered rename — "
                "every thread on this lock stalls behind disk; "
                "bank outside the critical section")
        elif f.attr in ("recv", "accept", "connect", "urlopen"):
            add(node, "T003",
                f"socket/HTTP {f.attr} under a held lock blocks "
                "the lock on network latency — move I/O outside")
        elif "compile" in f.attr.lower() \
                or "precompile" in f.attr.lower():
            add(node, "T003",
                f"{f.attr} under a held lock: an XLA compile is "
                "seconds-long — warm outside the lock and publish "
                "the result under it")
    elif isinstance(f, ast.Name):
        if f.id == "sleep":
            add(node, "T003",
                "sleep under a held lock stalls every thread "
                "waiting on that lock for the full sleep")
        elif dotted and "subprocess" in dotted:
            add(node, "T003",
                "subprocess call under a held lock blocks the lock "
                "on the child process")


def _t005(node: ast.If, cls: _ClassInfo, add) -> None:
    """Unlocked `if <check on self.X>` whose body writes self.X
    unlocked. The body scan tracks nested `with` locks so
    double-checked locking passes."""
    checked: set = set()
    for sub in ast.walk(node.test):
        if isinstance(sub, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn, ast.Is, ast.IsNot))
                for op in sub.ops):
            for part in [sub.left] + list(sub.comparators):
                attr = _self_attr(part)
                if attr is not None:
                    checked.add(attr)
        elif isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in ("is_set", "get", "__contains__"):
            attr = _self_attr(sub.func.value)
            if attr is not None:
                checked.add(attr)
    checked -= cls.lock_attrs
    checked -= set(cls.aliases)
    if not checked:
        return

    def body_writes(stmts, held: bool):
        stack = list(stmts)
        while stack:
            sub = stack.pop()
            if isinstance(sub, ast.With):
                inner_held = held or any(
                    _canonical_lock(i.context_expr, cls)
                    for i in sub.items)
                yield from body_writes(sub.body, inner_held)
                continue  # don't re-walk the with body unlocked
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            wt = _self_write_target(sub)
            if wt in checked and not held:
                # Event.set()/clear() on a checked Event attr is
                # still a lost-update window: check+act together
                yield sub, wt
            stack.extend(ast.iter_child_nodes(sub))

    hits = list(body_writes(node.body, False))
    for sub, wt in hits[:1]:
        add(node, "T005",
            f"check-then-act on self.{wt}: the test and the write "
            "in its body both run unlocked — another thread can "
            "interleave between them (take the lock around both, "
            "or re-check under the lock)")


def _t004(unit: _Unit, add) -> None:
    own = list(_walk_own(unit.node))
    src_has_join = any(
        isinstance(s, ast.Attribute) and s.attr == "join"
        for s in own)
    src_sets_daemon = any(
        isinstance(s, ast.Assign) and any(
            isinstance(t, ast.Attribute) and t.attr == "daemon"
            for t in s.targets)
        for s in own)
    has_return = any(isinstance(s, ast.Return) and s.value is not None
                     for s in own)
    for sub in own:
        if not isinstance(sub, ast.Call):
            continue
        if _ctor_name(sub) != "Thread":
            continue
        if any(kw.arg == "daemon" for kw in sub.keywords):
            continue
        if src_has_join or src_sets_daemon or has_return:
            continue
        add(sub, "T004",
            "Thread created without daemon= and with no join / "
            ".daemon assignment / return in this function — a "
            "leaked non-daemon thread blocks interpreter exit and "
            "is unstoppable; pass daemon=True or keep a join path")


def _t007(unit: _Unit, add) -> None:
    first_read_line = None
    for sub in _walk_own(unit.node):
        if not isinstance(sub, ast.Call) \
                or not isinstance(sub.func, ast.Attribute):
            continue
        recv = _dotted(sub.func.value) or ""
        if sub.func.attr in ("query", "records") \
                and _LEDGERISH_RE.search(_last_seg(recv) or recv):
            ln = sub.lineno
            if first_read_line is None or ln < first_read_line:
                first_read_line = ln
    if first_read_line is None:
        return
    for sub in _walk_own(unit.node):
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr == "index_signature" \
                and sub.lineno > first_read_line:
            add(sub, "T007",
                "index_signature() computed AFTER the data read it "
                "versions — an append landing between read and "
                "signature aliases the stale read under the fresh "
                "signature forever; compute the signature BEFORE "
                "reading (a stale signature merely refreshes next "
                "poll)")


def _t008(unit: _Unit, add) -> None:
    for loop in _walk_own(unit.node):
        if not isinstance(loop, (ast.For,)):
            continue
        loop_vars = {n.id for n in ast.walk(loop.target)
                     if isinstance(n, ast.Name)}
        for sub in ast.walk(loop):
            if not isinstance(sub, ast.Call):
                continue
            handoff_args = []
            if _ctor_name(sub) in _THREAD_HANDOFF_FUNCS:
                handoff_args.extend(sub.args)
            handoff_args.extend(
                kw.value for kw in sub.keywords
                if kw.arg in _THREAD_HANDOFF_KWARGS)
            for arg in handoff_args:
                if not isinstance(arg, (ast.Lambda, ast.Name)):
                    continue
                closure = None
                if isinstance(arg, ast.Lambda):
                    closure = arg
                else:
                    for d in ast.walk(loop):
                        if isinstance(d, ast.FunctionDef) \
                                and d.name == arg.id:
                            closure = d
                if closure is None:
                    continue
                bound = _param_names(closure)
                free_loop = {
                    n.id for n in ast.walk(
                        closure.body if isinstance(closure,
                                                   ast.Lambda)
                        else closure)
                    if isinstance(n, ast.Name)
                    and n.id in loop_vars and n.id not in bound}
                if free_loop:
                    add(arg, "T008",
                        f"closure captures loop variable(s) "
                        f"{sorted(free_loop)} and is handed to a "
                        "thread — every thread sees the LAST "
                        "iteration's value; bind it as a default "
                        "argument (lambda x=x: ...) or pass via "
                        "args=")


# ---------------------------------------------------------------------------
# allowlist + file plumbing (same contract as jaxlint)
# ---------------------------------------------------------------------------

def _apply_allowlist(findings: list, src: str) -> list:
    lines = src.splitlines()

    file_rules: set = set()
    for ln in lines[:_ALLOW_FILE_SCAN_LINES]:
        m = _ALLOW_FILE_RE.search(ln)
        if m:
            file_rules |= {w.strip() for w in m.group(1).split(",")
                           if w.strip()}

    def allowed(f: Finding) -> bool:
        if f.rule in file_rules:
            return True
        for ln in (f.line, f.line - 1):
            if 1 <= ln <= len(lines):
                m = _ALLOW_RE.search(lines[ln - 1])
                if m:
                    which = m.group(1)
                    if which is None:
                        return True
                    ids = {w.strip() for w in which.split(",")}
                    if f.rule in ids:
                        return True
        return False

    out = [f for f in findings if not allowed(f)]
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def lint_file(path: str) -> list:
    with open(path) as fh:
        return lint_source(fh.read(), path)


def lint_paths(paths) -> list:
    findings: list = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                if "__pycache__" in root:
                    continue
                for name in sorted(files):
                    if name.endswith(".py"):
                        findings += lint_file(os.path.join(root, name))
        elif p.endswith(".py"):
            findings += lint_file(p)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
