"""Preflight: static kernel-plan & capacity analyzer with admission verdicts.

The JVM checkers the reference wraps discover infeasibility by timing
out ("some tests are expensive to check… which requires we verify only
short histories" — jepsen.independent); our device engine used to
discover it the same way, by OOMing or burning device-seconds on a
plan that could never fit (a 100k-txn dense closure is ~3.75 GB of
bitset words before the squaring temporaries, ROADMAP item 3). This
module is the admission-control front door: given (model, encoded
history shapes, backend) it *enumerates without executing* the full
plan a check would take —

  * the adaptive ladder buckets `ops/adapt.LADDER32` / `ladder_for`
    would climb (`wgl.check`'s exact derivation, mirrored here),
  * the wgl32/wgln variant flags (`pack` via `wgl._packable`,
    `compact` via the depth-fused default) the kernel builders would
    pick,
  * the Elle route (host / bf16 / packed / trim) that
    `ops/route.elle_cycle_route` + `elle/tpu._squaring_select` would
    choose —

then costs each plan node via tracing+lowering-only
`jax.stages.Lowered.cost_analysis` (`occupancy.cost_for`, cached per
shape bucket, ZERO backend compiles — the cache keys match the ones
`ops/wgl.py` uses at result time, so the prediction and the executed
check read the same numbers) into a machine-readable plan report with
a verdict:

    feasible              admit as planned
    degrade               admit, but the report's `suggestion` names a
                          cheaper/safer shape (host oracle, adaptive
                          ladder, precompiled warm path, …)
    infeasible            reject statically — no backend compile, no
                          device byte is ever spent

Rule catalog (doc/STATIC_ANALYSIS.md "Plane 3"):

  P001 plan-exceeds-hbm          peak live bytes of a plan node blow
                                 the device memory budget
  P002 closure-over-capacity     a dense Elle closure (bf16/packed/
                                 trim) over its kernel capacity cap
  P003 compile-budget-blown      cold executables exceed the caller's
                                 CompileGuard-style compile budget —
                                 precompile (ops/aot) first
  P004 encoding-overflow-predicted   the WGL encoding would trip an
                                 `EncodingUnsupported` limit (window /
                                 info-cap / state-space) — route to
                                 the host oracle
  P005 padded-waste              predicted frontier/window fill under
                                 the occupancy target — the plan pays
                                 for lanes the wavefront can't use
  P006 route-cost-disagreement   the shape router's engine pick and
                                 the cost model disagree — trust the
                                 cost side and degrade

P001/P002 are *infeasible* (gating); P003-P006 are *degrade*
(advisory). Gates are wired into `checker.Linearizable`, elle
append/wr auto-routing, and both `parallel/batched.py` fan-out paths:
an infeasible request fast-fails as `{"valid?": "unknown", "cause":
"preflight", ...}` exactly like `history_lint`, is recorded as a
`preflight` series point + a `kind="preflight"` ledger record, and
surfaces on `/status.json`'s `preflight` block. The CLI is
`python -m jepsen_tpu preflight`.

This is the feasibility oracle the checker-as-a-service admission
queue (ROADMAP item 1) fronts requests with, and the one the
100k-Elle sharding work (item 3) queries before picking a plan.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

RULES = {
    "P001": "plan-exceeds-hbm",
    "P002": "closure-over-capacity",
    "P003": "compile-budget-blown",
    "P004": "encoding-overflow-predicted",
    "P005": "padded-waste",
    "P006": "route-cost-disagreement",
}

# Rules that reject (verdict "infeasible"); the rest only degrade.
INFEASIBLE_RULES = ("P001", "P002")

# TPU v5e HBM capacity (single chip, spec sheet) — the default device
# memory budget an admitted plan must fit. The cpu tier-1 runs use the
# same figure as a conservative host budget unless overridden: the
# dense-closure blowups this rule exists for are 6-100 GB, far past
# any sane budget either way.
V5E_HBM_CAPACITY_BYTES = 16 * 2 ** 30

# Live-copy multiplier for the dense closure squaring: the reach
# matrix, the einsum product, and the re-binarized result are live at
# once inside the while_loop body (elle/tpu.make_closure_kernel).
CLOSURE_LIVE_FACTOR = 3


def device_memory_budget(platform: Optional[str] = None) -> int:
    """The byte budget a plan's peak live bytes must fit. Precedence:

      1. JEPSEN_TPU_PREFLIGHT_MEM_BUDGET (the operator always wins);
      2. the chip's OWN `bytes_limit` from `Device.memory_stats()`
         when an initialized backend reports one
         (`devices.measured_bytes_limit` — min across local devices,
         init-safe: never triggers or waits on a backend init), so
         admission budgets stop assuming every chip is a v5e;
      3. the v5e spec constant — cpu tier-1 (no allocator stats) and
         planning-before-init land here, a conservative host budget
         either way: the dense-closure blowups P001 exists for are
         6-100 GB, far past any sane budget.
    """
    env = os.environ.get("JEPSEN_TPU_PREFLIGHT_MEM_BUDGET")
    if env:
        return int(float(env))
    try:
        from .. import devices as devices_mod
        measured = devices_mod.measured_bytes_limit()
    except Exception:  # noqa: BLE001 — the budget must never raise
        measured = None
    if measured:
        return int(measured)
    return V5E_HBM_CAPACITY_BYTES


def _compile_budget(explicit: Optional[int]) -> Optional[int]:
    if explicit is not None:
        return int(explicit)
    env = os.environ.get("JEPSEN_TPU_PREFLIGHT_COMPILE_BUDGET")
    return int(env) if env not in (None, "") else None


def _rule(rule: str, message: str, suggestion: Optional[str] = None,
          severity: Optional[str] = None) -> dict:
    return {"rule": rule, "name": RULES[rule],
            "severity": severity or ("infeasible"
                                     if rule in INFEASIBLE_RULES
                                     else "degrade"),
            "message": message, "suggestion": suggestion}


def _verdict(rules: list) -> tuple:
    """(verdict, suggestion) from the fired rules."""
    infeasible = [r for r in rules if r["severity"] == "infeasible"]
    if infeasible:
        return "infeasible", (infeasible[0].get("suggestion")
                              or infeasible[0]["message"])
    degrade = [r for r in rules if r["severity"] == "degrade"]
    if degrade:
        return "degrade", (degrade[0].get("suggestion")
                           or degrade[0]["message"])
    return "feasible", None


def _safe_platform(platform: Optional[str]) -> Optional[str]:
    if platform is not None:
        return platform
    try:
        from ..util import safe_backend
        return safe_backend()
    except Exception:  # noqa: BLE001 — no jax at all: host plans only
        return None


# ---------------------------------------------------------------------------
# WGL: shape probe + plan enumeration
# ---------------------------------------------------------------------------

def _probe_shapes(history) -> dict:
    """The encoding-relevant shapes of a history WITHOUT enumerating
    the model state space (`encode.build_table` is the expensive half
    of `encode`; everything the planner needs — window requirement,
    op/info counts, concurrency depth — comes from the prepared op
    intervals alone). The window math and pad buckets ARE encode's
    (`encode.window_requirement` / `encode._pad_to`), so probe and
    encoder cannot disagree."""
    from ..ops.encode import _pad_to, window_requirement
    from ..ops.linprep import prepare

    ops = prepare(history)
    ok = [o for o in ops if o.ok]
    info = [o for o in ops if not o.ok]
    n, ni = len(ok), len(info)
    inv = np.asarray([o.inv for o in ok], dtype=np.int64)
    ret = np.asarray([min(o.ret, 2 ** 31 - 1) for o in ok],
                     dtype=np.int64)
    w_needed, W = window_requirement(inv, ret)
    return {"n_ok": n, "n_info": ni, "W_raw": w_needed, "W": W,
            "n_pad": _pad_to(n, 64), "ic_pad": _pad_to(ni, 32),
            "S": None, "O": None,
            # every time class `wgl._packable` checks that is knowable
            # without the table: ok inv/ret AND info invocation times
            # (sufminret is bounded by max ret); the table-rows cap is
            # the one residual the `pack_estimated` flag covers
            "times_max": int(max(inv.max() if n else 0,
                                 ret.max() if n else 0,
                                 max((o.inv for o in info),
                                     default=0))),
            "inv": inv, "ret": ret}


def _shapes_from_enc(enc) -> dict:
    n = int(enc.n_ok)
    inv = enc.inv[:n].astype(np.int64)
    ret = enc.ret[:n].astype(np.int64)
    m = 0
    from ..ops.wgl import INF
    for a in (enc.inv, enc.ret, enc.sufminret, enc.inv_info):
        finite = a[a < INF]
        if finite.size:
            m = max(m, int(finite.max()))
    return {"n_ok": n, "n_info": int(enc.n_info),
            "W_raw": int(enc.window_raw), "W": int(enc.window),
            "n_pad": len(enc.inv), "ic_pad": len(enc.inv_info),
            "S": int(enc.table.shape[0]), "O": int(enc.table.shape[1]),
            "times_max": m, "inv": inv, "ret": ret}


def _depth_stats(shapes: dict) -> dict:
    """Mean/p95 pending-op depth — the static wavefront predictor the
    router uses (`ops/route.shape_stats`); the planner reuses it for
    the predicted-fill model behind P005."""
    inv, ret = shapes.get("inv"), shapes.get("ret")
    if inv is None or not len(inv):
        return {"mean_depth": 0.0, "p95_depth": 0}
    order_i = np.sort(inv)
    order_r = np.sort(ret)
    depth = (np.searchsorted(order_i, inv, side="right")
             - np.searchsorted(order_r, inv, side="right"))
    return {"mean_depth": round(float(depth.mean()), 2),
            "p95_depth": int(np.percentile(depth, 95))}


def _node_bytes(K, W_eff, ic_eff, window_lanes, H, B, n_pad) -> int:
    """Peak-live-bytes model for one kernel bucket: memo table (16 B /
    slot) + packed backlog rows + the per-round successor
    intermediates (R rows x packed lanes x ~3 temporaries) + consts.
    One model for both variants — `window_lanes` is the packed window
    word count (1 for wgl32, which always carries exactly one uint32
    window lane; L for wgln). An upper-bound-flavored model, like the
    util-block accounting."""
    lanes = window_lanes + max(1, ic_eff // 32) + 4
    rows = K * (W_eff + ic_eff)
    return int(H * 16 + B * lanes * 4
               + 3 * rows * lanes * 4 + 6 * n_pad * 4)


def _lower_wgl_node(enc, kern: str, *, K, H, B, chunk, probes, W_eff,
                    ic_eff, L, accel, depth, pack):
    """A `jax.stages.Lowered` for one plan node — tracing + lowering
    only, NO backend compile (`occupancy.cost_for`'s contract; the
    CompileGuard proof in tests/test_preflight.py). Uses the SAME
    builders (and their lru caches) the runtime search uses, so a
    later real check over this shape stays warm."""
    import jax

    from ..ops.aot import _wgl_consts_spec

    n_pad = len(enc.inv)
    S, O = enc.table.shape
    if kern == "wgl32":
        from ..ops.wgl32 import compiled_search32
        init_fn, chunk_jit = compiled_search32(
            n_pad=n_pad, ic_pad=ic_eff, S=S, O=O, K=K, H=H, B=B,
            chunk=chunk, probes=probes, W=W_eff, accel=accel,
            depth=depth, pack=pack)
    else:
        from ..ops.wgln import compiled_searchN
        init_fn, chunk_jit = compiled_searchN(
            n_pad=n_pad, ic_pad=ic_eff, S=S, O=O, K=K, H=H, B=B,
            chunk=chunk, probes=probes, W=W_eff, L=L, accel=accel,
            pack=pack)
    consts_spec = _wgl_consts_spec(n_pad, ic_eff, S, O)
    carry_spec = jax.eval_shape(init_fn, 0)
    return chunk_jit.lower(consts_spec, carry_spec)


def plan_wgl(model=None, history=None, *, enc=None,
             platform: Optional[str] = None,
             frontier: Optional[int] = None,
             adaptive: Optional[bool] = None,
             shape_bucket: Optional[dict] = None,
             lower: bool = False,
             lanes: int = 1,
             compile_budget: Optional[int] = None) -> dict:
    """Enumerate the exact plan `ops/wgl.check` would run for this
    history — kernel variant, ladder buckets, capacities, pack bit —
    without executing any of it, and attach the admission rules that
    fire. With `lower=True` each bucket additionally carries the
    compiler's own per-round cost analysis (`cost_for`, cached under
    the runtime's keys; requires a real `enc` or (model, history) to
    encode one); `lower="warm"` attaches cost ONLY from that shared
    cache — no encode, no tracing — for callers (bench) that just ran
    the check whose kernels populated it. `lanes` > 1 bills each
    bucket for a vmapped lockstep batch (lanes-per-device x the lane
    bytes). Returns the plan report dict (module docstring)."""
    from ..ops import wgl as wgl_mod

    plat = _safe_platform(platform)
    accel = plat not in (None, "cpu")
    rules: list = []

    # -- shapes ---------------------------------------------------------
    if enc is None and lower is True and model is not None \
            and history is not None:
        from ..ops.encode import EncodingUnsupported, encode
        try:
            enc = encode(model, history)
        except EncodingUnsupported as e:
            rules.append(_rule(
                "P004", f"encoding unsupported: {e}",
                suggestion="route to the host oracle (wgl_ref)"))
            verdict, suggestion = _verdict(rules)
            return {"schema": 1, "kind": "wgl", "platform": plat,
                    "engine": "oracle", "shapes": {},
                    "encoding": e.to_dict(), "plan": [], "rules": rules,
                    "verdict": verdict, "suggestion": suggestion}
    if enc is not None:
        shapes = _shapes_from_enc(enc)
    elif history is not None:
        shapes = _probe_shapes(history)
    else:
        raise ValueError("plan_wgl needs enc or history")
    shapes.update(_depth_stats(shapes))
    if shape_bucket:
        # the bucket maxima are the compiled shape — a representative
        # enc smaller than the bucket must not shrink the byte model
        shapes["n_pad"] = max(shapes["n_pad"],
                              int(shape_bucket.get("n_pad", 0)))
        shapes["ic_pad"] = max(shapes["ic_pad"],
                               int(shape_bucket.get("ic_pad", 0)))
    n, ni = shapes["n_ok"], shapes["n_info"]
    w_raw, W = shapes["W_raw"], shapes["W"]
    ic_pad = shapes["ic_pad"]

    # -- predictive encoding limits (P004) — encode.py's own caps ------
    from ..ops.encode import MAX_INFO, MAX_WINDOW
    if W > MAX_WINDOW:
        rules.append(_rule(
            "P004", f"window {w_raw} would exceed the encode cap "
                    f"{MAX_WINDOW} (rule=window)",
            suggestion="route to the host oracle (wgl_ref)"))
    if ni > MAX_INFO:
        rules.append(_rule(
            "P004", f"{ni} crashed ops would exceed the encode cap "
                    f"{MAX_INFO} (rule=info-cap)",
            suggestion="route to the host oracle (wgl_ref)"))
    if any(r["rule"] == "P004" for r in rules):
        verdict, suggestion = _verdict(rules)
        shapes.pop("inv", None), shapes.pop("ret", None)
        return {"schema": 1, "kind": "wgl", "platform": plat,
                "engine": "oracle", "shapes": shapes, "plan": [],
                "rules": rules, "verdict": verdict,
                "suggestion": suggestion}

    # -- the SAME derivation wgl.check executes (single source of
    #    truth: ops/wgl.derive_plan — the planner cannot drift from
    #    the kernel it models) -----------------------------------------
    plan_p = wgl_mod.derive_plan(
        window_raw=w_raw, W=W, ic_pad=ic_pad, n=n, n_info=ni,
        accel=accel, frontier=frontier, adaptive=adaptive,
        shape_bucket=shape_bucket)
    kern = plan_p["kern"]
    H, B = plan_p["H"], plan_p["B"]
    W_eff, ic_eff, L = plan_p["W_eff"], plan_p["ic_eff"], plan_p["L"]
    chunk, depth, probes = (plan_p["chunk"], plan_p["depth"],
                            plan_p["probes"])
    use_adapt, buckets = plan_p["use_adapt"], plan_p["buckets"]
    compact = depth > 1  # wgl32's compact-before-expand default
    if enc is not None:
        pack = (bool(shape_bucket["pack"])
                if shape_bucket and "pack" in shape_bucket
                else wgl_mod._packable(enc))
        pack_estimated = False
    else:
        # probe mode: times + a typical table fit; labeled an estimate
        from ..ops.wgl32 import PACK_MAX
        pack = shapes["times_max"] < PACK_MAX
        pack_estimated = True

    # -- plan nodes -----------------------------------------------------
    budget = device_memory_budget(plat)
    nodes: list = []
    for k in buckets:
        hbm = _node_bytes(k, W_eff, ic_eff,
                          1 if kern == "wgl32" else L, H, B,
                          shapes["n_pad"])
        if lanes > 1:
            # a vmapped lockstep batch keeps every lane's buffers
            # resident at once (parallel/batched.encode_batch): the
            # per-device bill is lanes-per-device x the lane bytes
            hbm *= lanes
        node = {"kernel": kern, "K": k, "H": H, "B": B,
                "W_eff": W_eff, "ic_eff": ic_eff, "chunk": chunk,
                "depth": depth, "pack": pack, "compact": compact,
                "succ_rows": k * (W_eff + ic_eff),
                "hbm_bytes": hbm}
        if lanes > 1:
            node["lanes"] = lanes
        if lower:
            from .. import occupancy as occ_mod
            # the SAME cache key ops/wgl.py uses at result time (the
            # bucket-padded n_pad IS len(enc.inv) there), so the
            # executed check's roofline and this prediction can't drift
            key = (kern, shapes["n_pad"], ic_eff, W_eff, k, chunk,
                   depth, accel, pack)
            if lower is True and enc is not None:
                node["cost"] = occ_mod.cost_for(
                    key, lambda k_=k: _lower_wgl_node(
                        enc, kern, K=k_, H=H, B=B, chunk=chunk,
                        probes=probes, W_eff=W_eff, ic_eff=ic_eff,
                        L=L, accel=accel, depth=depth, pack=pack))
            else:
                # lower="warm" (with or without an enc): cost only
                # when the executed check already lowered this exact
                # kernel — no encode, no tracing, just the shared
                # cache. lower=True without an enc lands here too.
                cost = occ_mod.cost_cached(key)
                if cost is not None:
                    node["cost"] = cost
        nodes.append(node)
    peak = max(nd["hbm_bytes"] for nd in nodes)
    if peak > budget:
        rules.append(_rule(
            "P001", f"plan peak {peak / 1e9:.2f} GB exceeds the "
                    f"{budget / 1e9:.2f} GB device budget",
            suggestion="shard the history (parallel/batched) or cap "
                       "the frontier"))

    # -- P003: cold executables vs the caller's compile budget ----------
    cbudget = _compile_budget(compile_budget)
    if cbudget is not None and len(nodes) > cbudget:
        rules.append(_rule(
            "P003", f"{len(nodes)} cold executables exceed the "
                    f"compile budget {cbudget}",
            suggestion="warm the ladder first: "
                       "aot.precompile_wgl_ladder(...)"))

    # -- P005: predicted fill at the starting bucket --------------------
    wavefront = max(shapes.get("mean_depth") or 0.0, 1.0)
    k_start = buckets[0]
    fill_pred = round(min(1.0, wavefront / max(k_start, 1)), 4)
    from ..occupancy import TARGET_FILL
    if fill_pred < TARGET_FILL:
        why = (f"predicted fill {fill_pred} at start bucket "
               f"K={k_start} (wavefront ~{wavefront}) under target "
               f"{TARGET_FILL}")
        sugg = ("enable the adaptive ladder (ops/adapt.py)"
                if not use_adapt else
                "near-serial shape: the jitlin probe route "
                "(ops/route.check_routed) decides it cheaper")
        if shape_bucket and shape_bucket.get("w_eff", 0) > 2 * W:
            sugg = ("shared bucket pads W to "
                    f"{shape_bucket['w_eff']} vs raw {w_raw}: split "
                    "the bucket")
        rules.append(_rule("P005", why, suggestion=sugg))

    verdict, suggestion = _verdict(rules)
    shapes.pop("inv", None), shapes.pop("ret", None)
    return {
        "schema": 1, "kind": "wgl", "platform": plat,
        "engine": "device", "shapes": shapes, "kernel": kern,
        "pack": pack, "pack_estimated": pack_estimated,
        "adaptive": bool(use_adapt), "buckets": buckets,
        "plan": nodes,
        "hbm": {"peak_bytes": peak, "budget_bytes": budget},
        "compiles": {"cold_max": len(nodes), "budget": cbudget},
        "fill": {"predicted": fill_pred, "target": TARGET_FILL,
                 "start_K": k_start},
        "rules": rules, "verdict": verdict, "suggestion": suggestion,
    }


# ---------------------------------------------------------------------------
# Elle: route + closure capacity plan
# ---------------------------------------------------------------------------

def _fleet_shards(w: int) -> tuple:
    """(n_shards, assumed?) for the sharded closure's word-column
    split, init-safe (planning must never trigger a backend init): an
    explicit JEPSEN_TPU_ELLE_SHARDS pin wins; an ALREADY-initialized
    backend is asked for its device count; otherwise one v5e host's 8
    chips are ASSUMED — and labeled, so a report built before init
    says which half of its bill is measured."""
    import os

    from ..parallel.mesh import word_shard_count
    pin = os.environ.get("JEPSEN_TPU_ELLE_SHARDS")
    if pin:
        return word_shard_count(w, int(pin)), False
    try:
        from .. import devices as devices_mod
        if devices_mod._backend_up():
            import jax
            return word_shard_count(w, len(jax.devices())), False
    except Exception:  # noqa: BLE001 — fall through to the assumption
        pass
    return word_shard_count(w, 8), True


def plan_elle_sharded(*, n_txns: int, n_shards: Optional[int] = None,
                      platform: Optional[str] = None) -> dict:
    """The mesh-sharded closure's plan node for `n_txns`: shard count
    (from the fleet unless pinned), per-shard live bytes — ONE
    gathered row-set copy plus 2/n_shards writable column blocks, the
    exact working set of elle/tpu.cycle_queries_sharded — and the
    all_gather bytes each squaring iteration moves. Pure host
    arithmetic; `platform` is accepted for symmetry with the other
    planners but the bill is shape-only."""
    import math

    from ..elle import tpu as elle_tpu

    n = int(n_txns)
    n_sub = len(elle_tpu.SUBSETS)
    n_pad = elle_tpu._round_up(
        max(elle_tpu._bucket(max(n, 2)), n + 2), 128)
    iters = max(1, math.ceil(math.log2(max(n_pad, 2))))
    assumed = False
    if n_shards is None:
        n_shards, assumed = _fleet_shards(n_pad // 32)
    ns = max(1, int(n_shards))
    bitset = n_sub * n_pad * (n_pad // 32) * 4
    per_shard = int(bitset * (1.0 + 2.0 / ns))
    return {"kernel": "sharded", "n_pad": n_pad, "iters": iters,
            "n_shards": ns, "shards_assumed": assumed,
            "shard_words": (n_pad // 32) // ns,
            "per_shard_bytes": per_shard,
            "gather_bytes_per_iter": int(bitset),
            "hbm_bytes": per_shard,
            "capacity": elle_tpu.SHARDED_MAX_N}


def plan_elle(*, n_txns: int, edges: Optional[int] = None,
              rw_edges: Optional[int] = None, backend: str = "auto",
              platform: Optional[str] = None,
              lower: bool = False) -> dict:
    """Enumerate the cycle-engine plan an Elle check over `n_txns`
    graph nodes would take: the `ops/route.elle_cycle_route` decision
    (when `backend="auto"`), the kernel the shape selector would pick
    (trim on cpu-XLA, bf16-vs-packed-vs-sharded by cost on an
    accelerator), the closure's padded shapes and peak live bytes,
    and the capacity rules that fire. Past a single-chip cap the plan
    carries a `plan_elle_sharded` node (n_shards, per-shard bytes,
    all_gather bytes per iteration): when the fleet and the per-shard
    bill allow, P002 fires as a DEGRADE onto the sharded kernel
    instead of rejecting — `dense_100k` becomes degrade(sharded) on
    any fleet with >= 2 word shards. Edge counts default to the append-builder's
    typical density (~4 edges and ~1 rw edge per txn), labeled as
    estimates. Pure host arithmetic: no graph build, no backend
    compile, no device byte."""
    import importlib.util
    import math

    from ..ops.route import elle_cycle_route

    plat = _safe_platform(platform)
    accel = plat not in (None, "cpu")
    n = int(n_txns)
    e = int(edges) if edges is not None else 4 * n
    rw = int(rw_edges) if rw_edges is not None else n
    estimated = edges is None or rw_edges is None
    rules: list = []

    # lazy: PACKED_MAX_N / DEFAULT_MAX_N are the kernels' own caps
    from ..elle import tpu as elle_tpu
    packed_cap = elle_tpu.PACKED_MAX_N
    bf16_cap = elle_tpu.DEFAULT_MAX_N
    sharded_cap = elle_tpu.SHARDED_MAX_N
    n_pad = elle_tpu._round_up(
        max(elle_tpu._bucket(max(n, 2)), n + 2), 128)
    n_shards, shards_assumed = _fleet_shards(n_pad // 32)

    engine = backend
    route_reason = None
    if backend == "auto":
        device_ok = importlib.util.find_spec("jax") is not None
        engine, route_reason = elle_cycle_route(
            n=n, e=e, rw_edges=rw, accel=accel, device_ok=device_ok,
            packed_cap=packed_cap, sharded_cap=sharded_cap,
            n_shards=n_shards)

    if engine in ("host", "host-fallback"):
        verdict, suggestion = _verdict(rules)
        return {"schema": 1, "kind": "elle", "platform": plat,
                "engine": "host", "backend": backend,
                "route": {"engine": "host", "reason": route_reason},
                "shapes": {"n": n, "e": e, "rw": rw,
                           "estimated": estimated},
                "plan": [{"kernel": "host-tarjan",
                          "host_work": rw * max(e, 1)}],
                "rules": rules, "verdict": verdict,
                "suggestion": suggestion}

    # -- kernel selection (mirror device_cycle_search) ------------------
    forced = backend in ("tpu", "packed", "trim", "sharded")
    if forced:
        kernel = "bf16" if backend == "tpu" else backend
        sel = {"why": f"forced {kernel}"}
    elif engine == "sharded":
        # the router pinned the kernel: only the sharded layout holds
        # the bitset at this n
        kernel, sel = "sharded", {"why": route_reason}
    elif accel:
        if lower:
            kernel, sel = elle_tpu._squaring_select(n)
        elif n > packed_cap:
            if n <= sharded_cap and n_shards >= 2:
                kernel, sel = "sharded", {
                    "why": f"n {n} > packed cap {packed_cap}; "
                           f"{n_shards}-shard word columns (static)"}
            else:
                kernel, sel = "packed", {
                    "why": f"n {n} > packed cap {packed_cap} and no "
                           f"shardable fleet ({n_shards} shards)"}
        elif n > bf16_cap:
            kernel, sel = "packed", {
                "why": f"n {n} > bf16 cap {bf16_cap}"}
        else:
            kernel, sel = "bf16", {"why": "bf16 under cap (static)"}
    else:
        kernel, sel = "trim", {
            "why": "cpu backend: dense squaring is "
                   "compute-prohibitive; trim kernel"}

    # -- padded shapes + capacity + bytes -------------------------------
    n_sub = len(elle_tpu.SUBSETS)
    iters = max(1, math.ceil(math.log2(max(n_pad, 2))))
    cap = {"bf16": bf16_cap,
           "sharded": sharded_cap}.get(kernel, packed_cap)
    budget = device_memory_budget(plat)
    orig_kernel, orig_cap = kernel, cap
    sharded_node = None
    if kernel == "sharded" or n > cap:
        sharded_node = plan_elle_sharded(n_txns=n, n_shards=n_shards,
                                         platform=plat)
        sharded_node["shards_assumed"] = shards_assumed
    if n > cap:
        # past a single-chip cap the mesh-sharded layout is the one
        # dense remedy: degrade onto it when the fleet and its
        # per-shard bill allow, reject naming it when they don't
        # only kernels whose executed path falls through to the
        # sharded closure may degrade onto it (packed and trim do;
        # a forced bf16 request host-falls-back instead)
        fits = (kernel in ("packed", "trim") and n <= sharded_cap
                and n_shards >= 2
                and sharded_node["per_shard_bytes"] <= budget)
        if fits:
            rules.append(_rule(
                "P002",
                f"n {n} over the {kernel} closure capacity {cap}: "
                f"degrading to the mesh-sharded closure "
                f"({n_shards} word-column shards"
                f"{', assumed fleet' if shards_assumed else ''}, "
                f"{sharded_node['per_shard_bytes'] / 1e9:.2f} GB "
                f"per shard)",
                suggestion="sharded closure selected "
                           "(backend=\"sharded\" pins it); widen "
                           "the fleet for smaller shards",
                severity="degrade"))
            kernel = "sharded"
            cap = sharded_cap
            sel = {"why": f"degrade(sharded): {sel.get('why')}",
                   "n_shards": n_shards}
        elif kernel == "sharded":
            rules.append(_rule(
                "P002",
                f"n {n} over the sharded closure capacity {cap}: "
                "past it the gathered row set alone blows a chip",
                suggestion="host Tarjan/BFS (chunked closure is "
                           "ROADMAP item 4's 1M residue)"))
        else:
            why_not = (f"n {n} over the sharded cap {sharded_cap}"
                       if n > sharded_cap else
                       f"fleet yields only {n_shards} word shard(s)"
                       if n_shards < 2 else
                       f"per-shard "
                       f"{sharded_node['per_shard_bytes'] / 1e9:.2f}"
                       f" GB over the "
                       f"{budget / 1e9:.2f} GB budget")
            rules.append(_rule(
                "P002",
                f"n {n} over the {kernel} closure capacity {cap} "
                f"and the mesh-sharded remedy does not hold it "
                f"({why_not})",
                suggestion="host Tarjan/BFS, or widen the fleet so "
                           "the sharded word columns fit "
                           "(backend=\"sharded\")"))
    if kernel == "bf16":
        cell = 2.0            # bf16
    elif kernel == "packed":
        cell = 1.0 / 8.0      # one bit per pair, uint32 words
    else:
        cell = 0.0            # trim/sharded: billed below
    if kernel == "sharded":
        # per-shard bill: the gather buffer + 2/n_shards local blocks
        hbm = sharded_node["per_shard_bytes"]
    elif cell:
        hbm = int(CLOSURE_LIVE_FACTOR * n_sub * n_pad * n_pad * cell)
    else:
        # trim: padded neighbor gathers, O((E + N) x S)
        n_pad_t = elle_tpu._round_up(elle_tpu._bucket(max(n, 2)), 128)
        d_est = elle_tpu._bucket(max(4, (2 * e) // max(n, 1)))
        hbm = int(3 * n_pad_t * d_est * n_sub * 4)
    if hbm > budget:
        if backend == "auto":
            # the router said device but the cost side disagrees —
            # auto still holds the host engine in hand, so degrade
            # rather than reject (the route downstream stays free to
            # fall back; an explicit device request below does not)
            rules.append(_rule(
                "P006", "route picked the device closure but its "
                        f"cost model blows HBM ({hbm / 1e9:.2f} GB): "
                        "trust the cost side",
                suggestion="host Tarjan/BFS"))
        else:
            # backend= explicitly pins the device plane ("device"
            # included: device_cycle_search runs whatever kernel the
            # shape selector picks) — an over-budget closure would
            # OOM, so reject it statically
            per = " per shard" if kernel == "sharded" else ""
            rules.append(_rule(
                "P001", f"{kernel} closure peak {hbm / 1e9:.2f} GB"
                        f"{per} exceeds the {budget / 1e9:.2f} GB "
                        "device budget",
                suggestion="host Tarjan/BFS, or widen the fleet so "
                           "the sharded word columns fit "
                           "(backend=\"sharded\")"
                if kernel == "sharded" else
                "host Tarjan/BFS, or shard the bitset words across "
                "the mesh (backend=\"sharded\")"))

    if kernel == "sharded":
        # a degrade keeps the rejected single-chip node in the plan
        # beside its sharded remedy (its bill is what P002 priced);
        # a routed/forced sharded pick plans the one node it runs
        plan = ([{"kernel": orig_kernel,
                  "n_pad": n_pad, "iters": iters,
                  "hbm_bytes": int(CLOSURE_LIVE_FACTOR * n_sub
                                   * n_pad * n_pad
                                   * (2.0 if orig_kernel == "bf16"
                                      else 0.125)),
                  "capacity": orig_cap}, sharded_node]
                if orig_kernel != "sharded"
                else [sharded_node])
    else:
        plan = [{"kernel": kernel, "n_pad": n_pad, "iters": iters,
                 "hbm_bytes": hbm, "capacity": cap}]

    verdict, suggestion = _verdict(rules)
    return {
        "schema": 1, "kind": "elle", "platform": plat,
        "engine": "device", "backend": backend,
        "route": {"engine": "device", "reason": route_reason},
        "shapes": {"n": n, "e": e, "rw": rw, "n_pad": n_pad,
                   "iters": iters, "estimated": estimated,
                   "n_shards": n_shards,
                   "shards_assumed": shards_assumed},
        "kernel": kernel, "select": sel,
        "plan": plan,
        "hbm": {"peak_bytes": hbm, "budget_bytes": budget},
        "rules": rules, "verdict": verdict, "suggestion": suggestion,
    }


def elle_closure_feasible(n_txns: int,
                          platform: Optional[str] = None) -> tuple:
    """(feasible?, report) for a dense device closure over `n_txns` —
    the feasibility oracle the 100k-Elle sharding plan queries before
    choosing whole-closure vs column-blocked execution."""
    rep = plan_elle(n_txns=n_txns, backend="device",
                    platform=platform)
    return rep["verdict"] != "infeasible", rep


# ---------------------------------------------------------------------------
# recording + gates
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_RECENT: deque = deque(maxlen=32)
_COUNTS: dict = {}


def compact(report: dict) -> dict:
    """The bounded projection of a plan report that rides gate
    results, ledger records, and /status.json (the full plan nodes
    stay with the CLI/report path)."""
    out = {k: report.get(k) for k in
           ("schema", "kind", "platform", "engine", "kernel",
            "buckets", "verdict", "suggestion")
           if report.get(k) is not None}
    out["rules"] = [{"rule": r["rule"], "name": r["name"],
                     "severity": r["severity"],
                     "message": r["message"]}
                    for r in report.get("rules", [])]
    hbm = report.get("hbm") or {}
    if hbm.get("peak_bytes") is not None:
        out["hbm_peak_bytes"] = hbm["peak_bytes"]
        out["hbm_budget_bytes"] = hbm.get("budget_bytes")
    return out


def _register(report: dict, where: str,
              ledger_name: Optional[str] = None) -> None:
    """Record one preflight verdict into the ambient observability
    planes: the in-process recent window (/status.json's `preflight`
    block), the `preflight` metrics series, and — when `ledger_name`
    names a top-level analysis — a `kind="preflight"` ledger record.
    Never raises; accounting must not void an admission decision."""
    entry = {"where": where, "kind": report.get("kind"),
             "verdict": report.get("verdict"),
             "engine": report.get("engine"),
             "rules": [r["rule"] for r in report.get("rules", [])],
             "t": round(time.time(), 3)}
    with _LOCK:
        _RECENT.append(entry)
        _COUNTS[entry["verdict"]] = _COUNTS.get(entry["verdict"],
                                                0) + 1
    try:
        from .. import metrics as metrics_mod
        mx = metrics_mod.get_default()
        if mx.enabled:
            mx.series("preflight",
                      "admission-control preflight verdicts"
                      ).append(dict(entry))
            mx.counter("preflight_checks_total",
                       "preflight admission decisions").inc(
                where=where, verdict=str(entry["verdict"]))
    except Exception:  # noqa: BLE001
        pass
    if ledger_name:
        try:
            from .. import ledger as ledger_mod
            ledger_mod.record({
                "kind": "preflight", "name": ledger_name,
                "verdict": str(report.get("verdict")),
                "engine": report.get("engine"),
                "where": where,
                "rules": entry["rules"],
                "preflight": compact(report)})
        except Exception:  # noqa: BLE001
            pass


def snapshot() -> dict:
    """The `/status.json` `preflight` block: how many admission
    decisions this process made, their verdict mix, and a bounded
    recent window."""
    with _LOCK:
        recent = list(_RECENT)[-8:]
        counts = dict(_COUNTS)
    return {"checked": sum(counts.values()), "verdicts": counts,
            "recent": recent}


def _reject(report: dict, op_count: Optional[int] = None) -> dict:
    out = {"valid?": "unknown", "cause": "preflight",
           "preflight": compact(report),
           "rules": [r["rule"] for r in report.get("rules", [])
                     if r["severity"] == "infeasible"]}
    if op_count is not None:
        out["op_count"] = op_count
    return out


def gate_wgl(model, history, *, where: str, enc=None,
             platform: Optional[str] = None,
             ledger_name: Optional[str] = None) -> Optional[dict]:
    """The WGL admission gate (history_lint.gate's sibling): None when
    the plan is admissible (feasible or degrade), else a checker-style
    `{"valid?": "unknown", "cause": "preflight", ...}` fast-fail.
    Cheap — a shape probe plus integer plan math, no encode table, no
    jax."""
    try:
        rep = plan_wgl(model, history, enc=enc, platform=platform)
    except Exception:  # noqa: BLE001 — an unplannable history is the
        return None    # search engines' problem, not the gate's
    _register(rep, where, ledger_name=ledger_name)
    if rep["verdict"] != "infeasible":
        return None
    return _reject(rep, op_count=len(history))


def gate_elle(n_txns: int, *, backend: str, where: str,
              edges: Optional[int] = None,
              rw_edges: Optional[int] = None,
              platform: Optional[str] = None,
              ledger_name: Optional[str] = None) -> Optional[dict]:
    """The Elle admission gate: rejects a device-backend cycle search
    whose closure can never fit (P001/P002) BEFORE any graph build,
    backend compile, or device execution. None when admissible."""
    try:
        rep = plan_elle(n_txns=n_txns, edges=edges, rw_edges=rw_edges,
                        backend=backend, platform=platform)
    except Exception:  # noqa: BLE001
        return None
    _register(rep, where, ledger_name=ledger_name)
    if rep["verdict"] != "infeasible":
        return None
    return _reject(rep)


def gate_fanout(model, histories, *, encs=None, where: str,
                platform: Optional[str] = None,
                mode: str = "group",
                n_devices: int = 1,
                on_infeasible: str = "reject") -> Optional[dict]:
    """Admission gate for the parallel fan-out paths: plan the SHARED
    shape bucket each kernel branch will actually compile (the same
    `shared_shape_bucket` maxima `parallel/batched.py` pads every
    lane to — keys split at window_raw 32 exactly like the runtime),
    so the admitted plan is the kernel that runs.

    mode="group" (the streamed path): the narrow and wide groups
    compile SEPARATE kernels and each lane runs alone on a device, so
    an infeasible bucket rejects only within its own group — and only
    the keys whose OWN plan is infeasible, with the survivors' bucket
    re-planned (the runtime re-buckets without rejected lanes); the
    whole group rejects only in the mixed-maxima edge where every key
    fits alone but the combined maxima do not.
    mode="batch" (the lockstep vmap path): `encode_batch` pads EVERY
    lane to the batch maxima and one kernel keeps ceil(lanes /
    n_devices) lanes' buffers resident per device — the plan is that
    single batch kernel, and an infeasible plan rejects every key.
    `on_infeasible="degrade"` (batch mode) records the decision as a
    degrade instead of an infeasible rejection, for callers that
    answer an infeasible batch by streaming per-key kernels.

    Returns `{key_index: rejection}` for the rejected keys (indices
    into the encs/histories as passed), or None when admissible.
    Without encs there is no shared bucket yet: each key is probed
    and gated on its own plan."""
    rejected: dict = {}
    try:
        if encs:
            from ..parallel.batched import shared_shape_bucket
            if mode == "batch":
                bucket = shared_shape_bucket(list(encs))
                # the rep must take the kernel branch encode_batch
                # takes (wgln iff ANY lane is wide); the bucket's
                # n_pad/ic_pad maxima override its smaller dims
                rep_enc = max(encs,
                              key=lambda e: (e.window_raw > 32,
                                             len(e.inv)))
                per_dev = -(-len(encs) // max(n_devices, 1))
                rep = plan_wgl(enc=rep_enc, platform=platform,
                               shape_bucket=bucket, lanes=per_dev)
                if rep["verdict"] == "infeasible" \
                        and on_infeasible == "degrade":
                    # the caller's declared policy: an infeasible
                    # lockstep batch is served by per-key kernels
                    # instead — the admission decision actually made
                    # for this request is a degrade, not a rejection
                    _register(dict(rep, verdict="degrade",
                                   suggestion="stream per-key kernels "
                                              "(check_streamed)"),
                              where)
                else:
                    _register(rep, where)
                if rep["verdict"] == "infeasible":
                    rej = _reject(rep)
                    rejected = {i: rej for i in range(len(encs))}
                return rejected or None
            def _bucket_plan(idxs):
                grp = [encs[i] for i in idxs]
                bucket = shared_shape_bucket(grp)
                # the representative carries the bucket's n_pad (the
                # byte model reads it off the enc); W_eff/ic_eff/
                # n_cap/pack come from the bucket dict itself
                rep_enc = max(grp, key=lambda e: (len(e.inv),
                                                  e.window_raw))
                rep = plan_wgl(enc=rep_enc, platform=platform,
                               shape_bucket=bucket)
                _register(rep, where)
                return rep

            idx_groups = (
                [i for i, e in enumerate(encs) if e.window_raw <= 32],
                [i for i, e in enumerate(encs) if e.window_raw > 32])
            for idxs in idx_groups:
                if not idxs:
                    continue
                rep = _bucket_plan(idxs)
                if rep["verdict"] != "infeasible":
                    continue
                # the shared bucket is blown — but the bucket is the
                # group MAXIMA, so first reject only the keys whose
                # OWN single-key plan is infeasible, then re-try the
                # survivors' re-computed bucket (the runtime streams
                # re-bucket without the rejected lanes)
                survivors = []
                for i in idxs:
                    own = plan_wgl(enc=encs[i], platform=platform)
                    if own["verdict"] == "infeasible":
                        # this plan IS the decision delivered to the
                        # caller — it must land in the series/status
                        # like every other admission verdict
                        _register(own, where)
                        rejected[i] = _reject(own)
                    else:
                        survivors.append(i)
                if not survivors:
                    continue
                if len(survivors) == len(idxs):
                    # mixed-maxima edge: every key fits alone, the
                    # combined maxima do not — the group compiles ONE
                    # kernel, so it rejects as a group
                    rej = _reject(rep)
                    for i in survivors:
                        rejected[i] = rej
                    continue
                rep2 = _bucket_plan(survivors)
                if rep2["verdict"] == "infeasible":
                    rej = _reject(rep2)
                    for i in survivors:
                        rejected[i] = rej
        elif histories:
            # no encodings yet: no shared bucket exists either, so
            # each key runs (and is gated) on its own probe plan — a
            # feasible key must not lose its verdict to an oversized
            # neighbor
            for i, h in enumerate(histories):
                rep = plan_wgl(model, h, platform=platform)
                _register(rep, where)
                if rep["verdict"] == "infeasible":
                    rejected[i] = _reject(rep)
    except Exception:  # noqa: BLE001 — an unplannable batch is the
        return None    # engines' problem, not the gate's
    return rejected or None


def plan_mesh(encs, *, n_devices: int,
              lanes_per_device: Optional[int] = None,
              platform: Optional[str] = None,
              axes=("keys",),
              compile_budget: Optional[int] = None,
              shape_bucket: Optional[dict] = None) -> dict:
    """The mesh fan-out's plan report (`parallel/mesh.py`): one
    `mesh`-annotated plan node per (lane group x ladder bucket), each
    billed for `lanes_per_device` resident lanes — the per-SHARD cost
    of the lane-packed scheduler, not the whole-batch cost the vmap
    path pays. P001 fires when a shard's lane group blows the device
    budget; P003 when the ladder's cold executables exceed the compile
    budget (the remedy is `aot.precompile_mesh_plan`, not the
    single-search ladder warm). The caller degrades an infeasible
    report to the streamed path — `gate_mesh` below — so a too-big
    lane group costs a routing decision, never a crash."""
    from ..parallel import mesh as mesh_mod
    from ..parallel.batched import shared_shape_bucket

    plat = _safe_platform(platform)
    s_d = int(lanes_per_device or mesh_mod.MESH_LANES_PER_DEVICE)
    groups = [("narrow", [i for i, e in enumerate(encs)
                          if e.window_raw <= 32]),
              ("wide", [i for i, e in enumerate(encs)
                        if e.window_raw > 32])]
    nodes: list = []
    rules: list = []
    group_reports: list = []
    for gname, idxs in groups:
        if not idxs:
            continue
        grp = [encs[i] for i in idxs]
        # a caller-forced canonical bucket (the service plane) is the
        # kernel that will actually run — admit THAT, not the smaller
        # batch-derived one, so the gate and the executable agree
        bucket = (dict(shape_bucket) if shape_bucket is not None
                  else shared_shape_bucket(grp))
        # bill the CALLER's lane count verbatim: an explicit
        # lanes_per_device allocates that many resident lanes per
        # shard regardless of group size, and for the derived case a
        # small group billed at the larger group's width merely
        # over-bills — admission must err toward degrade, never
        # under-bill an allocation that then OOMs at run time
        g_sd = s_d
        rep_enc = max(grp, key=lambda e: (len(e.inv), e.window_raw))
        rep = plan_wgl(enc=rep_enc, platform=plat,
                       shape_bucket=bucket, lanes=g_sd,
                       compile_budget=compile_budget)
        mesh_note = {"group": gname, "keys": len(idxs),
                     "n_devices": int(n_devices),
                     "lanes_per_device": g_sd,
                     "axes": [str(a) for a in axes]}
        for node in rep.get("plan", []):
            nodes.append(dict(node, mesh=dict(mesh_note)))
        for r in rep.get("rules", []):
            if r["rule"] == "P003":
                r = dict(r, suggestion="warm the mesh plan first: "
                                       "aot.precompile_mesh_plan("
                                       "shape_bucket, mesh)")
            rules.append(r)
        group_reports.append({"group": gname, "keys": len(idxs),
                              "kernel": rep.get("kernel"),
                              "buckets": rep.get("buckets"),
                              "verdict": rep["verdict"]})
    verdict, suggestion = _verdict(rules)
    peak = max((nd["hbm_bytes"] for nd in nodes), default=0)
    return {
        "schema": 1, "kind": "mesh", "platform": plat,
        "engine": "device",
        "mesh": {"n_devices": int(n_devices),
                 "lanes_per_device": s_d,
                 "axes": [str(a) for a in axes]},
        "groups": group_reports, "plan": nodes,
        "hbm": {"peak_bytes": peak,
                "budget_bytes": device_memory_budget(plat)},
        "compiles": {"cold_max": len(nodes),
                     "budget": _compile_budget(compile_budget)},
        "rules": rules, "verdict": verdict, "suggestion": suggestion,
    }


def gate_mesh(encs, *, n_devices: int,
              lanes_per_device: Optional[int] = None,
              where: str = "parallel.mesh",
              platform: Optional[str] = None,
              axes=("keys",),
              shape_bucket: Optional[dict] = None) -> Optional[dict]:
    """Admission gate for the mesh fan-out: None when the mesh plan is
    admissible; else the report — the caller answers by STREAMING
    per-key kernels, so the decision actually delivered is a degrade
    (recorded as one), never a rejection."""
    try:
        rep = plan_mesh(encs, n_devices=n_devices,
                        lanes_per_device=lanes_per_device,
                        platform=platform, axes=axes,
                        shape_bucket=shape_bucket)
    except Exception:  # noqa: BLE001 — an unplannable batch is the
        return None    # engines' problem, not the gate's
    if rep["verdict"] == "infeasible":
        _register(dict(rep, verdict="degrade",
                       suggestion="stream per-key kernels "
                                  "(check_streamed)"), where)
        return rep
    _register(rep, where)
    return None


# ---------------------------------------------------------------------------
# CLI (`python -m jepsen_tpu preflight`)
# ---------------------------------------------------------------------------

CLI_CONFIGS = ("headline", "elle_append_8k", "dense_100k")


def _cli_headline(n_ops: int, execute: bool) -> dict:
    from .. import synth
    from ..models import cas_register

    model = cas_register()
    hist = synth.cas_register_history(n_ops, n_procs=5, seed=42,
                                      crash_p=0.002)
    rep = plan_wgl(model, hist, lower=True)
    _register(rep, "cli.headline", ledger_name="preflight-headline")
    out = {"report": rep}
    if execute:
        from ..ops import wgl
        from .. import metrics as metrics_mod
        with metrics_mod.use(metrics_mod.Registry()):
            res = wgl.check(model, hist)
        out["executed"] = _parity(rep, res)
    return out


def _cli_elle(n_txns: int, execute: bool) -> dict:
    from .. import synth
    from ..elle import build as build_mod
    from ..elle import tpu as elle_tpu

    hist = synth.list_append_history(n_txns, n_procs=5, seed=7)
    oks = [op for op in hist
           if op.is_ok and op.f in ("txn", None) and op.value]
    infos = [op for op in hist
             if op.is_info and op.f in ("txn", None) and op.value]
    bt = build_mod.build_append(hist, oks, infos,
                                additional_graphs=("realtime",))
    gt = bt.tensors
    edges = np.asarray(gt.edges)
    from ..elle.graph import RW
    rw = int(np.sum(edges[:, 2] == RW)) if len(edges) else 0
    rep = plan_elle(n_txns=int(np.asarray(gt.nodes).shape[0]),
                    edges=int(len(edges)), rw_edges=rw,
                    backend="auto", lower=True)
    _register(rep, "cli.elle_append_8k",
              ledger_name="preflight-elle-append-8k")
    out = {"report": rep}
    if execute:
        res = elle_tpu.standard_cycle_search(gt, backend="auto")
        out["executed"] = {
            "engine": res.get("engine"),
            "kernel": (res.get("util") or {}).get("kernel"),
            "engine_match": _engines_match(rep, res),
        }
    return out


def _cli_dense_100k() -> dict:
    """The synthetic oversized request: a 100k-txn dense closure.
    Statically — zero graph build, zero backend compiles, zero device
    execution (the smoke proves it under a CompileGuard zero-compile
    budget) — the single-chip bill is rejected and the plan DEGRADES
    onto the mesh-sharded column layout whenever the fleet yields
    >= 2 word shards whose per-shard bill fits the budget; with no
    shardable fleet the old infeasible verdict stands."""
    rep = plan_elle(n_txns=100_000, backend="packed")
    _register(rep, "cli.dense_100k", ledger_name="preflight-dense-100k")
    return {"report": rep}


def _engines_match(rep: dict, res: dict) -> bool:
    planned = rep.get("engine")
    ran = res.get("engine")
    if planned == "host":
        return ran in ("host", "host-fallback")
    kernel = (res.get("util") or {}).get("kernel")
    return ran in ("device", "tpu", "trim", "packed", "sharded") \
        and (rep.get("kernel") in (None, kernel))


def _parity(rep: dict, res: dict) -> dict:
    """Planned-vs-executed comparison for the WGL path: did the
    executed check stay inside the planned buckets, on the planned
    kernel/variant, and how far is the measured per-round byte stream
    from the plan's prediction for the bucket it ended on."""
    util = res.get("util") or {}
    adapt = util.get("adapt") or {}
    visited = adapt.get("buckets_visited") or [res.get("K")]
    planned = rep.get("buckets") or []
    occ = res.get("occupancy") or {}
    measured = ((occ.get("roofline") or {}).get("bytes_per_round"))
    pred = None
    for node in rep.get("plan", []):
        if node.get("K") == res.get("K") and node.get("cost"):
            pred = node["cost"].get("bytes_accessed")
    out = {
        "verdict": res.get("valid?"),
        "kernel_match": (occ.get("kernel") or
                         ("wgl32" if res.get("W", 33) <= 32
                          else "wgln")) == rep.get("kernel"),
        "buckets_planned": planned,
        "buckets_visited": visited,
        "buckets_subset": all(k in planned for k in visited if k),
        "pack_match": (util.get("packed_tables") is None
                       or bool(util.get("packed_tables"))
                       == bool(rep.get("pack"))),
        "bytes_per_round_predicted": pred,
        "bytes_per_round_measured": measured,
    }
    if pred and measured:
        out["drift_x"] = round(measured / pred, 4)
    return out


def cli_main(options: dict) -> int:
    """`python -m jepsen_tpu preflight` — emit plan reports for the
    named config(s); `--execute` additionally runs the check and
    prints the planned-vs-executed parity block."""
    import json as json_mod

    which = options.get("config") or "all"
    execute = bool(options.get("execute"))
    as_json = bool(options.get("json"))
    names = list(CLI_CONFIGS) if which == "all" else [which]
    out: dict = {}
    for name in names:
        if name == "headline":
            out[name] = _cli_headline(
                int(options.get("ops") or 10_000), execute)
        elif name == "elle_append_8k":
            out[name] = _cli_elle(
                int(options.get("txns") or 4_000), execute)
        elif name == "dense_100k":
            out[name] = _cli_dense_100k()
        else:
            print(f"unknown preflight config {name!r} "
                  f"(known: {', '.join(CLI_CONFIGS)} | all)")
            return 254
    if as_json:
        print(json_mod.dumps(out, indent=2, default=str))
    else:
        for name, blk in out.items():
            rep = blk["report"]
            rules = ", ".join(r["rule"] for r in rep["rules"]) or "-"
            line = (f"{name:18s} verdict={rep['verdict']:10s} "
                    f"engine={rep.get('engine')} "
                    f"kernel={rep.get('kernel', '-')} "
                    f"buckets={rep.get('buckets', '-')} "
                    f"hbm={((rep.get('hbm') or {}).get('peak_bytes') or 0) / 1e9:.3f}GB "
                    f"rules=[{rules}]")
            print(line)
            if rep.get("suggestion"):
                print(f"{'':18s} -> {rep['suggestion']}")
            if "executed" in blk:
                print(f"{'':18s} executed: {blk['executed']}")
    return 0
