"""Changed-file scoping for the lint CLIs (one copy, two linters).

`scripts/jax_lint.py` and `scripts/thread_lint.py` both offer
`--changed-only`: lint just the files changed vs git HEAD (plus
untracked), intersected with the linter's default paths — the fast
pre-commit loop. The git plumbing lives here so the two CLIs cannot
drift apart in how they interpret the working tree.
"""

from __future__ import annotations

import os
import subprocess


def changed_files(repo_root: str):
    """Python files changed vs HEAD (staged, unstaged, untracked),
    absolute paths. Returns None when git is unavailable/failing —
    the caller must then lint the full paths rather than silently
    passing an unknowable working tree."""
    out: list = []
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=repo_root, capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=repo_root, capture_output=True, text=True, timeout=30)
        if diff.returncode != 0 or untracked.returncode != 0:
            return None
        names = diff.stdout.splitlines() + untracked.stdout.splitlines()
    except Exception:  # noqa: BLE001 — no git: signal the caller
        return None
    for name in names:
        path = os.path.join(repo_root, name)
        # a deleted tracked file still shows in the diff — nothing to
        # lint there
        if name.endswith(".py") and os.path.isfile(path):
            out.append(path)
    return out


def under(path: str, roots) -> bool:
    """Is `path` one of `roots` or inside one of them?"""
    path = os.path.abspath(path)
    for r in roots:
        r = os.path.abspath(r)
        if path == r or path.startswith(r + os.sep):
            return True
    return False


def scope_changed(paths, repo_root: str, *, quiet: bool,
                  label: str):
    """The shared --changed-only behavior: intersect changed files
    with `paths`. Returns (paths, done) — `done` True means "nothing
    to lint, exit 0 now". Falls back to the full paths (with a stderr
    note) when git is unusable."""
    import sys
    changed = changed_files(repo_root)
    if changed is None:
        # no usable git: a silent pass here would green-light an
        # unknowable tree — lint the full scope instead
        print(f"{label}: git unavailable; --changed-only falls "
              "back to the full lint paths", file=sys.stderr)
        return list(paths), False
    kept = [p for p in changed if under(p, paths)]
    if not kept:
        if not quiet:
            print(f"{label}: no changed files under the lint paths")
        return [], True
    return kept, False
