"""jit-safety lint for the kernel modules (AST-based, stdlib-only).

The kernels under `jepsen_tpu/ops/` and `jepsen_tpu/elle/` are the
perf-critical path (BASELINE.json: 10k-op cas-register in <60 s on
v5e-8). The classic JAX footguns — a hidden host sync inside a jitted
region, a fresh `jax.jit` per call, a Python branch on a tracer —
don't fail loudly; they silently serialize the device or recompile
per invocation. This linter encodes them as static rules:

  J001 host-sync-in-jit   `.block_until_ready()`, `.item()`,
                          `.tolist()`, `np.asarray`/`np.array`, or
                          `float()`/`int()`/`bool()` applied to a
                          traced value inside a jit region — each
                          forces a device->host sync (or fails to
                          trace at all)
  J002 tracer-branch      Python `if`/`while` whose condition
                          references a traced value inside a jit
                          region — either a ConcretizationTypeError
                          at trace time or a silent host round-trip
  J003 uncached-jit       `jax.jit(...)` constructed inside a
                          function with no caching decorator on the
                          enclosing chain — a fresh jit (and a fresh
                          compile) every call
  J004 scalar-closure     a jitted closure capturing a parameter of
                          an uncached enclosing function — every
                          distinct captured value retraces and
                          recompiles
  J005 dtype-promotion    arithmetic mixing two *different* explicit
                          integer dtypes in one expression — implicit
                          promotion drifts dtypes (and x64 stays off
                          in this tree, so int64 creep is a bug)
  J006 python-loop-jnp    `jnp`/`lax` ops inside a Python
                          `for ... in range(...)` statement in a jit
                          region — unrolls into the trace; belongs in
                          `lax.scan`/`lax.fori_loop`
  J007 transfer-in-loop   a host transfer/sync (`np.asarray`/
                          `np.array` on a device result,
                          `jax.device_get`, `.block_until_ready()`)
                          inside a HOST-side poll loop — each
                          iteration pays a device->host round-trip
                          (~75 ms on a tunneled v5e); the static twin
                          of the transfer budget `guards.CompileGuard`
                          enforces at runtime. While-loops check all
                          four forms; for-loops only the unambiguous
                          syncs (`device_get`/`block_until_ready`),
                          since `np.asarray` over a host iterable is
                          idiomatic numpy
  J008 missing-donation   `jax.jit(fn)` where `fn` is a chunked
                          kernel (a parameter named `carry`/`state` —
                          the re-fed search carry) without
                          `donate_argnums` — every call copies the
                          multi-MB carry instead of donating it

Jit regions are resolved per module: functions passed to `jax.jit`
(call or decorator, incl. `functools.partial(jax.jit, ...)`),
functions handed to `lax` control-flow HOFs (`while_loop`,
`fori_loop`, `scan`, `cond`, `switch`, `map` — their bodies trace
regardless of an enclosing jit), and everything they call by name
within the module, to a fixpoint. Traced names within a region are
the function's parameters plus locals assigned from `jnp`/`lax`/
traced expressions (one forward pass).

Allowlist: a `# jaxlint: ok(J001)` (or `ok(J001,J006)`, or a bare
`# jaxlint: ok`) comment on the flagged line — or on the line
directly above it — suppresses the finding. A file-level
`# jaxlint: ok-file(J003,J006)` within the first 20 lines suppresses
the named rules for the whole module (for benchmark-style scripts
whose one-shot compiles and timing loops ARE the point; never a bare
form — file-wide suppression must name its rules). Every allowlist in
the tree is an explicit, reviewable decision; CI keeps the tree clean
(`scripts/jax_lint.py`, wired as a tier-1 test).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Optional

RULES = {
    "J001": "host-sync-in-jit",
    "J002": "tracer-branch",
    "J003": "uncached-jit",
    "J004": "scalar-closure",
    "J005": "dtype-promotion",
    "J006": "python-loop-jnp",
    "J007": "transfer-in-loop",
    "J008": "missing-donation",
}

# jitted-kernel carry parameter names J008 keys on: the re-fed search
# carry is the multi-MB buffer donation exists for.
_CARRY_PARAMS = {"carry", "state"}

_LAX_HOFS = {"while_loop", "fori_loop", "scan", "cond", "switch", "map"}
_CACHE_DECORATORS = {"lru_cache", "cache", "cached_property"}
_NUMPY_NAMES = {"np", "numpy", "onp"}
_HOST_SYNC_ATTRS = {"block_until_ready", "item", "tolist"}
_HOST_SYNC_NP_FUNCS = {"asarray", "array"}
_INT_DTYPES = {"int8", "int16", "int32", "int64",
               "uint8", "uint16", "uint32", "uint64"}
_ALLOW_RE = re.compile(r"#\s*jaxlint:\s*ok(?:\(([^)]*)\))?")
_ALLOW_FILE_RE = re.compile(r"#\s*jaxlint:\s*ok-file\(([^)]*)\)")
# ok-file must sit in the module header, a visible reviewable banner
_ALLOW_FILE_SCAN_LINES = 20


@dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{RULES[self.rule]}] {self.message}")


# ---------------------------------------------------------------------------
# module indexing
# ---------------------------------------------------------------------------

class _FuncInfo:
    __slots__ = ("node", "name", "parents", "params", "cached_chain")

    def __init__(self, node, name, parents, params, cached_chain):
        self.node = node
        self.name = name
        self.parents = parents          # enclosing _FuncInfo chain
        self.params = params            # parameter name set
        self.cached_chain = cached_chain  # any enclosing def is cached


def _decorator_names(node) -> set:
    out = set()
    for dec in getattr(node, "decorator_list", []):
        d = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(d, ast.Attribute):
            out.add(d.attr)
        elif isinstance(d, ast.Name):
            out.add(d.id)
        if isinstance(dec, ast.Call):
            # functools.partial(jax.jit, ...) as a decorator
            for a in dec.args:
                if _is_jit_ref(a):
                    out.add("jit")
    return out


def _is_jit_ref(node) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "jit") or \
        (isinstance(node, ast.Name) and node.id == "jit")


def _is_lax_hof(node) -> Optional[str]:
    """lax.while_loop / jax.lax.scan / ... -> the hof name."""
    if isinstance(node, ast.Attribute) and node.attr in _LAX_HOFS:
        v = node.value
        if isinstance(v, ast.Name) and v.id == "lax":
            return node.attr
        if isinstance(v, ast.Attribute) and v.attr == "lax":
            return node.attr
    return None


def _param_names(node) -> set:
    a = node.args
    names = [x.arg for x in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


class _Index(ast.NodeVisitor):
    """Collect every function def with its enclosing chain, plus the
    calls that mark jit regions."""

    def __init__(self):
        self.funcs: list = []            # all _FuncInfo
        self.by_name: dict = {}          # name -> [FuncInfo]
        self.jit_roots: list = []        # (FuncInfo, reason)
        self.jit_calls: list = []        # (Call node, enclosing chain)
        self._stack: list = []

    def _enter(self, node, name):
        cached = any(_decorator_names(f.node) & _CACHE_DECORATORS
                     for f in self._stack)
        cached = cached or bool(_decorator_names(node)
                                & _CACHE_DECORATORS)
        fi = _FuncInfo(node, name, list(self._stack),
                       _param_names(node), cached)
        self.funcs.append(fi)
        self.by_name.setdefault(name, []).append(fi)
        if _decorator_names(node) & {"jit"}:
            self.jit_roots.append((fi, "decorator"))
        self._stack.append(fi)
        self.generic_visit(node)
        self._stack.pop()

    def visit_FunctionDef(self, node):
        self._enter(node, node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._enter(node, "<lambda>")

    def visit_Call(self, node):
        if _is_jit_ref(node.func):
            self.jit_calls.append((node, list(self._stack)))
        elif _is_lax_hof(node.func):
            for arg in node.args:
                self._mark_fn_arg(arg)
        self.generic_visit(node)

    def _mark_fn_arg(self, arg):
        if isinstance(arg, ast.Name):
            for fi in self.by_name.get(arg.id, []):
                self.jit_roots.append((fi, "lax-hof"))
        elif isinstance(arg, (ast.List, ast.Tuple)):
            for el in arg.elts:
                self._mark_fn_arg(el)
        # Lambda args are indexed when visited; mark by node identity
        elif isinstance(arg, ast.Lambda):
            self.jit_roots.append((arg, "lax-hof-lambda"))


def _resolve_regions(idx: _Index) -> set:
    """The set of FunctionDef/Lambda AST nodes that trace (jit
    regions), propagated through direct in-module calls."""
    region: set = set()
    node_to_fi = {fi.node: fi for fi in idx.funcs}

    def add(fn_node):
        if fn_node in region:
            return
        region.add(fn_node)
        # propagate: names called from this body
        for sub in ast.walk(fn_node):
            if isinstance(sub, ast.Call) and isinstance(sub.func,
                                                        ast.Name):
                for fi in idx.by_name.get(sub.func.id, []):
                    add(fi.node)
            elif isinstance(sub, ast.Call) and _is_lax_hof(sub.func):
                for arg in sub.args:
                    if isinstance(arg, ast.Name):
                        for fi in idx.by_name.get(arg.id, []):
                            add(fi.node)
                    elif isinstance(arg, ast.Lambda):
                        add(arg)

    for root, _why in idx.jit_roots:
        add(root.node if isinstance(root, _FuncInfo) else root)
    for call, _chain in idx.jit_calls:
        for arg in call.args[:1]:
            if isinstance(arg, ast.Name):
                for fi in idx.by_name.get(arg.id, []):
                    add(fi.node)
            elif isinstance(arg, ast.Lambda):
                add(arg)
    del node_to_fi
    return region


# ---------------------------------------------------------------------------
# per-function analysis helpers
# ---------------------------------------------------------------------------

def _names_in(node) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _walk_own(fn_node):
    """Walk a function body WITHOUT descending into nested function
    defs/lambdas — those are their own (possibly jit-region) scopes
    and are analyzed separately, so descending would double-report
    and apply the wrong traced-name set."""
    body = fn_node.body if isinstance(fn_node.body, list) \
        else [fn_node.body]
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


def _is_static_access(parent_map, node) -> bool:
    """x.shape / x.ndim / x.dtype / len(x) / isinstance(...) never
    hold tracers — conditions built only from these are static."""
    p = parent_map.get(node)
    if isinstance(p, ast.Attribute) and p.attr in ("shape", "ndim",
                                                   "dtype", "size"):
        return True
    if isinstance(p, ast.Call) and isinstance(p.func, ast.Name) \
            and p.func.id in ("len", "isinstance", "getattr",
                              "hasattr", "type"):
        return True
    return False


def _traced_names(fn_node) -> set:
    """Parameters + locals assigned from jnp/lax/traced expressions
    (single forward pass, good enough for lint)."""
    traced = set(_param_names(fn_node)) if not isinstance(
        fn_node, ast.Lambda) else {a.arg for a in fn_node.args.args}

    def expr_traced(e) -> bool:
        for sub in ast.walk(e):
            if isinstance(sub, ast.Name) and sub.id in traced:
                return True
            if isinstance(sub, ast.Attribute) and isinstance(
                    sub.value, ast.Name) and sub.value.id in ("jnp",
                                                              "lax"):
                return True
        return False

    body = fn_node.body if isinstance(fn_node.body, list) \
        else [fn_node.body]
    for stmt in body:
        for sub in ast.walk(stmt) if isinstance(stmt, ast.stmt) \
                else []:
            if isinstance(sub, ast.Assign) and expr_traced(sub.value):
                for tgt in sub.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, (ast.Name,)):
                            traced.add(n.id)
    return traced


def _walk_skip_defs(node):
    """Walk a subtree without descending into nested function defs or
    lambdas (separate scopes, analyzed on their own)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue
        yield sub
        stack.extend(ast.iter_child_nodes(sub))


def _loop_call_targets(loop) -> set:
    """Names assigned from a call expression inside the loop body —
    the values that can hold device results in a host poll loop."""
    out: set = set()
    for sub in _walk_skip_defs(loop):
        if isinstance(sub, ast.Assign) and any(
                isinstance(x, ast.Call) for x in ast.walk(sub.value)):
            for tgt in sub.targets:
                for nm in ast.walk(tgt):
                    if isinstance(nm, ast.Name):
                        out.add(nm.id)
    return out


def _dtype_markers(node) -> set:
    """Explicit integer-dtype markers in an expression subtree:
    jnp.int32(x) casts, dtype=jnp.uint32 kwargs, .astype(jnp.int32),
    convert_element_type(..., jnp.uint32)."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _INT_DTYPES:
            out.add(sub.attr)
        elif isinstance(sub, ast.Name) and sub.id in _INT_DTYPES:
            out.add(sub.id)
    return out


# ---------------------------------------------------------------------------
# the linter
# ---------------------------------------------------------------------------

def lint_source(src: str, path: str = "<string>") -> list:
    """Lint one module's source. Returns a list of Findings (already
    filtered through the inline allowlist)."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, 0, "J001",
                        f"syntax error prevents linting: {e.msg}")]
    idx = _Index()
    idx.visit(tree)
    regions = _resolve_regions(idx)
    findings: list = []

    parent_map: dict = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parent_map[child] = node

    def add(node, rule, msg):
        findings.append(Finding(path, getattr(node, "lineno", 0),
                                getattr(node, "col_offset", 0),
                                rule, msg))

    # -- J007: host transfers/syncs inside host-side poll loops --------
    def in_region(node) -> bool:
        p = node
        while p is not None:
            if p in regions:
                return True
            p = parent_map.get(p)
        return False

    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.While, ast.For)) \
                or in_region(loop):
            continue
        targets = _loop_call_targets(loop)
        is_while = isinstance(loop, ast.While)
        for sub in _walk_skip_defs(loop):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if not isinstance(f, ast.Attribute):
                continue
            arg_names = set()
            for a in sub.args:
                arg_names |= _names_in(a)
            if isinstance(f.value, ast.Name):
                arg_names.add(f.value.id)  # method receiver
            if not (arg_names & targets):
                continue  # not a device result produced in this loop
            if f.attr in ("block_until_ready", "device_get"):
                add(sub, "J007",
                    f"{f.attr} on a device result inside a host poll "
                    "loop — each iteration pays a device->host "
                    "round-trip; batch the fetch into the packed "
                    "poll summary (allowlist the ONE designed poll)")
            elif is_while and f.attr in _HOST_SYNC_NP_FUNCS \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in _NUMPY_NAMES:
                add(sub, "J007",
                    f"np.{f.attr} on a device result inside a "
                    "while/poll loop transfers per iteration — "
                    "batch the fetch (allowlist the ONE designed "
                    "poll)")

    # -- J008: carry-style jitted kernels must donate the carry --------
    for call, _chain in idx.jit_calls:
        target = call.args[0] if call.args else None
        if not isinstance(target, ast.Name):
            continue
        if {kw.arg for kw in call.keywords} \
                & {"donate_argnums", "donate_argnames"}:
            continue
        for fi in idx.by_name.get(target.id, []):
            if isinstance(fi.node, ast.Lambda):
                continue
            carry = _param_names(fi.node) & _CARRY_PARAMS
            if carry:
                add(call, "J008",
                    f"jax.jit({target.id}) re-feeds its "
                    f"{sorted(carry)} parameter without "
                    "donate_argnums — every chunk call copies the "
                    "multi-MB carry instead of donating it")
                break
    # decorator spellings of the same footgun: @jax.jit / @jit bare,
    # or @functools.partial(jax.jit, ...) without donation
    for fi in idx.funcs:
        carry = fi.params & _CARRY_PARAMS
        if not carry:
            continue
        for dec in getattr(fi.node, "decorator_list", []):
            if _is_jit_ref(dec):
                donated = False
            elif isinstance(dec, ast.Call) and (
                    _is_jit_ref(dec.func)
                    or any(_is_jit_ref(a) for a in dec.args)):
                donated = bool({kw.arg for kw in dec.keywords}
                               & {"donate_argnums", "donate_argnames"})
            else:
                continue
            if not donated:
                add(dec, "J008",
                    f"@jit on {fi.name} re-feeds its "
                    f"{sorted(carry)} parameter without "
                    "donate_argnums — every chunk call copies the "
                    "multi-MB carry instead of donating it")
            break

    # -- J003 / J004: jit construction + closure captures -------------
    for call, chain in idx.jit_calls:
        call_site_cached = any(
            f.cached_chain or (_decorator_names(f.node)
                               & _CACHE_DECORATORS) for f in chain)
        if chain and not call_site_cached:
            add(call, "J003",
                "jax.jit constructed inside an uncached function — "
                "a fresh compile every call (wrap the builder in "
                "functools.lru_cache)")
        # closure-captured enclosing params on the jitted function —
        # only a problem when the jit call site itself is uncached
        # (a cached builder memoizes one jit per static config)
        target = call.args[0] if call.args else None
        if isinstance(target, ast.Name) and chain \
                and not call_site_cached:
            for fi in idx.by_name.get(target.id, []):
                if fi.cached_chain or not fi.parents:
                    continue
                outer_params = set()
                for p in fi.parents:
                    outer_params |= p.params
                captured = (_names_in(fi.node) - fi.params) \
                    & outer_params
                if captured:
                    add(call, "J004",
                        f"jitted closure captures enclosing "
                        f"parameter(s) {sorted(captured)} without a "
                        "cached builder — each distinct value "
                        "retraces and recompiles")

    for fn_node in regions:
        traced = _traced_names(fn_node)

        for sub in _walk_own(fn_node):
            # -- J001: host syncs -------------------------------------
            if isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in _HOST_SYNC_ATTRS:
                    add(sub, "J001",
                        f".{f.attr}() inside a jit region forces a "
                        "host sync (or fails to trace)")
                elif isinstance(f, ast.Attribute) \
                        and f.attr in _HOST_SYNC_NP_FUNCS \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in _NUMPY_NAMES:
                    if any(a for a in sub.args
                           if _names_in(a) & traced):
                        add(sub, "J001",
                            f"np.{f.attr} on a traced value inside a "
                            "jit region materializes on host")
                elif isinstance(f, ast.Name) \
                        and f.id in ("float", "int", "bool") \
                        and sub.args \
                        and (_names_in(sub.args[0]) & traced):
                    add(sub, "J001",
                        f"{f.id}() on a traced value inside a jit "
                        "region forces concretization")
            # -- J002: python branch on a tracer ----------------------
            elif isinstance(sub, (ast.If, ast.While)):
                test_names = {
                    n.id for n in ast.walk(sub.test)
                    if isinstance(n, ast.Name) and n.id in traced
                    and not _is_static_access(parent_map, n)}
                if test_names:
                    kind = "if" if isinstance(sub, ast.If) else "while"
                    add(sub, "J002",
                        f"Python `{kind}` on traced value(s) "
                        f"{sorted(test_names)} inside a jit region — "
                        "use lax.cond/jnp.where or hoist to a static "
                        "argument")
            # -- J005: mixed explicit int dtypes ----------------------
            elif isinstance(sub, ast.BinOp) and isinstance(
                    sub.op, (ast.Add, ast.Sub, ast.Mult, ast.FloorDiv,
                             ast.Mod, ast.BitAnd, ast.BitOr,
                             ast.BitXor, ast.LShift, ast.RShift)):
                lm, rm = _dtype_markers(sub.left), \
                    _dtype_markers(sub.right)
                if lm and rm and not (lm & rm):
                    add(sub, "J005",
                        f"arithmetic mixes explicit dtypes "
                        f"{sorted(lm)} and {sorted(rm)} — implicit "
                        "promotion drifts dtypes; cast one side "
                        "explicitly")
            # -- J006: jnp ops inside a Python range loop -------------
            elif isinstance(sub, ast.For):
                it = sub.iter
                is_range = isinstance(it, ast.Call) and isinstance(
                    it.func, ast.Name) and it.func.id == "range"
                if is_range:
                    uses_jnp = any(
                        isinstance(s, ast.Attribute) and isinstance(
                            s.value, ast.Name)
                        and s.value.id in ("jnp", "lax")
                        for st in sub.body for s in ast.walk(st))
                    if uses_jnp:
                        add(sub, "J006",
                            "jnp/lax ops inside a Python `for "
                            "... in range(...)` in a jit region "
                            "unroll into the trace — use lax.scan / "
                            "lax.fori_loop (allowlist intentional "
                            "bounded unrolls)")

    # nested regions can still be reached twice via different roots
    seen: set = set()
    uniq: list = []
    for f in findings:
        k = (f.path, f.line, f.col, f.rule)
        if k not in seen:
            seen.add(k)
            uniq.append(f)
    return _apply_allowlist(uniq, src)


def _apply_allowlist(findings: list, src: str) -> list:
    lines = src.splitlines()

    file_rules: set = set()
    for ln in lines[:_ALLOW_FILE_SCAN_LINES]:
        m = _ALLOW_FILE_RE.search(ln)
        if m:
            file_rules |= {w.strip() for w in m.group(1).split(",")
                           if w.strip()}

    def allowed(f: Finding) -> bool:
        if f.rule in file_rules:
            return True
        for ln in (f.line, f.line - 1):
            if 1 <= ln <= len(lines):
                m = _ALLOW_RE.search(lines[ln - 1])
                if m:
                    which = m.group(1)
                    if which is None:
                        return True
                    ids = {w.strip() for w in which.split(",")}
                    if f.rule in ids:
                        return True
        return False

    out = [f for f in findings if not allowed(f)]
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def lint_file(path: str) -> list:
    with open(path) as fh:
        return lint_source(fh.read(), path)


def lint_paths(paths) -> list:
    """Lint every .py file under the given files/directories."""
    findings: list = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                if "__pycache__" in root:
                    continue
                for name in sorted(files):
                    if name.endswith(".py"):
                        findings += lint_file(os.path.join(root, name))
        elif p.endswith(".py"):
            findings += lint_file(p)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
