"""Stall watchdog: heartbeat monitoring for device rounds and workers.

Every engine here deadlines gracefully *between* device chunks — but a
hang *inside* a chunk (a wedged XLA dispatch, a tunneled accelerator
that stops answering, a worker thread stuck in backend init) is
invisible to those checks: the poll loop never comes back to look at
the clock. The JVM baseline's failure mode — "times out with nothing
to show" — becomes "blocks forever with nothing to show", which is
worse.

This module closes that gap with heartbeats. Instrumented loops
register a `Source` and `beat()` at their natural poll boundaries
(`ops/wgl.py` per chunk, `parallel/batched.py` per key / per poll,
`elle/tpu.py` around the closure kernel call); a monitor thread scans
registered sources and declares any source whose last beat is older
than `stall_s` **stalled**:

  * the stall is recorded as a structured `fleet` fault
    (stage="watchdog") plus a `watchdog_stalls` metrics series point
    and counter, and surfaces on the live RunStatus;
  * with `escalation="cancel"`, the run is soft-cancelled: cooperating
    loops observe `cancelled()` at their next boundary and return
    `{"valid?": "unknown", "cause": "stalled"}` carrying their partial
    progress (configs explored, ops linearized, keys decided), and
    `guarded()` / the streamed fan-out stop waiting on the hung thread
    instead of blocking forever.

Tuning knobs (doc/OBSERVABILITY.md): `stall_s` (heartbeat age that
declares a stall; default 30 s, env JEPSEN_TPU_WATCHDOG_STALL_S),
`poll_s` (monitor scan interval, default stall_s/4), `escalation`
("record" — default — or "cancel", env
JEPSEN_TPU_WATCHDOG_ESCALATION).

Zero-cost contract (matching metrics/fleet/ledger): the module
default is a disabled `NULL_WATCHDOG`; `register()` hands back an
inert source and `beat()` returns immediately. `core.run` and
`bench.py` install a real one; JEPSEN_TPU_WATCHDOG=1 enables it
ambiently.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Iterator, Optional

from .analysis import lockwatch

DEFAULT_STALL_S = 30.0

# Heartbeat series sampling floor: beats can arrive at kHz on the cpu
# fast path; the watchdog_heartbeats series keeps ~1 Hz per source.
_HEARTBEAT_RECORD_S = 1.0


class Source:
    """One heartbeat stream (a device-round loop, a fleet worker, a
    kernel call). `beat()` goes through the owning Watchdog; consumers
    read `stalled` / `progress` / `stall_event`."""

    __slots__ = ("name", "meta", "t0", "last", "beats", "progress",
                 "stalled", "cancel", "stall_event", "_last_rec",
                 "live", "stall_s", "grace_s")

    def __init__(self, name: str, meta: dict,
                 stall_s: Optional[float] = None,
                 grace_s: float = 0.0):
        self.name = name
        self.meta = meta
        self.t0 = self.last = time.monotonic()
        self.beats = 0
        self.progress: dict = {}
        self.stalled = False
        self.cancel = False
        self.stall_event: Optional[dict] = None
        self._last_rec = 0.0
        self.live = True
        # per-source threshold override (a known-slow healthy call,
        # e.g. the Elle closure at capacity) and a first-beat grace
        # (the first WGL chunk folds in XLA compile, which can dwarf
        # a steady-state poll) — both prevent false stalls on healthy
        # slow paths while keeping steady-state detection tight
        self.stall_s = stall_s
        self.grace_s = float(grace_s)


_NULL_SOURCE = Source("null", {})
_NULL_SOURCE.live = False


class Watchdog:
    """Heartbeat registry + monitor thread (see module docstring).
    All recording methods return immediately on a disabled instance."""

    def __init__(self, enabled: bool = True,
                 stall_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 escalation: Optional[str] = None):
        self.enabled = enabled
        self.stall_s = float(
            stall_s if stall_s is not None else os.environ.get(
                "JEPSEN_TPU_WATCHDOG_STALL_S", DEFAULT_STALL_S))
        self.poll_s = float(poll_s) if poll_s is not None \
            else max(0.05, self.stall_s / 4)
        esc = (escalation if escalation is not None else
               os.environ.get("JEPSEN_TPU_WATCHDOG_ESCALATION",
                              "record"))
        if esc not in ("record", "cancel"):
            raise ValueError(f"unknown escalation {esc!r} "
                             "(want 'record' or 'cancel')")
        self.escalation = esc
        self.stalls: list = []
        self._sources: list = []
        self._lock = lockwatch.lock("watchdog")
        self._cancel_all = False
        self._cancel_reason: Optional[str] = None
        self._seq = 0
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- source lifecycle ---------------------------------------------
    def register(self, name: str, stall_s: Optional[float] = None,
                 grace_s: float = 0.0, **meta) -> Source:
        """Register a heartbeat source (an inert shared stub when
        disabled). `stall_s` overrides this watchdog's threshold for
        the source; `grace_s` is ADDED to the threshold until the
        first beat (compile headroom). Callers pair with `unregister`
        (or use `watch`)."""
        if not self.enabled:
            return _NULL_SOURCE
        with self._lock:
            self._seq += 1
            src = Source(f"{name}#{self._seq}", meta,
                         stall_s=stall_s, grace_s=grace_s)
            self._sources.append(src)
        self._ensure_monitor()
        return src

    def unregister(self, src: Source) -> None:
        if not self.enabled or src is _NULL_SOURCE:
            return
        src.live = False
        with self._lock:
            if src in self._sources:
                self._sources.remove(src)

    @contextlib.contextmanager
    def watch(self, name: str, **meta) -> Iterator[Source]:
        """Scoped register/unregister."""
        src = self.register(name, **meta)
        try:
            yield src
        finally:
            self.unregister(src)

    # -- the hot path -------------------------------------------------
    def beat(self, src: Source, **progress) -> None:
        """One heartbeat: refreshes the stall clock and merges progress
        counters (what a stalled partial verdict will report). Called
        at poll boundaries — ~Hz, never inside device rounds."""
        if not self.enabled or src is _NULL_SOURCE:
            return
        now = time.monotonic()
        src.last = now
        src.beats += 1
        if src.stalled and not src.cancel:
            # the source recovered (a transient slow poll, not a
            # hang): re-arm detection so a LATER genuine hang is
            # still declared — scan() is idempotent only until the
            # next beat. Cancel-escalated sources stay latched; the
            # run is already winding down.
            src.stalled = False
            src.stall_event = None
        if progress:
            src.progress.update(progress)
        if now - src._last_rec >= _HEARTBEAT_RECORD_S:
            src._last_rec = now
            from . import metrics as _metrics
            mx = _metrics.get_default()
            if mx.enabled:
                mx.series("watchdog_heartbeats",
                          "throttled per-source heartbeat samples"
                          ).append({"source": src.name,
                                    "beats": src.beats,
                                    **{k: v for k, v in
                                       src.progress.items()
                                       if isinstance(v, (int, float))}})

    def cancelled(self, src: Optional[Source] = None) -> bool:
        """Should this loop wind down? True after a run-wide
        soft-cancel or a per-source cancel (escalation='cancel' sets
        it on the stalled source so a woken zombie stops promptly)."""
        if not self.enabled:
            return False
        if self._cancel_all:
            return True
        return bool(src is not None and src is not _NULL_SOURCE
                    and src.cancel)

    def soft_cancel(self, reason: str = "stalled") -> None:
        """Run-wide soft-cancel: every cooperating loop returns a
        partial `{"valid?": "unknown", "cause": "stalled"}` at its
        next boundary."""
        if not self.enabled:
            return
        with self._lock:
            self._cancel_all = True
            self._cancel_reason = reason

    # -- stall detection ----------------------------------------------
    def scan(self, now: Optional[float] = None) -> list:
        """One detection pass over live sources; returns the NEW stall
        events. Idempotent per source until its next beat (a source is
        declared stalled once, not once per scan). The monitor thread
        calls this every `poll_s`; tests call it directly."""
        if not self.enabled:
            return []
        now = time.monotonic() if now is None else now
        with self._lock:
            sources = list(self._sources)
        events = []
        for src in sources:
            age = now - src.last
            limit = (src.stall_s if src.stall_s is not None
                     else self.stall_s)
            if src.beats == 0:
                limit += src.grace_s
            if src.stalled or age <= limit:
                continue
            ev = {"type": "StallDetected",
                  "error": (f"no heartbeat from {src.name} for "
                            f"{age:.1f}s (threshold {limit}s)"),
                  "stage": "watchdog",
                  "device": src.meta.get("device"),
                  "key_index": src.meta.get("key_index"),
                  "source": src.name,
                  "age_s": round(age, 3),
                  "beats": src.beats,
                  "progress": dict(src.progress),
                  "escalation": self.escalation}
            with self._lock:
                # check-and-set under the lock: the monitor thread and
                # a caller's manual scan() must not both declare (and
                # double-record) the same stall — and the cancel flags
                # + stall log mutate under the SAME critical section,
                # so a concurrent soft_cancel()/scan() can neither
                # tear the reason nor double-append the event
                if src.stalled:
                    continue
                src.stalled = True
                if self.escalation == "cancel":
                    # run-wide soft-cancel: healthy loops wind down
                    # with partial verdicts at their next boundary;
                    # only the genuinely hung thread gets abandoned
                    # by its waiter
                    src.cancel = True
                    self._cancel_all = True
                    if self._cancel_reason is None:
                        self._cancel_reason = f"stalled: {src.name}"
                self.stalls.append(ev)
            src.stall_event = ev
            events.append(ev)
            self._publish(ev)
        return events

    def _publish(self, ev: dict) -> None:
        """Fan a stall event out to the observability planes; never
        raises (a broken sink must not break detection)."""
        try:
            from . import fleet as _fleet
            _fleet.record_fault(ev)
            st = _fleet.get_default()
            if st.enabled:
                st.stall(ev)
        except Exception:  # noqa: BLE001
            pass
        try:
            from . import metrics as _metrics
            mx = _metrics.get_default()
            if mx.enabled:
                mx.series("watchdog_stalls",
                          "stalled-source detections").append(
                    {"source": ev["source"], "age_s": ev["age_s"],
                     "beats": ev["beats"],
                     "escalation": ev["escalation"]})
                mx.counter("watchdog_stalls_total",
                           "sources declared stalled").inc(
                    device=str(ev.get("device") or "host"))
        except Exception:  # noqa: BLE001
            pass

    # -- monitor thread -----------------------------------------------
    def _ensure_monitor(self) -> None:
        if self._monitor is not None and self._monitor.is_alive():
            return
        with self._lock:
            if self._monitor is not None and self._monitor.is_alive():
                return
            self._stop.clear()
            t = threading.Thread(target=self._run_monitor,
                                 name="jepsen-tpu-watchdog",
                                 daemon=True)
            self._monitor = t
            t.start()

    def _run_monitor(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.scan()
            except Exception:  # noqa: BLE001 — detection must survive
                pass

    def stop(self) -> None:
        """Stop the monitor thread (sources stay registered; scan()
        still works synchronously)."""
        self._stop.set()
        t = self._monitor
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=self.poll_s + 1.0)


def stall_result(src: Source, op_count: Optional[int] = None,
                 partial: Optional[dict] = None,
                 stall_s: Optional[float] = None) -> dict:
    """The soft-cancel verdict: "unknown" with cause "stalled" and the
    partial progress the source last reported — the anti-"times out
    with nothing to show" contract."""
    out: dict = {"valid?": "unknown", "cause": "stalled",
                 "partial": dict(partial if partial is not None
                                 else src.progress)}
    if op_count is not None:
        out["op_count"] = op_count
    ev = src.stall_event
    out["stall"] = ({k: ev.get(k) for k in
                     ("source", "age_s", "beats", "escalation")}
                    if ev else {"source": src.name, "beats": src.beats})
    if stall_s is not None:
        out["stall"]["stall_s"] = stall_s
    return out


def guarded(fn, *, name: str = "guarded", wd: Optional["Watchdog"] = None,
            join_s: float = 0.05, op_count: Optional[int] = None,
            **meta):
    """Run `fn(source)` under surveillance: fn executes on a daemon
    thread, beating through the handed `Source`; if the watchdog
    declares it stalled and escalation is "cancel", return
    `stall_result` (partial progress included) instead of blocking
    forever on the hung thread. With the NULL watchdog (or
    escalation="record") this degrades to a plain call/join."""
    wd = wd if wd is not None else get_default()
    if not wd.enabled:
        return fn(_NULL_SOURCE)
    with wd.watch(name, **meta) as src:
        box: dict = {}

        def run():
            try:
                box["result"] = fn(src)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["error"] = e

        th = threading.Thread(target=run, daemon=True,
                              name=f"watchdog-{name}")
        th.start()
        while th.is_alive():
            th.join(join_s)
            if not th.is_alive():
                break
            wd.scan()
            if src.stalled and wd.escalation == "cancel":
                # abandon the hung daemon thread; it observes
                # src.cancel if it ever wakes
                return stall_result(src, op_count=op_count,
                                    stall_s=wd.stall_s)
        if "error" in box:
            raise box["error"]
        return box.get("result")


NULL_WATCHDOG = Watchdog(enabled=False)


# Ambient default — a plain module global (NOT thread-local), like
# metrics/fleet/ledger: engine threads and fleet workers must see the
# watchdog the run installed.
_default: Watchdog = (
    Watchdog() if os.environ.get("JEPSEN_TPU_WATCHDOG", "")
    not in ("", "0") else NULL_WATCHDOG)


def get_default() -> Watchdog:
    """The ambient Watchdog — NULL_WATCHDOG unless JEPSEN_TPU_WATCHDOG
    was set at import or a caller installed one (core.run and bench.py
    do)."""
    return _default


def set_default(wd: Optional[Watchdog]) -> Watchdog:
    global _default
    prev = _default
    _default = wd if wd is not None else NULL_WATCHDOG
    return prev


@contextlib.contextmanager
def use(wd: Watchdog) -> Iterator[Watchdog]:
    """Scoped ambient watchdog (restores the previous on exit)."""
    prev = set_default(wd)
    try:
        yield wd
    finally:
        set_default(prev)
