"""Ahead-of-time TPU compilation evidence — no hardware required.

Three rounds of this build ran on a machine whose TPU runtime is wedged
(the `axon` tunnel hangs at backend init), so every measured number is
XLA:CPU.  This module closes the evidence gap from the *compiler* side:
jax's topology API (`jax.experimental.topologies.get_topology_desc`)
loads the real libtpu compiler and AOT-compiles our kernels for a TPU
v5e topology without touching any device.  That yields

  * a serialized TPU executable (proof the kernels lower and compile
    for the MXU target, committed as StableHLO + optimized-HLO text),
  * the compiler's own cost analysis (FLOPs, bytes accessed), and
  * a roofline model: v5e peak (197 TFLOP/s bf16, 819 GB/s HBM) turns
    cost analysis into a modeled per-call time, modeled MFU, and a
    modeled configs/s for the search kernels — published in BENCH next
    to the measured CPU numbers.

The reference's north star is wall-clock analysis budget
(jepsen/src/jepsen/checker.clj:185-216 gates on a 60 s default); the
modeled numbers below say what that budget buys once a chip shows up.

Kernels covered:
  * `wgl32`   — narrow-window bitmask search (ops/wgl32.py), the
                headline cas-register shape;
  * `wgln`    — packed multi-lane wide-window search (ops/wgln.py), the
                adversarial 2.2M-config shape (W=71 -> 96, L=3);
  * `elle`    — Elle closure-by-squaring (elle/tpu.py) in bf16, the
                dtype the kernel itself selects on a TPU backend.
"""

from __future__ import annotations

import gzip
import json
import os
import time
from typing import Any, Optional

# TPU v5e (v5 lite) single-chip peaks, public spec sheet numbers.
V5E_PEAK_BF16_FLOPS = 197e12
V5E_PEAK_HBM_BYTES = 819e9
V5E_NAME = "tpu v5e (v5 lite)"

# bf16 single-chip peaks by detected device kind (public spec sheets),
# substring-matched: jax `device_kind` strings vary by runtime plugin
# ("TPU v5 lite" from libtpu, "TPU v5e" from some plugins). MFU ratios
# in BENCH must divide by the peak of the chip that RAN, not a
# hardcoded v5e number (round-5 ADVICE).
PEAK_BF16_BY_KIND = (
    ("v6 lite", 918e12, "tpu v6e (trillium)"),
    ("v6e", 918e12, "tpu v6e (trillium)"),
    ("v5 lite", V5E_PEAK_BF16_FLOPS, V5E_NAME),
    ("v5e", V5E_PEAK_BF16_FLOPS, V5E_NAME),
    ("v5p", 459e12, "tpu v5p"),
    ("v5", 459e12, "tpu v5p"),
    ("v4", 275e12, "tpu v4"),
)


def peak_bf16_flops(device_kind: Optional[str] = None
                    ) -> tuple[float, str]:
    """(peak bf16 FLOP/s, chip label) for a detected jax device kind.
    Unknown/absent kinds fall back to the v5e spec numbers the AOT
    roofline model uses — labeled as a default so the fallback is
    visible in the published ratio."""
    kind = (device_kind or "").lower()
    for pat, peak, label in PEAK_BF16_BY_KIND:
        if pat in kind:
            return peak, label
    return V5E_PEAK_BF16_FLOPS, f"{V5E_NAME} (default: unknown kind)"

_TOPOLOGY = "v5e:2x2"  # smallest layout divisible by the 2x2x1 host


def tpu_topology(name: str = _TOPOLOGY):
    """A TPU TopologyDescription from libtpu, or None when the
    compiler stack can't provide one (no libtpu in the image).  Pure
    host work: never initializes a backend, so it is safe on the
    wedged-axon machine (see util.backend_ready docs)."""
    # libtpu init probes the GCE metadata server for a dozen tpu-env
    # variables; off-GCE each probe can retry for ~30 s against a
    # 403-ing endpoint (observed: 460 s before the first topology
    # call returns — it single-handedly blew the tier-1 time budget).
    # The topology here is named EXPLICITLY, so nothing from the
    # metadata server is needed: tell libtpu to skip it. setdefault
    # only — a real TPU VM that pre-set it stays authoritative.
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "true")
    try:
        from jax.experimental import topologies
        return topologies.get_topology_desc(platform="tpu",
                                            topology_name=name)
    except Exception:  # noqa: BLE001 — absence of libtpu, bad name…
        return None


def _single_chip_sharding(topo):
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
    mesh = Mesh(np.array(topo.devices[:1]), ("d",))
    return NamedSharding(mesh, PartitionSpec())


def aot_compile(fn, arg_specs: tuple, label: str,
                out_dir: Optional[str] = None,
                topo=None) -> dict:
    """AOT-compile `fn(*arg_specs)` for one v5e chip; return the
    compiler's verdict and cost analysis, optionally writing the
    StableHLO and optimized-HLO artifacts (gzipped) to out_dir."""
    import jax
    t0 = time.monotonic()
    topo = topo or tpu_topology()
    if topo is None:
        return {"label": label, "ok": False,
                "error": "no TPU topology available (libtpu missing)"}
    sh = _single_chip_sharding(topo)
    try:
        n_args = len(arg_specs)
        # one-shot AOT evidence path: a fresh lower+compile per call
        # is the point here, not a hot-loop footgun
        lowered = jax.jit(fn, in_shardings=(sh,) * n_args,  # jaxlint: ok(J003)
                          out_shardings=sh).lower(*arg_specs)
        compiled = lowered.compile()
    except Exception as e:  # noqa: BLE001 — a kernel that fails to
        #                     lower for TPU is exactly what to report
        return {"label": label, "ok": False,
                "error": f"{type(e).__name__}: {e}"[:400]}
    compile_s = time.monotonic() - t0
    ca = compiled.cost_analysis() or {}
    if isinstance(ca, (list, tuple)):
        # newer jax returns one analysis dict per device/computation
        ca = ca[0] if ca else {}
    flops = float(ca.get("flops", 0.0))
    byts = float(ca.get("bytes accessed", 0.0))
    t_compute = flops / V5E_PEAK_BF16_FLOPS
    t_memory = byts / V5E_PEAK_HBM_BYTES
    t_bound = max(t_compute, t_memory)
    res: dict[str, Any] = {
        "label": label, "ok": True,
        "target": V5E_NAME,
        "device_kind": topo.devices[0].device_kind,
        "compile_s": round(compile_s, 2),
        # Verbatim compiler cost analysis.  Two caveats, verified by
        # compiling the same kernel at chunk=1/64/1024 (identical
        # numbers): HloCostAnalysis counts a while-loop body ONCE, and
        # it charges gathers/scatters at full-operand width — so for
        # the search kernels these are per-ROUND numbers and `bytes`
        # is a conservative upper bound on real traffic.
        "compiler_flops": flops,
        "compiler_bytes_accessed": byts,
        "compiler_note": ("loop body counted once; scatter/gather "
                          "charged at full-operand width"),
        "arithmetic_intensity": round(flops / max(byts, 1.0), 6),
        "compiler_roofline_time_s": t_bound,
        "roofline_bound": ("compute" if t_compute >= t_memory
                           else "memory"),
    }
    if out_dir:
        try:
            os.makedirs(out_dir, exist_ok=True)
            stablehlo = lowered.as_text()
            hlo = compiled.as_text()
            for suffix, text in (("stablehlo.mlir", stablehlo),
                                 ("optimized.hlo", hlo)):
                path = os.path.join(out_dir, f"{label}.{suffix}.gz")
                with gzip.open(path, "wt") as f:
                    f.write(text)
            res["artifacts"] = sorted(
                p for p in os.listdir(out_dir) if p.startswith(label))
        except OSError as e:
            # a full disk must not discard the compile verdict itself
            res["artifacts_error"] = str(e)[:200]
    return res


# -- kernel-specific spec builders ------------------------------------------

def _wgl_consts_spec(n_pad: int, ic_pad: int, S: int, O: int):
    import jax
    import jax.numpy as jnp
    v = lambda shape: jax.ShapeDtypeStruct(shape, jnp.int32)  # noqa: E731
    # sufminret carries one extra slot (encode.py pads a suffix-min
    # sentinel past the last op), and the kernels now stack it into
    # the fused meta table, so the spec must match exactly
    return (v((n_pad,)), v((n_pad,)), v((n_pad,)), v((n_pad + 1,)),
            v((ic_pad,)), v((ic_pad,)), v((S, O)), v(()), v(()), v(()))


def _wgl_analytic(K: int, W: int, ic: int, probes: int = 4) -> dict:
    """Roofline in the kernel's OWN traffic currency (the same one the
    runtime util blocks report): per round the search processes K
    beam rows x (W + ic) successor columns, each costing ~probes x 16 B
    of memo-table traffic — the dominant stream (ops/wgl.py util
    accounting).  Bandwidth-bound time per round against v5e HBM gives
    a modeled configs/s CEILING (real rounds also pay sort/dispatch)."""
    bytes_per_round = K * (W + ic) * probes * 16
    t_round = bytes_per_round / V5E_PEAK_HBM_BYTES
    return {"analytic_bytes_per_round": bytes_per_round,
            "analytic_round_time_s": t_round,
            "modeled_configs_per_s_ceiling": int(K / t_round),
            # round-4 calibration: the measured v5e point sits ~10^3-4
            # below this ceiling — the real rounds are LATENCY-bound
            # (serialized gather/scatter dependency chains), not
            # bandwidth-bound. The ceiling stays as compile-level
            # evidence; bench.py's tpu_measured block prints the
            # measured configs/s and the model-error factor beside it.
            "model_status": "uncalibrated bandwidth ceiling; see "
                            "BENCH tpu_measured.model_error_x"}


def wgl32_case(n_pad: int = 16384, ic_pad: int = 8, S: int = 1024,
               O: int = 16, K: int = 16, H: int = 1 << 23,
               B: int = 1 << 18, chunk: int = 4096, W: int = 8,
               pack: bool = True) -> tuple:
    """The headline shape: a 10k-op cas-register history (n_pad 2^14,
    register state space, narrow window) through the bitmask kernel —
    compiled with the ACCEL layout and chunk size the chip actually
    runs (accel=True; the host layout differs, see wgl32 docstring).
    `pack` mirrors the runtime default: a 10k-op history's event
    times fit int16, so the grand-table gather runs half-width."""
    import jax
    from .adapt import LADDER32
    from .wgl32 import _build_search32
    init_fn, chunk_fn = _build_search32(n_pad, ic_pad, S, O, K, H, B,
                                        chunk, probes=4, W=W,
                                        accel=True, pack=pack)
    carry_spec = jax.eval_shape(init_fn, 0)
    return chunk_fn, (_wgl_consts_spec(n_pad, ic_pad, S, O), carry_spec), \
        {"K": K, "W": W, "chunk": chunk, "packed_tables": pack,
         "ladder": list(LADDER32),
         **_wgl_analytic(K, W, ic_pad)}


def precompile_wgl_ladder(*, n_pad: int, ic_pad: int, S: int, O: int,
                          H: int = 1 << 23, B: int = 1 << 18,
                          chunk: int = 1024, probes: int = 4,
                          W: int = 8, L: int = 0, accel: bool = False,
                          depth: int = 1, pack: bool = False,
                          ladder: Optional[tuple] = None) -> dict:
    """Backend-compile every adaptive-ladder bucket for one shape
    bucket, ahead of traffic — the checker-as-a-service warm-up
    (ROADMAP item 1) and the CI ladder smoke both use it: after this
    returns, a search over this shape stays at ZERO recompiles no
    matter which buckets the occupancy policy visits (the
    CompileGuard proof in tests/test_adapt.py). Returns {K:
    compile_seconds}."""
    from .adapt import LADDER32, precompile_ladder
    return precompile_ladder(
        n_pad=n_pad, ic_pad=ic_pad, S=S, O=O, H=H, B=B, chunk=chunk,
        probes=probes, W=W, L=L, accel=accel, depth=depth, pack=pack,
        ladder=ladder or LADDER32, compile_now=True)


def precompile_service_bucket(shape_bucket: dict, *,
                              accel: bool = False) -> dict:
    """precompile_wgl_ladder driven by a service.bucket_for CANONICAL
    shape bucket: derive the exact kernel plan `wgl.check` will run
    for any member of the bucket (via the shared `wgl.derive_plan` —
    the single source of truth, so the warmed executables ARE the
    scheduled ones) and backend-compile every ladder bucket. After
    this returns, any `wgl.check(shape_bucket=bucket)` over the same
    canonical bucket stays at ZERO recompiles — the service warm path
    (jepsen_tpu/service.py) and its restart re-warm both use it, and
    it is the autopilot's D001 compile-storm actuator (the
    "warm-bucket" row of jepsen_tpu/autopilot.py's policy table warms
    the offending canonical bucket through this path and verifies at
    zero further compiles); scripts/service_smoke.py carries the
    CompileGuard proof. Returns {K: compile_seconds}."""
    from . import wgl as wgl_mod

    b = shape_bucket
    w_eff = int(b["w_eff"])
    wide = w_eff > 32
    # any window_raw on the right side of the 32 branch point yields
    # this bucket's plan: derive_plan maxes W_eff with the bucket's
    window_raw = w_eff if wide else min(32, w_eff)
    plan = wgl_mod.derive_plan(
        window_raw=window_raw, W=(w_eff if wide else 32),
        ic_pad=int(b["ic_pad"]),
        n=int(b.get("n_cap") or b["n_pad"]),
        n_info=int(b["ic_pad"]), accel=accel, shape_bucket=b)
    return precompile_wgl_ladder(
        n_pad=int(b["n_pad"]), ic_pad=plan["ic_eff"],
        S=int(b["S"]), O=int(b["O"]), H=plan["H"], B=plan["B"],
        chunk=plan["chunk"], W=plan["W_eff"], L=plan["L"],
        accel=accel, depth=plan["depth"], pack=bool(b.get("pack")),
        ladder=tuple(plan["ladder"] or plan["buckets"]))


def precompile_mesh_plan(shape_bucket: dict, mesh=None, *,
                         lanes_per_device: Optional[int] = None,
                         n_keys: Optional[int] = None,
                         chunk: int = 1024, model_name: str = "any",
                         save: bool = True) -> dict:
    """precompile_wgl_ladder's sibling for the mesh fan-out
    (parallel/mesh.py): backend-compile every executable the lane
    scheduler may touch for one shared shape bucket — each adaptive-
    ladder bucket's vmapped kernel, the jitted init + selective lane
    reset, and the adjacent-bucket frontier migrations. After this
    returns, a `check_mesh` over the same bucket stays at ZERO
    recompiles no matter what the scheduler does (retire/refill,
    rebucket, steal) — the CompileGuard proof in
    scripts/mesh_smoke.py. The plan is registered in `fs_cache` keyed
    on (model, W, K, lane shapes, mesh axes), so a fresh process can
    re-warm the same plans before traffic
    (`precompile_cached_mesh_plans`; pair with the persistent jax
    compilation cache to skip the XLA work too). `mesh` defaults to
    every visible device on a 1-D "keys" axis. Pass `n_keys` (or an
    explicit `lanes_per_device`) matching the traffic you are warming
    for: the batch width is part of the executable shape, so a warm
    at the wrong lane count compiles a never-used kernel set
    (`mesh.lanes_for` is the scheduler's own derivation). Returns
    {K: compile_seconds}."""
    from ..parallel import mesh as mesh_mod

    if mesh is None:
        from ..parallel.batched import default_mesh
        mesh = default_mesh()
    return mesh_mod.warm_plan(
        shape_bucket, mesh=mesh, lanes_per_device=lanes_per_device,
        n_keys=n_keys, chunk=chunk, model_name=model_name, save=save)


def precompile_service_plan(shape_bucket: dict, *, bucket_key,
                            model_name: Optional[str] = None,
                            accel: bool = False,
                            mesh_layout: Optional[dict] = None,
                            save: bool = True) -> dict:
    """ONE warm for the service plane: the serial ladder
    (`precompile_service_bucket`) AND — when a mesh layout is given —
    the lane-group plan (`precompile_mesh_plan`) for the SAME canonical
    bucket, registered as a single fs_cache entry under
    ("service-plan", model, key). The warmed executables must BE the
    scheduled ones: `service._serve_batch` routes coalesced batches
    through `check_mesh(shape_bucket=<canonical bucket>)` at exactly
    this lane layout, so both the mesh path and the serial fallback
    stay at zero recompiles against this one registry entry
    (`Service.rewarm` replays it on restart). `mesh_layout` is
    {"n_devices": int, "lanes_per_device": int, "chunk": int} —
    lanes pinned to the service's FULL batch width (and the mesh to
    its `n_devices` ceiling) so every batch of the bucket, whatever
    its n, reuses one executable set. Returns
    {"serial": {K: s}, "mesh": {K: s} | None}."""
    import time as _time_mod

    out: dict = {"serial": precompile_service_bucket(
        shape_bucket, accel=accel), "mesh": None}
    layout = None
    if mesh_layout:
        from ..parallel.batched import default_mesh
        mesh = default_mesh(
            n_devices=mesh_layout.get("n_devices"))
        nd = int(mesh.devices.size)
        if nd >= 2:
            out["mesh"] = precompile_mesh_plan(
                shape_bucket, mesh,
                lanes_per_device=int(mesh_layout["lanes_per_device"]),
                chunk=int(mesh_layout.get("chunk") or 1024),
                model_name=str(model_name or "any"), save=False)
            layout = {"n_devices": nd,
                      "lanes_per_device":
                          int(mesh_layout["lanes_per_device"]),
                      "chunk": int(mesh_layout.get("chunk") or 1024),
                      "axes": [str(a) for a in mesh.axis_names]}
    if save:
        try:
            from .. import fs_cache
            keystr = "-".join(str(k) for k in tuple(bucket_key))
            fs_cache.save_data(
                ("service-plan", str(model_name), keystr),
                {"bucket": shape_bucket, "key": list(bucket_key),
                 "model": model_name, "mesh": layout,
                 "t": round(_time_mod.time(), 3)})
        except Exception:  # noqa: BLE001 — the registry is a warm-up
            pass           # accelerant, never a correctness gate
    return out


def precompile_cached_mesh_plans(mesh=None) -> list:
    """Re-warm every mesh plan earlier traffic registered in fs_cache
    (`precompile_mesh_plan(save=True)`): the service restart path —
    a fresh process walks the ("mesh-plan",) registry and backend-
    compiles each recorded (bucket, lanes, axes) plan before traffic
    arrives. Plans whose recorded device count no longer matches the
    live mesh are skipped (their executables would never be used).
    Returns [{key shapes..., "compile_s": {K: s}}] per warmed plan."""
    from .. import fs_cache
    from ..parallel import mesh as mesh_mod

    if mesh is None:
        from ..parallel.batched import default_mesh
        mesh = default_mesh()
    nd = int(mesh.devices.size)
    out = []
    for plan in fs_cache.list_data(("mesh-plan",)):
        if not isinstance(plan, dict) or "bucket" not in plan:
            continue
        if int(plan.get("n_devices") or 0) != nd:
            continue
        try:
            compile_s = mesh_mod.warm_plan(
                plan["bucket"], mesh=mesh,
                lanes_per_device=plan.get("lanes_per_device"),
                chunk=int(plan.get("chunk") or 1024),
                model_name=plan.get("model") or "any", save=False)
        except Exception:  # noqa: BLE001 — one stale plan must not
            continue       # block the others' warm-up
        out.append({"model": plan.get("model"),
                    "bucket": plan["bucket"],
                    "lanes_per_device": plan.get("lanes_per_device"),
                    "compile_s": compile_s})
    return out


def precompile_elle_closure(shape_bucket: dict,
                            kernels: Optional[tuple] = None) -> dict:
    """precompile_wgl_ladder's sibling for the Elle cycle engines:
    backend-compile every closure kernel the router might pick for one
    shape bucket, ahead of traffic — the checker-as-a-service warm
    path (ROADMAP item 1) and bench's elle configs both use it. After
    this returns, an elle check over the same shape stays at ZERO
    recompiles no matter which kernel the shape router lands on (the
    CompileGuard proof in tests/test_elle_build.py).

    `shape_bucket` is elle/tpu.shape_bucket_for(tensors) — or any dict
    with the same {"trim": ..., "dense": ...} layout (the "sharded"
    sub-bucket rides along for shapes past the single-chip caps; its
    shard count is NOT stored in the bucket but resolved from the
    LIVE fleet here, so one plan record rewarms correctly on any
    replica's fleet width — a too-narrow fleet simply skips the
    sharded compile instead of building an executable it cannot run).
    `kernels` defaults to the platform's plausible picks: ("trim",)
    plus, on an accelerator, the cost-analysis squaring choice.
    Returns {kernel: compile_seconds}."""
    from ..elle import tpu as elle_tpu
    from ..util import safe_backend

    if kernels is None:
        kernels = ("trim",)
        if safe_backend() not in (None, "cpu"):
            pick, _sel = elle_tpu._squaring_select(
                int(shape_bucket.get("n") or 0))
            kernels = ("trim", pick)
    out: dict = {}
    for k in kernels:
        if k == "trim":
            n_pad, d_in, d_out, p_pad, use_rt, use_proc = \
                shape_bucket["trim"]
            _fn, compile_s = elle_tpu._compiled_trim(
                n_pad, d_in, d_out, len(elle_tpu.SUBSETS), p_pad,
                use_rt, use_proc)
        elif k == "packed":
            d = shape_bucket["dense"]
            _fn, compile_s = elle_tpu._compiled_packed(
                d["n_pad"], d["q_pad"], len(elle_tpu.SUBSETS),
                d["iters"])
        elif k == "bf16":
            d = shape_bucket["dense"]
            _fn, compile_s = elle_tpu._compiled(
                d["n_pad"], d["e_pad"], d["q_pad"],
                len(elle_tpu.SUBSETS), d["iters"])
        elif k == "sharded":
            d = shape_bucket.get("sharded") or shape_bucket["dense"]
            from ..parallel.mesh import word_shard_count
            ns = word_shard_count(d.get("w", d["n_pad"] // 32))
            if ns < 1:
                continue
            _fn, _mesh, compile_s = elle_tpu._compiled_sharded(
                d["n_pad"], d["q_pad"], len(elle_tpu.SUBSETS),
                d["iters"], ns)
        else:
            raise ValueError(f"unknown elle kernel {k!r}")
        out[k] = round(compile_s, 3)
    return out


def wgln_case(n_pad: int = 4096, ic_pad: int = 8, S: int = 256,
              O: int = 16, K: int = 1024, H: int = 1 << 23,
              B: int = 1 << 20, chunk: int = 512, W: int = 96,
              L: int = 3) -> tuple:
    """The adversarial-wave shape: W raw 71 -> 96 padded, 3 uint32
    lanes, production beam — the 2.2M-config bench config's kernel,
    compiled with the ACCEL layout and chunk size the chip runs."""
    import jax
    from .wgln import _build_searchN
    init_fn, chunk_fn = _build_searchN(n_pad, ic_pad, S, O, K, H, B,
                                       chunk, probes=4, W=W, L=L,
                                       accel=True)
    carry_spec = jax.eval_shape(init_fn, 0)
    return chunk_fn, (_wgl_consts_spec(n_pad, ic_pad, S, O), carry_spec), \
        {"K": K, "W": W, "L": L, "chunk": chunk,
         **_wgl_analytic(K, W, ic_pad)}


def elle_case(n_pad: int = 4096, e_pad: int = 16384, q_pad: int = 256,
              n_sub: int = 4) -> tuple:
    """Closure-by-squaring at the capacity its docstring sizes (8k txns
    -> n_pad 4096 per shard bucket), bf16 on the MXU — the dtype the
    kernel itself picks for a TPU backend (elle/tpu.py:96)."""
    import jax
    import jax.numpy as jnp
    from ..elle.tpu import make_closure_kernel
    iters = max(1, (n_pad - 1).bit_length())
    kernel = make_closure_kernel(n_pad, n_sub, iters, jnp.bfloat16)
    specs = (jax.ShapeDtypeStruct((e_pad,), jnp.int32),
             jax.ShapeDtypeStruct((e_pad,), jnp.int32),
             jax.ShapeDtypeStruct((n_sub, e_pad), jnp.float32),
             jax.ShapeDtypeStruct((q_pad,), jnp.int32),
             jax.ShapeDtypeStruct((q_pad,), jnp.int32))
    # The closure is iters dense (n_sub, N, N) @ (N, N) squarings —
    # pure MXU work.  The compiler counts one fori_loop iteration;
    # multiplying back out gives the full-call model.
    total_flops = 2.0 * n_sub * iters * n_pad ** 3
    t_full = total_flops / V5E_PEAK_BF16_FLOPS
    return kernel, specs, {
        "n_pad": n_pad, "n_sub": n_sub, "iters": iters,
        "analytic_matmul_flops": total_flops,
        "modeled_full_call_time_s": round(t_full, 5),
        # an UPPER BOUND, not a claim: the bench's tpu_measured block
        # prints the achieved TFLOP/s / MFU next to this model (round-4
        # VERDICT #4 — measured v5e point: ~50 TFLOP/s, ~25% MFU)
        "modeled_mfu_upper_bound": 1.0,
        "modeled_tflops_at_peak": round(V5E_PEAK_BF16_FLOPS / 1e12, 1)}


def evidence(out_dir: Optional[str] = None,
             include_wgln: bool = True) -> dict:
    """AOT-compile the flagship kernels for TPU v5e and return the
    BENCH `tpu_aot` block.  ~1-2 min of pure host compile work.
    The persistent jax cache is bypassed for these compiles: TPU
    executables serialized by a compile-only client can't deserialize
    ("DeserializeLoadedExecutable not implemented" warnings observed),
    so caching them is pure pollution."""
    import jax
    old_cache = jax.config.jax_compilation_cache_dir
    if old_cache:
        jax.config.update("jax_compilation_cache_dir", None)
    try:
        return _evidence(out_dir, include_wgln)
    finally:
        if old_cache:
            jax.config.update("jax_compilation_cache_dir", old_cache)


def _evidence(out_dir: Optional[str], include_wgln: bool) -> dict:
    topo = tpu_topology()
    if topo is None:
        return {"ok": False,
                "error": "no TPU topology available (libtpu missing)"}
    out: dict[str, Any] = {"ok": True, "topology": _TOPOLOGY,
                           "device_kind": topo.devices[0].device_kind,
                           "peaks": {"bf16_flops": V5E_PEAK_BF16_FLOPS,
                                     "hbm_bytes_per_s": V5E_PEAK_HBM_BYTES},
                           "kernels": {}}
    cases = [("wgl32_headline", wgl32_case)]
    if include_wgln:
        cases.append(("wgln_adversarial", wgln_case))
    cases.append(("elle_closure_8k", elle_case))
    for label, case in cases:
        try:
            fn, specs, meta = case()
        except Exception as e:  # noqa: BLE001
            out["kernels"][label] = {"ok": False,
                                     "error": f"build: {e}"[:300]}
            continue
        try:
            r = aot_compile(fn, specs, label, out_dir=out_dir, topo=topo)
        except Exception as e:  # noqa: BLE001 — one kernel's failure
            r = {"ok": False,     # must not discard the others' results
                 "error": f"{type(e).__name__}: {e}"[:300]}
        r.update(meta)
        out["kernels"][label] = r
    out["all_ok"] = all(k.get("ok") for k in out["kernels"].values())
    return out


def main() -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    out_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "artifacts",
        "tpu_aot")
    print(json.dumps(evidence(out_dir=out_dir), indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
