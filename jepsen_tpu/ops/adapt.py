"""Occupancy-driven adaptive frontier scheduling for the WGL kernels.

ROADMAP item 5: the PR-8 occupancy observatory showed the vmap lanes
mostly empty on the bench configs (frontier_fill 0.14-0.44 at the
fixed K=16 beam) — every round still pays the full K x (W + ic)
successor expansion for a wavefront of 2-7 configs. Measured on the
cpu backend (cas_register 10k, the headline shape): K=2 decides in
0.39 s at fill 0.9999 where K=16 takes 1.61 s at fill 0.79 — a 4x
wall win from *shrinking* the beam to the wavefront. The flip side is
the exhaustive regime (invalid / adversarial histories must expand
the whole reachable space): there rounds ~= total/K, so a narrow
beam serializes and breadth wins — the old `wgl._ESCALATE_AT` jump
to K=512 was exactly that observation, hard-coded.

This module generalizes both into a **bucket ladder**: a small set of
pre-compilable frontier capacities (one XLA executable per bucket,
`functools.lru_cache`d by the kernel builders, so a warm ladder run
stays inside a CompileGuard zero-compile budget) and a host-side
hysteresis **policy** that picks the bucket BETWEEN device chunks
from the same packed poll summary the host already reads — no extra
transfers, no host syncs inside the hot loop, no retraces inside
`lax.while_loop`.

Policy signals (all host-side, per poll):

  * **grow** when the search looks exhaustive: configs explored pass
    an n_ok-relative threshold that quadruples per level (a valid
    history explores ~2-3 x n_ok configs total and never trips it;
    an exhaustive one blows through every level), or the backlog
    nears capacity (overflow turns False into "unknown" — jump to
    the top bucket before that);
  * **shrink** when the beam runs persistently sparse: mean occupied
    lanes fit inside HALF the next bucket down for `patience`
    consecutive polls (hysteresis — a single sparse chunk on an
    oscillating wavefront must not thrash the ladder, see
    tests/test_adapt.py);
  * a bucket abandoned by a shrink-then-regrow within the thrash
    window is burned for the rest of the search.

The policy is pure Python over integers — unit-testable with no
device, no jax import.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

# The narrow-kernel (wgl32) ladder. Bottom bucket 2: the measured
# sweet spot for valid histories (wavefront 2-4 configs on the
# register/cas/mutex matrix — see module docstring); top bucket 512:
# the proven exhaustion beam (`wgl._K_BIG`). Geometric x8 spacing
# keeps the ladder at 4 executables.
LADDER32 = (2, 16, 64, 512)

# Explored-configs growth schedule: level i -> i+1 when
# explored >= max(ESC_BASE, ESC_MULT * n_ok) * ESC_STEP**i.
# Calibration: a valid history explores ~2.6 x n_ok configs, so
# 6 x n_ok never fires on one; the 40k floor keeps tiny adversarial
# histories (n_ok ~ 100, reachable space ~ millions) from crawling
# at the bottom bucket for long.
ESC_BASE = 40_000
ESC_MULT = 6
ESC_STEP = 4


def enabled(default: bool = True) -> bool:
    """The adaptive kill-switch: JEPSEN_TPU_ADAPTIVE=0 pins the old
    fixed-K behavior (and the legacy one-shot escalation)."""
    v = os.environ.get("JEPSEN_TPU_ADAPTIVE")
    if v is None:
        return default
    return v not in ("0", "false", "no")


def ladder_for(k_max: int, k_min: int = 2, step: int = 8) -> tuple:
    """A geometric bucket ladder [k_min .. k_max] (k_max always
    included), for kernels whose capacity ceiling is platform-derived
    (the packed wide-window path). Powers of two, ascending."""
    k_max = max(1, int(k_max))
    k_min = max(1, min(int(k_min), k_max))
    out = []
    k = k_min
    while k < k_max:
        out.append(k)
        k *= step
    out.append(k_max)
    return tuple(out)


def recommend(ladder: tuple, occupied: float) -> int:
    """The stateless per-lane hint: the smallest bucket that holds
    ~2x the observed mean occupancy (the batched vmap path records
    these per lane — it cannot re-bucket a single lane of a lockstep
    batch, but the hint names the capacity each lane actually
    needs)."""
    want = max(1.0, 2.0 * float(occupied))
    for k in ladder:
        if k >= want:
            return k
    return ladder[-1]


@dataclass
class Decision:
    """One policy verdict, recorded into the `wgl_adapt` series."""

    switch: bool
    to_k: int
    reason: str


# ---------------------------------------------------------------------------
# ladder pin — the autopilot's D002/D003 actuator
# ---------------------------------------------------------------------------
# A module-level pin that every live Policy consults per poll: while a
# pin is set, the policy forces one rebucket to the pinned capacity
# (reason "pinned" on the wgl_adapt series) and then HOLDS there —
# the hysteresis machinery is bypassed, so a fill-collapsed or
# thrashing ladder settles immediately. `unpin_ladder` is the
# rollback half of the autopilot's verify-or-revert contract
# (jepsen_tpu/autopilot.py): reverting the action restores normal
# hysteresis on the very next poll. The pin is process-global on
# purpose — the supervisor acts on the service process, and a pin
# scoped to one Policy instance would miss the next search's fresh
# Policy.

_PIN_LOCK = threading.Lock()
_PIN: Optional[dict] = None


def pin_ladder(k: int, reason: str = "autopilot") -> dict:
    """Pin every live (and future) Policy to bucket `k`. Returns the
    pin record {k, reason, t}; re-pinning replaces the prior pin."""
    global _PIN
    pin = {"k": int(k), "reason": str(reason),
           "t": round(time.time(), 3)}
    with _PIN_LOCK:
        _PIN = pin
    return pin


def unpin_ladder() -> Optional[dict]:
    """Clear the pin (the autopilot's rollback); returns the pin that
    was cleared, None when none was set."""
    global _PIN
    with _PIN_LOCK:
        pin, _PIN = _PIN, None
    return pin


def ladder_pin() -> Optional[dict]:
    """The active pin record, None when the ladder floats freely."""
    with _PIN_LOCK:
        return _PIN


@dataclass
class Policy:
    """Hysteresis bucket selection from per-poll occupancy inputs.

    `observe()` is called once per device poll with cumulative
    explored plus this chunk's round/expansion deltas and the
    end-of-chunk frontier/backlog counts; it returns a `Decision`.
    The caller owns the actual kernel swap + carry migration
    (`wgl._search_loop` / `migrate_frontier`).
    """

    ladder: tuple
    n_ok: int
    backlog_cap: int            # B: jump to top before overflow
    start_k: Optional[int] = None
    esc_base: int = ESC_BASE
    esc_mult: int = ESC_MULT
    esc_step: int = ESC_STEP
    shrink_frac: float = 0.5    # occupied <= frac * lower bucket
    patience: int = 2           # consecutive sparse polls to shrink
    level: int = field(init=False)
    sparse_streak: int = field(default=0, init=False)
    burned: set = field(default_factory=set, init=False)
    switches: list = field(default_factory=list, init=False)

    def __post_init__(self):
        self.ladder = tuple(sorted(set(int(k) for k in self.ladder)))
        if not self.ladder:
            raise ValueError("empty ladder")
        # an active pin outranks the caller's start bucket: a fresh
        # Policy (the next search / the next service batch) starts AT
        # the pinned capacity instead of rediscovering the collapse
        pin = ladder_pin()
        if pin is not None and int(pin["k"]) in self.ladder:
            self.start_k = int(pin["k"])
        self.level = (self.ladder.index(self.start_k)
                      if self.start_k in self.ladder else 0)

    @property
    def k(self) -> int:
        return self.ladder[self.level]

    def _esc_threshold(self) -> int:
        base = max(self.esc_base, self.esc_mult * max(self.n_ok, 1))
        return base * (self.esc_step ** self.level)

    def observe(self, *, explored: int, rounds_delta: int,
                explored_delta: int, frontier: int,
                backlog: int) -> Decision:
        k = self.k
        top = len(self.ladder) - 1
        # an autopilot pin outranks every signal EXCEPT backlog
        # pressure (a pin must not turn a False verdict into
        # "backlog-overflow"): force one switch to the pinned bucket,
        # then hold there until unpinned
        pin = ladder_pin()
        if pin is not None and int(pin["k"]) in self.ladder \
                and backlog < max(1, self.backlog_cap // 8):
            lvl = self.ladder.index(int(pin["k"]))
            if lvl != self.level:
                return self._switch(lvl, "pinned")
            return Decision(False, k, "pinned")
        # overflow prevention outranks everything: a backlog within
        # 1/8 of capacity risks turning a False verdict into
        # "backlog-overflow"/unknown — take the whole top beam now
        if self.level < top and backlog >= max(1, self.backlog_cap // 8):
            return self._switch(top, "backlog-pressure")
        # exhaustion regime: explored blew through this level's
        # threshold — the search is enumerating, breadth amortizes
        if self.level < top and explored >= self._esc_threshold():
            return self._switch(self.level + 1, "explored-threshold")
        # sparse beam: mean occupied lanes fit well inside the next
        # bucket down, for `patience` consecutive polls
        if self.level > 0 and rounds_delta > 0:
            occupied = explored_delta / rounds_delta
            lower = self.ladder[self.level - 1]
            fits = (occupied <= self.shrink_frac * lower
                    and frontier <= lower
                    and self.level - 1 not in self.burned)
            self.sparse_streak = self.sparse_streak + 1 if fits else 0
            if self.sparse_streak >= self.patience:
                return self._switch(self.level - 1, "sparse-frontier")
        else:
            self.sparse_streak = 0
        return Decision(False, k, "hold")

    def _switch(self, new_level: int, reason: str) -> Decision:
        # shrink-then-regrow inside the thrash window burns the
        # abandoned lower bucket: oscillating wavefronts settle at
        # the wider bucket instead of ping-ponging executables
        if (new_level > self.level and self.switches
                and self.switches[-1][1] < self.switches[-1][0]):
            self.burned.add(self.level)
        self.switches.append((self.level, new_level, reason))
        self.level = new_level
        self.sparse_streak = 0
        return Decision(True, self.k, reason)

    def summary(self) -> dict:
        """The `util.adapt` block: what the ladder did this search."""
        return {
            "ladder": list(self.ladder),
            "final_K": self.k,
            "switches": len(self.switches),
            "path": [[self.ladder[a], self.ladder[b], r]
                     for a, b, r in self.switches],
            "buckets_visited": sorted(
                {self.ladder[0]} | {self.ladder[b]
                                    for _, b, _ in self.switches}),
        }


def migrate_frontier(carry, k_new: int):
    """Re-bucket a packed wgl32/wgln carry between chunks: the
    frontier (K, C) grows by zero-padding (rows past fr_cnt are
    inert) or shrinks by slicing. The caller must only shrink when
    the polled fr_cnt <= k_new (the policy's sparse rule guarantees
    it); backlog/memo/flags/stats/ring ride along untouched. A couple
    of device ops per switch, outside the jitted loop — no retrace,
    no host sync."""
    import jax.numpy as jnp

    fr = carry[0]
    k_old = fr.shape[0]
    if k_new == k_old:
        return carry
    if k_new > k_old:
        fr = jnp.pad(fr, [(0, k_new - k_old), (0, 0)])
    else:
        fr = fr[:k_new]
    return (fr, *carry[1:])


def migrate_frontier_batch(carry, k_new: int):
    """`migrate_frontier` for a VMAPPED carry: the frontier is
    (Bk, K, C) — lane axis in front — so the pad/slice runs on axis 1.
    Same contract as the single-search migration: only shrink when
    every live lane's polled fr_cnt fits k_new (the mesh scheduler's
    sparse rule guarantees it; retired lanes are exempt — their
    kernels no longer expand); memo/backlog/flags/stats/ring ride
    along untouched, so frontier state crosses bucket switches AND
    shard migrations without a restart."""
    import jax.numpy as jnp

    fr = carry[0]
    k_old = fr.shape[1]
    if k_new == k_old:
        return carry
    if k_new > k_old:
        fr = jnp.pad(fr, [(0, 0), (0, k_new - k_old), (0, 0)])
    else:
        fr = fr[:, :k_new]
    return (fr, *carry[1:])


def precompile_ladder(*, n_pad: int, ic_pad: int, S: int, O: int,
                      H: int, B: int, chunk: int, probes: int,
                      W: int, L: int = 0, accel: bool = False,
                      depth: int = 1, ladder: tuple = LADDER32,
                      pack: bool = False,
                      compile_now: bool = False) -> dict:
    """Warm every ladder bucket's kernel for one shape bucket.

    By default this only populates the builders' lru caches (tracing
    is deferred to first call); `compile_now=True` additionally runs
    each bucket's kernel ONCE with a zero config budget — the
    while-loop exits before its first round, so the call costs pure
    trace + XLA compile and leaves the jit call cache (and, when
    enabled, the persistent compilation cache) warm. A later real
    search over this shape bucket then stays at zero recompiles no
    matter which buckets the policy visits — the
    checker-as-a-service warm-up path (`ops/aot.py
    precompile_wgl_ladder`). Returns {K: compile_seconds | None}."""
    import time as _t

    out: dict = {}
    for k in ladder:
        if L:
            from .wgln import compiled_searchN
            init_fn, chunk_jit = compiled_searchN(
                n_pad=n_pad, ic_pad=ic_pad, S=S, O=O, K=k, H=H, B=B,
                chunk=chunk, probes=probes, W=W, L=L, accel=accel,
                pack=pack)
        else:
            from .wgl32 import compiled_search32
            init_fn, chunk_jit = compiled_search32(
                n_pad=n_pad, ic_pad=ic_pad, S=S, O=O, K=k, H=H, B=B,
                chunk=chunk, probes=probes, W=W, accel=accel,
                depth=depth, pack=pack)
        if not compile_now:
            out[k] = None
            continue
        import jax
        import jax.numpy as jnp

        t0 = _t.monotonic()
        z1 = jnp.zeros((n_pad,), jnp.int32)
        consts = (z1, z1, z1, jnp.zeros((n_pad + 1,), jnp.int32),
                  jnp.zeros((ic_pad,), jnp.int32),
                  jnp.zeros((ic_pad,), jnp.int32),
                  jnp.zeros((S, O), jnp.int32),
                  jnp.int32(0), jnp.int32(0),
                  jnp.int32(0))  # max_cfg 0: zero rounds run
        carry, summary = chunk_jit(consts, init_fn(0))
        # per-bucket warm compile: one sync per executable IS the job
        jax.block_until_ready(summary)  # jaxlint: ok(J007)
        del carry
        out[k] = round(_t.monotonic() - t0, 3)
    return out
