"""Polynomial-time FIFO-queue linearizability for distinct-value
complete histories.

The generic WGL search (ours and JVM knossos alike — the engines behind
`checker/linearizable` with `model/fifo-queue`) explodes on queue
histories: concurrent enqueues fork queue-content states that only
reconcile when the queue drains, so even 200-op histories DNF. But for
the common disciplined workload — every enqueue value distinct, every
dequeue's value known, history complete (no crashed ops), no
dequeue-from-empty — queue linearizability is decidable in polynomial
time (Gibbons & Korach, "Testing Shared Memories", SIAM J. Comput.
1997, establish the tractable-cases landscape; this is the classic
tractable case).

Characterization used here. Work over *values*: enq(v) has interval
[ei_v, er_v], deq(v) (if present) [di_v, dr_v]. In any linearization
the sequence of dequeued values equals the sequence of their enqueues
(FIFO), so one total order σ over values governs both. σ must respect
every forced precedence:

  (1) er(enq v) < ei(enq w)          -> v before w   (enq precedence)
  (2) dr(deq v) < di(deq w)          -> v before w   (deq precedence)
  (3) dr(deq v) < ei(enq w)          -> v before w   (deq-v precedes
                                                      enq-w entirely)
  (4) v dequeued, u never dequeued   -> v before u   (if u's enqueue
      point preceded v's, FIFO would force u out before v)

plus the pairwise feasibility ei_v < dr_v (the dequeue must be able to
linearize after its enqueue). Any σ acyclic under (1)-(4) is
realizable by an explicit point schedule (greedy earliest-feasible
placement works because each constraint family is an interval order),
so the history is linearizable iff the constraint graph is acyclic.
Acyclicity is tested by greedy topological peeling with heaps —
O(n log n), no quadratic edge materialization — so 100k-op histories
decide in milliseconds where the JVM search times out at 200 ops.

Correctness is established differentially: `tests/test_queuecheck.py`
replays thousands of random small histories (valid and corrupted)
through this checker and the WGL oracle and demands identical verdicts.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import Any, Optional

from ..history import History
from .linprep import prepare


class QueueUnsupported(Exception):
    """History shape outside the fast path (duplicate values, unknown
    dequeue values, crashed ops, dequeue-from-empty, failed ops that
    still need the search's may-skip semantics)."""


@dataclass
class _Val:
    v: Any
    ei: int
    er: int
    di: Optional[int] = None
    dr: Optional[int] = None

    @property
    def dequeued(self) -> bool:
        return self.di is not None


INF_T = 2**62


def _collect(history: History) -> tuple[list, bool]:
    """LinOps -> (per-value records, exact?, op count).

    Open (never-completed / crashed) ops get one-sided handling that is
    sound for True verdicts: an open dequeue is excluded (one legal
    completion choice), an open enqueue whose value is never dequeued
    is excluded (equivalent to placing it last), and an open enqueue
    whose value IS dequeued must have happened, so it is included with
    ret = infinity (exact). When any op was excluded, `exact` is False:
    an invalid verdict must then fall back to the full search, because
    including the dropped op might have rescued the history."""
    ops = prepare(history)
    n_ops = len(ops)
    vals: dict = {}
    open_enqs: dict = {}
    exact = True
    for o in ops:
        if o.f == "enqueue":
            if o.value in vals or o.value in open_enqs:
                raise QueueUnsupported(f"duplicate enqueue {o.value!r}")
            if o.ok:
                vals[o.value] = _Val(o.value, o.inv, o.ret)
            else:
                open_enqs[o.value] = o
        elif o.f == "dequeue":
            if not o.ok:
                exact = False  # excluded open dequeue
            elif o.value is None:
                raise QueueUnsupported("dequeue with unknown value")
        else:
            raise QueueUnsupported(f"op f {o.f!r}")
    for o in ops:
        if o.f != "dequeue" or not o.ok:
            continue
        rec = vals.get(o.value)
        if rec is None:
            oe = open_enqs.pop(o.value, None)
            if oe is not None:
                # the open enqueue definitely happened
                rec = _Val(o.value, oe.inv, INF_T)
                vals[o.value] = rec
            else:
                # dequeued a value never enqueued: plainly invalid
                return ([_Val(o.value, INF_T, INF_T, o.inv, o.ret)],
                        True, n_ops)
        if rec.dequeued:
            raise QueueUnsupported(f"value {o.value!r} dequeued twice")
        rec.di, rec.dr = o.inv, o.ret
    if open_enqs:
        exact = False  # excluded open never-dequeued enqueues
    return list(vals.values()), exact, n_ops


def check(history: History) -> dict:
    """{"valid?": bool, ...}; raises QueueUnsupported outside the fast
    path (callers fall back to the WGL search)."""
    vals, exact, n_ops = _collect(history)
    n = len(vals)
    if n == 0:
        return {"valid?": True, "op_count": n_ops,
                "engine": "queue-poly"}

    def invalid(res: dict) -> dict:
        if not exact:
            # the excluded open ops might have rescued this history;
            # only the full search can tell
            raise QueueUnsupported("invalid with open ops excluded")
        return res

    for r in vals:
        if r.dequeued and not r.ei < r.dr:
            return invalid({"valid?": False, "op_count": n_ops,
                            "engine": "queue-poly",
                            "error": ["dequeue-before-enqueue", r.v]})

    # Topological peel. A remaining value v has no incoming constraint
    # edge iff (minima taken over *remaining* values, self included —
    # self-inclusion is exact because ei<=er and di<=dr make the self
    # conditions vacuous):
    #   v in D:     ei_v <= B            (rule 1, B = min er, all)
    #               ei_v <= A            (rule 3, A = min dr over D)
    #               di_v <= A            (rule 2)
    #   v not in D: ei_v <= B and D empty  (rules 1, 4)
    # Peeling only raises A and B, so eligibility is monotone: a value
    # stages from the ei-ordered heap into the di-ordered heap once
    # ei <= min(A, B), and peels once its di <= A. If no value is
    # eligible, none ever will be — a constraint cycle — invalid.
    # DAG peeling is confluent, so any eligible choice is exhaustive.
    er_heap = [(r.er, i) for i, r in enumerate(vals)]
    dr_heap = [(r.dr, i) for i, r in enumerate(vals) if r.dequeued]
    by_ei = sorted(((r.ei, i) for i, r in enumerate(vals) if r.dequeued),
                   reverse=True)  # pop smallest from the end
    staged: list = []  # (di, idx) for D values whose ei passed
    undeq = sorted(((r.ei, i) for i, r in enumerate(vals)
                    if not r.dequeued), reverse=True)
    heapq.heapify(er_heap)
    heapq.heapify(dr_heap)
    done: set = set()
    order: list = []
    n_deq_left = len(dr_heap)

    def _peek(heap):
        while heap and heap[0][1] in done:
            heapq.heappop(heap)
        return heap[0] if heap else None

    while len(done) < n:
        if n_deq_left:
            a = _peek(dr_heap)[0]
            b = _peek(er_heap)[0]
            thresh = min(a, b)
            while by_ei and by_ei[-1][0] <= thresh:
                _, i = by_ei.pop()
                heapq.heappush(staged, (vals[i].di, i))
            top = _peek(staged)
            if top is None or top[0] > a:
                stuck = ([vals[i].v for _, i in by_ei[-3:]]
                         + [vals[i].v for d, i in staged[:3]
                            if i not in done])
                return invalid({"valid?": False, "op_count": n_ops,
                                "engine": "queue-poly",
                                "error": ["no-linearizable-order",
                                          stuck],
                                "linearized_prefix":
                                    [r.v for r in order[-8:]]})
            _, i = heapq.heappop(staged)
            done.add(i)
            order.append(vals[i])
            n_deq_left -= 1
        else:
            # only never-dequeued enqueues remain: a pure interval
            # order, always acyclic — min-er is always eligible
            while undeq and undeq[-1][1] in done:
                undeq.pop()
            _, i = undeq.pop()
            done.add(i)
            order.append(vals[i])

    return {"valid?": True, "op_count": n_ops, "engine": "queue-poly",
            "order": [r.v for r in order] if n <= 64 else None}
