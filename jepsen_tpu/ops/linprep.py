"""History preprocessing for linearizability checking.

Turns a raw Jepsen-style history (invoke/ok/fail/info events) into a table
of *linearizable operations*, each with an invocation index and a return
index, shared by the Python oracle (`wgl_ref`) and the TPU kernel (`wgl`).

Semantics (matching knossos's treatment, which the reference relies on at
`jepsen/src/jepsen/checker.clj:185-216`):
  * an op that completed :ok happened — it must appear in any linearization;
  * an op that completed :fail did NOT happen — it is excluded entirely;
  * an op that ended :info (or never completed) is in an unknown state —
    it MAY appear at any point after its invocation, or not at all.
    Crashed *reads* are dropped outright: they have no effect on state and
    their result was never observed, so they constrain nothing.

Values of invocations are completed from their :ok completion when the
invocation's value is None (knossos history/complete parity) — this is how
reads acquire their observed value.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Optional

from ..history import History, Op

INF_TIME = 2**62  # return index for ops that never returned


@dataclass(frozen=True)
class LinOp:
    """One linearizable operation."""

    f: Any  # op function (read/write/cas/acquire/...)
    value: Any  # completed value (see module docstring)
    ok: bool  # True: must linearize; False (:info): may linearize
    inv: int  # index of invocation event in the (stripped) history
    ret: int  # index of completion event, or INF_TIME
    process: Any = None
    orig_index: int = -1  # the invocation Op's own .index — the
    #   coordinate users see; inv/ret renumber after nemesis stripping

    def as_op(self) -> Op:
        """The op as seen by Model.step / reported in diagnostics."""
        idx = self.orig_index if self.orig_index >= 0 else self.inv
        return Op("ok" if self.ok else "info", f=self.f, process=self.process,
                  value=self.value, index=idx)


_PREP_MEMO: "OrderedDict[tuple, tuple]" = OrderedDict()
_PREP_LOCK = threading.Lock()
# 2 entries: the memo exists for the gate-probe → admitted-check pair
# (plus one concurrent neighbor); a bigger cap would only pin more
# histories alive in a long-lived serve process
_PREP_CAP = 2


def clear_prepare_memo() -> None:
    """Drop the bounded prepare memo (and the strong refs pinning its
    histories) — for long-lived processes between runs."""
    with _PREP_LOCK:
        _PREP_MEMO.clear()


def prepare(history: History, crashed_read_fs=("read",)) -> list[LinOp]:
    """History -> list of LinOps ordered by invocation index.

    `crashed_read_fs` names op functions that are pure reads (droppable
    when crashed).

    Memoized (bounded, identity-keyed): the preflight admission gate
    probes a history's shapes immediately before the check it admits
    re-prepares the same history — back-to-back callers share one
    pass. The entry holds the history strongly so its id() cannot be
    recycled while cached; hits return a fresh list (LinOps are
    frozen, so sharing them is safe — the list itself is not).
    """
    # len() in the key: History is append-only mutable, so a grown
    # history must miss; the strong ref keeps id() from recycling
    key = (id(history), len(history), tuple(crashed_read_fs))
    with _PREP_LOCK:
        hit = _PREP_MEMO.get(key)
        if hit is not None and hit[0] is history:
            _PREP_MEMO.move_to_end(key)
            return list(hit[1])
    ops: list[LinOp] = []
    pending: dict[Any, tuple[int, Op]] = {}  # process -> (event idx, invoke op)
    for i, op in enumerate(history):
        if op.process == "nemesis":
            continue
        if op.is_invoke:
            if op.process in pending:
                raise ValueError(
                    f"process {op.process!r} invoked twice without completing "
                    f"(events {pending[op.process][0]} and {i})")
            pending[op.process] = (i, op)
        elif op.is_ok or op.is_fail or op.is_info:
            ent = pending.pop(op.process, None)
            if ent is None:
                # Completion without invocation (e.g. nemesis-style markers
                # from clients): ignore.
                continue
            inv_i, inv = ent
            if op.is_fail:
                continue  # did not happen
            value = inv.value if inv.value is not None else op.value
            if op.is_info:
                if inv.f in crashed_read_fs:
                    continue  # crashed read: no effect, no constraint
                ops.append(LinOp(inv.f, inv.value, False, inv_i, INF_TIME,
                                 inv.process, orig_index=inv.index))
            else:
                ops.append(LinOp(inv.f, value, True, inv_i, i, inv.process,
                                 orig_index=inv.index))
    # ops whose processes never completed: crashed
    for inv_i, inv in pending.values():
        if inv.f in crashed_read_fs:
            continue
        ops.append(LinOp(inv.f, inv.value, False, inv_i, INF_TIME,
                         inv.process, orig_index=inv.index))
    ops.sort(key=lambda o: o.inv)
    with _PREP_LOCK:
        _PREP_MEMO[key] = (history, ops)
        while len(_PREP_MEMO) > _PREP_CAP:
            _PREP_MEMO.popitem(last=False)
    return list(ops)


def precedence_masks(ops: list[LinOp]) -> list[int]:
    """pred[i] = bitmask (python int) of ops j that returned before op i was
    invoked — the real-time order constraint: j must be linearized before i.
    O(n log n) via sorting returns."""
    n = len(ops)
    # Sort op ids by return index; walk invocations in order, accumulating
    # the mask of ops whose return precedes the current invocation.
    by_ret = sorted(range(n), key=lambda j: ops[j].ret)
    pred = [0] * n
    acc = 0
    k = 0
    # ops are sorted by inv already
    for i in range(n):
        inv_i = ops[i].inv
        while k < n and ops[by_ret[k]].ret < inv_i:
            acc |= 1 << by_ret[k]
            k += 1
        pred[i] = acc
    return pred
