"""Just-in-time linearization — the knossos `linear` algorithm.

Capability parity with `knossos.linear/analysis`, the second of the
reference's three linearizability engines (selected by
`:algorithm :linear` at jepsen/src/jepsen/checker.clj:199-202). Where
WGL explores linearization orders depth-first from the history's
front, JIT linearization (Lowe, "Testing for linearizability", 2017 —
the algorithm knossos.linear implements) sweeps the *event sequence*
once, maintaining the set of reachable configurations
(linearized-pending-set, model-state) with a memoized config cache:

  * at a call event, the op joins the pending set;
  * at a return event, every configuration must already have (or be
    able to reach, by linearizing pending ops) that op linearized —
    configurations that cannot are pruned; if none survive, the
    history is invalid *at that event*, which pins blame to a specific
    operation (the knossos `:op` in analysis results).

Returned ops are dropped from configuration masks (every surviving
configuration has them), so the cache keys stay small — the moral
equivalent of WGL's window renormalization.

Complements the WGL engines: same verdicts, different search order and
different failure diagnostics, and `competition` semantics can race
them exactly as `knossos.competition` races linear against wgl.

Scope note: crashed (:info) ops never return, so they stay pending to
the end of the sweep and the closure grows exponentially in their
count — knossos.linear has the same cliff. Prefer the WGL engines
(bounded info-masks) for crash-heavy histories; this engine's budget
guards return "unknown" rather than hanging.
"""

from __future__ import annotations

import time as _time
from typing import Optional

from ..history import History
from ..models.core import Model, is_inconsistent
from .linprep import prepare


def _expand(configs: dict, pending_ops: dict, deadline, max_configs,
            explored_box):
    """Closure of configs under linearizing any pending ops: from every
    configuration, linearize each not-yet-linearized pending op in
    every order (deduped by (mask, state))."""
    stack = list(configs.items())
    out = dict(configs)
    while stack:
        (mask, state), path = stack.pop()
        for i, op in pending_ops.items():
            bit = 1 << i
            if mask & bit:
                continue
            s2 = state.step(op)
            if is_inconsistent(s2):
                continue
            key = (mask | bit, s2)
            if key not in out:
                out[key] = path + (i,)
                stack.append((key, out[key]))
                explored_box[0] += 1
                if len(out) > max_configs:
                    raise _Budget("config-limit")
        if deadline is not None and _time.monotonic() > deadline:
            raise _Budget("timeout")
    return out


class _Budget(Exception):
    def __init__(self, cause):
        self.cause = cause


def check(model: Model, history: History,
          time_limit: Optional[float] = None,
          max_configs: int = 2_000_000) -> dict:
    """Decide linearizability by JIT linearization. Returns
    {"valid?": bool | "unknown", ...}; on False, "op" names the return
    event that no configuration could satisfy, and "configs" samples
    the surviving configurations just before the failure."""
    ops = prepare(history)
    n = len(ops)
    if n == 0:
        return {"valid?": True, "op_count": 0, "algorithm": "linear"}
    if n > 1000 and time_limit is None:
        time_limit = 3600.0
    deadline = _time.monotonic() + time_limit if time_limit else None

    # event sequence: (time, kind, op index); calls before returns at
    # equal times would be malformed histories — prepare's inv/ret
    # indexes are unique positions in the original history
    events = []
    for i, o in enumerate(ops):
        events.append((o.inv, 0, i))  # call
        if o.ok:
            events.append((o.ret, 1, i))  # return (crashed never do)
    events.sort()

    # configs: {(mask-over-pending-ids, model-state): path}. The path
    # is the full id sequence in model-step order — a real witnessed
    # linearization prefix, kept for failure diagnostics.
    configs: dict = {(0, model): ()}
    pending: dict = {}  # id -> op (as seen by Model.step)
    explored = [0]

    try:
        for _t, kind, i in events:
            if kind == 0:
                pending[i] = ops[i].as_op()
                continue
            # return of op i: expand closure, keep configs with i done
            configs = _expand(configs, pending, deadline, max_configs,
                              explored)
            bit = 1 << i
            survivors = {k: p for k, p in configs.items() if k[0] & bit}
            if not survivors:
                sample = [{"model": repr(k[1]),
                           "linearized-count": len(p)}
                          for k, p in list(configs.items())[:10]]
                return {"valid?": False, "op_count": n,
                        "algorithm": "linear",
                        "op": ops[i].as_op().to_dict(),
                        "configs": sample,
                        "configs_explored": explored[0],
                        "final_paths": [
                            [ops[j].as_op().to_dict() for j in p][-10:]
                            for p in list(configs.values())[:10]]}
            # renormalize: drop op i from masks (every survivor has it)
            # and from the pending set
            del pending[i]
            configs = {}
            for (mask, state), path in survivors.items():
                key = (mask & ~bit, state)
                if key not in configs or len(path) < len(configs[key]):
                    configs[key] = path
    except _Budget as e:
        return {"valid?": "unknown", "cause": e.cause, "op_count": n,
                "algorithm": "linear",
                "configs_explored": explored[0]}

    # all returns satisfied; crashed ops are optional
    return {"valid?": True, "op_count": n, "algorithm": "linear",
            "configs_explored": explored[0]}
