"""Bitmask fast-path WGL kernel (windows ≤ 32 ok-ops wide).

The general kernel (`wgl.py`) keeps the linearized-window as a (K, W)
bool tensor and renormalizes configs with (K, W, 2W) gather machinery;
profiling showed those gathers plus the 3-key successor sort dominate
per-round time. Real Jepsen histories have small concurrency, so the
exact window bound W (encode.py) is almost always ≤ 32 — and a window
that fits one uint32 lane turns the whole successor construction into
elementwise bit arithmetic:

  * set bit j:        win' = win | (1 << j)
  * renormalize:      t = count-trailing-ones(win'), base += t,
                      win' >>= t        (ctz via popcount((x & -x) - 1))
  * crashed-op masks: one uint32 word per 32 info ops

Dedup drops the sort entirely: every successor probes the memo hash
table directly, and racing twins (two parents producing the same config
in one round) are detected at insert time — the loser re-reads the slot
it just contended for and sees its own signature with a different row
id, i.e. "seen". Per-round work is a few (K, 32) gathers, elementwise
u32 math, and `probes` gather/scatter rounds on the table.

Same consts/carry contract as `wgl._build_search`, so the host driver
and the batched mesh path dispatch between kernels by window width.
"""

from __future__ import annotations

import functools

import numpy as np

INF = np.int32(2**31 - 1)


def _popcount32(x):
    """Bit population count for uint32 lanes."""
    import jax.numpy as jnp
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return (x * jnp.uint32(0x01010101)) >> 24


def _ctz32(x):
    """Count trailing zeros; 32 for x == 0."""
    import jax.numpy as jnp
    low = x & (~x + jnp.uint32(1))  # lowest set bit (two's complement)
    return jnp.where(x == 0, jnp.uint32(32), _popcount32(low - jnp.uint32(1)))


def _fnv_words(words, seed):
    import jax.numpy as jnp
    h = jnp.full_like(words[0], jnp.uint32(seed))
    prime = jnp.uint32(16777619)
    for w in words:
        h = (h ^ w) * prime
        h = h ^ (h >> 15)
    return h


def _build_search32(n_pad: int, ic_pad: int, S: int, O: int,
                    K: int, H: int, B: int, chunk: int, probes: int,
                    W: int = 32):
    """Build (init_fn, chunk_fn) for the W<=32 bitmask kernel. `W` is the
    window width actually materialized (pad the exact requirement to a
    small multiple — successor row count R = K*(W + ic_pad) drives probe
    traffic, the kernel's dominant cost). Crashed-op masks use
    ceil(ic_pad/32) uint32 words."""
    import jax.numpy as jnp
    from jax import lax

    assert 1 <= W <= 32
    Il = max(1, (ic_pad + 31) // 32)

    # Host-precomputed per-info-op word/bit masks: setting info op m.
    info_word = np.arange(ic_pad) // 32                     # (ic,)
    info_bit = (np.uint32(1) << (np.arange(ic_pad) % 32))   # (ic,)
    info_set_mask = np.zeros((ic_pad, Il), dtype=np.uint32)
    info_set_mask[np.arange(ic_pad), info_word] = info_bit

    def init_fn(mstate0):
        fr_base = jnp.zeros(K, dtype=jnp.int32)
        fr_win = jnp.zeros(K, dtype=jnp.uint32)
        fr_info = jnp.zeros((K, Il), dtype=jnp.uint32)
        fr_mst = jnp.zeros(K, dtype=jnp.int32).at[0].set(mstate0)
        fr_cnt = jnp.int32(1)
        bk_base = jnp.zeros(B, dtype=jnp.int32)
        bk_win = jnp.zeros(B, dtype=jnp.uint32)
        bk_info = jnp.zeros((B, Il), dtype=jnp.uint32)
        bk_mst = jnp.zeros(B, dtype=jnp.int32)
        bk_cnt = jnp.int32(0)
        table = jnp.zeros((H, 4), dtype=jnp.uint32)
        flags = jnp.zeros(3, dtype=bool)   # found, overflow, exhausted
        # explored, rounds-in-chunk, max_base, memo_hits, inserted,
        # rounds_total — the last three feed the result's util block
        stats = jnp.zeros(6, dtype=jnp.int32)
        return (fr_base, fr_win, fr_info, fr_mst, fr_cnt,
                bk_base, bk_win, bk_info, bk_mst, bk_cnt,
                table, flags, stats)

    jinfo_word = jnp.asarray(info_word.astype(np.int32))
    jinfo_bit = jnp.asarray(info_bit)
    jinfo_set = jnp.asarray(info_set_mask)

    def round_body(consts, carry):
        (inv, ret, opc, suf, iinv, iopc, T, n_ok, n_info, max_cfg) = consts
        (fr_base, fr_win, fr_info, fr_mst, fr_cnt,
         bk_base, bk_win, bk_info, bk_mst, bk_cnt,
         table, flags, stats) = carry

        alive = jnp.arange(K, dtype=jnp.int32) < fr_cnt
        j = jnp.arange(W, dtype=jnp.int32)
        winbit = (fr_win[:, None] >> j[None, :].astype(jnp.uint32)) \
            & jnp.uint32(1)                                   # (K, 32)
        linearized = winbit == 1

        # --- candidate discovery -------------------------------------
        pos = fr_base[:, None] + j                            # (K, 32)
        posc = jnp.minimum(pos, n_pad - 1)
        retw = jnp.where(linearized | (pos >= n_ok), INF, ret[posc])
        minret = jnp.min(retw, axis=1)
        tail = suf[jnp.minimum(fr_base + W, n_pad)]
        minret = jnp.minimum(minret, tail)                    # (K,)

        invw = inv[posc]
        cand_ok = (~linearized) & (pos < n_ok) \
            & (invw < minret[:, None]) & alive[:, None]
        opw = opc[posc]
        nst_ok = T[fr_mst[:, None], opw]                      # (K, 32)
        legal_ok = cand_ok & (nst_ok >= 0)

        m = jnp.arange(ic_pad, dtype=jnp.int32)
        # info bit m of lane k: (fr_info[k, word(m)] & bit(m)) != 0
        info_words = fr_info[:, jinfo_word]                   # (K, ic)
        info_set = (info_words & jinfo_bit[None, :]) != 0
        cand_info = (~info_set) & (m[None, :] < n_info) \
            & (iinv[None, :] < minret[:, None]) & alive[:, None]
        nst_info = T[fr_mst[:, None], iopc[None, :]]          # (K, ic)
        legal_info = cand_info & (nst_info >= 0)

        # --- successor construction (pure bit math) ------------------
        bit = (jnp.uint32(1) << j.astype(jnp.uint32))         # (32,)
        win_ok = fr_win[:, None] | bit[None, :]               # (K, 32)
        t = _ctz32(~win_ok)                                   # trailing ones
        ti = t.astype(jnp.int32)
        shifted = jnp.where(t >= 32, jnp.uint32(0),
                            win_ok >> jnp.minimum(t, jnp.uint32(31)))
        # t in [1, 32]; t == 32 -> window fully drained
        base_ok = fr_base[:, None] + ti                       # (K, 32)

        base_s = jnp.concatenate(
            [base_ok.reshape(-1),
             jnp.broadcast_to(fr_base[:, None], (K, ic_pad)).reshape(-1)])
        win_s = jnp.concatenate(
            [shifted.reshape(-1),
             jnp.broadcast_to(fr_win[:, None], (K, ic_pad)).reshape(-1)])
        info_ok = jnp.broadcast_to(fr_info[:, None, :], (K, W, Il))
        info_new = fr_info[:, None, :] | jinfo_set[None, :, :]  # (K, ic, Il)
        info_s = jnp.concatenate(
            [info_ok.reshape(-1, Il), info_new.reshape(-1, Il)])
        mst_s = jnp.concatenate(
            [nst_ok.reshape(-1), nst_info.reshape(-1)])
        legal = jnp.concatenate(
            [legal_ok.reshape(-1), legal_info.reshape(-1)])   # (R,)
        R = legal.shape[0]

        success = legal & (base_s >= n_ok) & (win_s == 0)
        found = jnp.any(success)
        explore = legal & ~success

        # --- hash signatures -----------------------------------------
        words = ([base_s.astype(jnp.uint32), win_s, mst_s.astype(jnp.uint32)]
                 + [info_s[:, i] for i in range(Il)])
        s0 = _fnv_words(words, 0x811C9DC5) | jnp.uint32(1)  # never 0
        s1 = _fnv_words(words, 0x01000193)
        s2 = _fnv_words(words, 0xDEADBEEF)
        myrow = jnp.arange(R, dtype=jnp.uint32)
        step = s1 | jnp.uint32(1)
        mysig = jnp.stack([s0, s1, s2], axis=1)               # (R, 3)

        # --- probe-based dedup (no sort) -----------------------------
        # Twins (same signature, same round) collide on the same probe
        # sequence: the claim loser re-reads the slot, sees its own
        # signature under a different row id, and counts as seen.
        def probe(_, st):
            table, pending, seen, pr = st
            idx = ((s0 + pr * step) & jnp.uint32(H - 1)).astype(jnp.int32)
            slot = table[idx]                                 # (R, 4)
            occupied = slot[:, 0] != 0
            sig_eq = jnp.all(slot[:, :3] == mysig, axis=1)
            equal = occupied & sig_eq
            seen = seen | (pending & equal)
            claim = pending & ~occupied
            widx = jnp.where(claim, idx, H)
            entry = jnp.concatenate([mysig, myrow[:, None]], axis=1)
            table = table.at[widx].set(entry, mode="drop")
            slot2 = table[idx]
            sig_eq2 = jnp.all(slot2[:, :3] == mysig, axis=1)
            won = claim & sig_eq2 & (slot2[:, 3] == myrow)
            twin = claim & sig_eq2 & ~won
            seen = seen | twin
            pending = pending & ~(equal | won | twin)
            pr = pr + pending.astype(jnp.uint32)
            return table, pending, seen, pr

        table, pending, seen, _ = lax.fori_loop(
            0, probes, probe,
            (table, explore, jnp.zeros(R, dtype=bool),
             jnp.zeros(R, dtype=jnp.uint32)))
        # leftover pending (table too contended): treat as unseen — may
        # re-explore later; sound.
        new = explore & ~seen

        # --- compact survivors into frontier + backlog ---------------
        posn = jnp.cumsum(new.astype(jnp.int32)) - 1          # (R,)
        total = jnp.sum(new.astype(jnp.int32))

        to_front = new & (posn < K)
        fidx = jnp.where(to_front, posn, K)
        nfr_base = jnp.zeros(K, dtype=jnp.int32).at[fidx].set(
            base_s, mode="drop")
        nfr_win = jnp.zeros(K, dtype=jnp.uint32).at[fidx].set(
            win_s, mode="drop")
        nfr_info = jnp.zeros((K, Il), dtype=jnp.uint32).at[fidx].set(
            info_s, mode="drop")
        nfr_mst = jnp.zeros(K, dtype=jnp.int32).at[fidx].set(
            mst_s, mode="drop")
        nfr_cnt = jnp.minimum(total, K)

        spill = new & (posn >= K)
        sidx = jnp.where(spill, bk_cnt + posn - K, B)
        overflow = jnp.any(spill & (sidx >= B))
        sidx = jnp.minimum(sidx, B)
        bk_base = bk_base.at[sidx].set(base_s, mode="drop")
        bk_win = bk_win.at[sidx].set(win_s, mode="drop")
        bk_info = bk_info.at[sidx].set(info_s, mode="drop")
        bk_mst = bk_mst.at[sidx].set(mst_s, mode="drop")
        nbk_cnt = jnp.minimum(bk_cnt + jnp.maximum(total - K, 0), B)

        # refill frontier from the backlog top
        room = K - nfr_cnt
        take = jnp.minimum(room, nbk_cnt)
        kidx = jnp.arange(K, dtype=jnp.int32)
        taking = kidx < take
        src = jnp.where(taking, jnp.maximum(nbk_cnt - 1 - kidx, 0), 0)
        dst = jnp.where(taking, nfr_cnt + kidx, K)
        nfr_base = nfr_base.at[dst].set(bk_base[src], mode="drop")
        nfr_win = nfr_win.at[dst].set(bk_win[src], mode="drop")
        nfr_info = nfr_info.at[dst].set(bk_info[src], mode="drop")
        nfr_mst = nfr_mst.at[dst].set(bk_mst[src], mode="drop")
        nfr_cnt = nfr_cnt + take
        nbk_cnt = nbk_cnt - take

        nflags = jnp.stack([flags[0] | found,
                            flags[1] | overflow,
                            nfr_cnt == 0])
        nstats = jnp.stack([
            stats[0] + fr_cnt,
            stats[1] + 1,
            jnp.maximum(stats[2], jnp.max(jnp.where(legal, base_s, 0))),
            stats[3] + jnp.sum(seen.astype(jnp.int32)),
            stats[4] + total,
            stats[5] + 1])
        return (nfr_base, nfr_win, nfr_info, nfr_mst, nfr_cnt,
                bk_base, bk_win, bk_info, bk_mst, nbk_cnt,
                table, nflags, nstats)

    def chunk_fn(consts, carry):
        max_cfg = consts[-1]

        def cond(c):
            flags, stats = c[11], c[12]
            return (~flags[0]) & (c[4] > 0) \
                & (stats[1] < chunk) & (stats[0] < max_cfg)

        def body(c):
            return round_body(consts, c)

        stats = carry[12]
        carry = carry[:12] + (stats.at[1].set(0),)
        return lax.while_loop(cond, body, carry)

    return init_fn, chunk_fn


@functools.lru_cache(maxsize=32)
def compiled_search32(n_pad: int, ic_pad: int, S: int, O: int,
                      K: int, H: int, B: int, chunk: int, probes: int,
                      W: int = 32):
    import jax

    init_fn, chunk_fn = _build_search32(n_pad, ic_pad, S, O,
                                        K, H, B, chunk, probes, W=W)
    return init_fn, jax.jit(chunk_fn, donate_argnums=(1,))
