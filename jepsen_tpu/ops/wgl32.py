"""Bitmask fast-path WGL kernel (windows ≤ 32 ok-ops wide), scatter-lean.

The general kernel (`wgl.py`) keeps the linearized-window as a (K, W)
bool tensor and renormalizes configs with (K, W, 2W) gather machinery.
Real Jepsen histories have small concurrency, so the exact window bound
W (encode.py) is almost always ≤ 32 — and a window that fits one uint32
lane turns the whole successor construction into elementwise bit
arithmetic:

  * set bit j:        win' = win | (1 << j)
  * renormalize:      t = count-trailing-ones(win'), base += t,
                      win' >>= t        (ctz via popcount((x & -x) - 1))
  * crashed-op masks: one uint32 word per 32 info ops

Layout is driven by a measured accelerator cost model (round 5, v5e
behind the axon runtime): inside a device `lax.while_loop`, elementwise
math / sorts / reductions are effectively free, row-gathers are cheap
and pipeline, but every SCATTER costs ~30 µs of serialized latency —
the round-3 layout (four frontier arrays + four backlog arrays + a
4-iteration probe loop with insert-per-probe) paid ~16 scatters ≈
600 µs/round on the chip vs ~70 µs on a CPU core. So this kernel:

  * packs each config into ONE int32 row [base, win, mst, info words]:
    frontier (K, C) and backlog (B, C) update in one scatter each;
  * folds the op metadata into one row table `meta` (n_pad+1, 4) =
    [inv, ret, opcode, sufminret] — one row-gather per round instead
    of four element-gathers;
  * folds the model transition table into `TK[opc * S + mst]` rows so
    ok-candidates and info-candidates share one row-gather;
  * probes the memo table with ONE batched gather of all `probes`
    candidate slots, inserts with ONE scatter at each row's first
    empty slot, and verifies with one gather — racing twins (two
    parents producing the same config in one round) are detected at
    verify time: the loser sees its own signature under a different
    row id, i.e. "seen". Rows whose insert lost to a *different*
    signature (slot collision) stay "unseen" and may re-explore
    later — sound, same as the old kernel's leftover-pending rows.

Same consts contract as `wgl._build_search` (inv, ret, opcode,
sufminret, inv_info, opcode_info, T, n_ok, n_info, max_cfg); the carry
is the packed 8-tuple

    (fr, fr_cnt, bk, bk_cnt, table, flags, stats, ring)

shared with the packed wide-window kernel (`wgln.py`) so the host
driver (`wgl.check`) and the batched mesh path (`parallel/batched.py`)
read counters at fixed indices: fr_cnt = carry[1], flags = carry[5],
stats = carry[6], and the per-round occupancy ring = carry[7] (see
RING_ROWS below — one row per round, drained through the packed poll
summary with no extra transfer).

Reference parity: this is the knossos wgl/analysis engine the
reference reaches through `jepsen/src/jepsen/checker.clj:199-202`.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

INF = np.int32(2**31 - 1)

# Packed-table (int16) plane: the masked-ret sentinel and the largest
# real event time a history may carry to qualify (strictly below the
# sentinel, with head-room so the clamp can never collide with data).
PACK_INF = np.int32(2**15 - 1)   # 32767
PACK_MAX = int(PACK_INF) - 64    # caller-side eligibility bound

# carry indices shared by wgl.py / parallel/batched.py
FR, FR_CNT, BK, BK_CNT, TABLE, FLAGS, STATS, RING_BUF = range(8)

# Per-round occupancy ring (the kernel-occupancy plane, doc/
# OBSERVABILITY.md "Occupancy & roofline"): each round writes ONE
# (RING_COLS,) int32 row into a preallocated (RING_ROWS, RING_COLS)
# buffer in the carry, indexed by the per-chunk round counter
# (stats[1]) — rows past RING_ROWS in one chunk are dropped, never
# wrapped, so the host reads ring[:min(stats[1], RING_ROWS)] with no
# ordering reconstruction. The ring rides the packed poll summary
# (flattened after the classic 11 words), so draining it costs ZERO
# extra host<->device transfers and the kernel is identical whether
# or not anyone reads it — the CompileGuard zero-recompile /
# zero-transfer proof in tests/test_occupancy.py depends on both.
# Cost: one small-row scatter per round (~30 us serialized on a TPU,
# noise on cpu) — the price of per-round visibility.
RING_ROWS = 512
RING_COLS = 7
# ring columns: [rounds_total after this round, frontier rows
# expanded, memo hits, unique survivors (inserts), frontier after
# compaction+refill, backlog depth, max linearized base]
(RING_ROUND, RING_FRONTIER, RING_HITS, RING_INSERTS, RING_FR_AFTER,
 RING_BACKLOG, RING_MAX_BASE) = range(RING_COLS)

# leading words of the packed poll summary, before the flattened ring:
# [fr_cnt, flags x3, stats x6, bk_cnt]
SUMMARY_HEAD = 11


def _popcount32(x):
    """Bit population count for uint32 lanes."""
    import jax.numpy as jnp
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return (x * jnp.uint32(0x01010101)) >> 24


def _ctz32(x):
    """Count trailing zeros; 32 for x == 0."""
    import jax.numpy as jnp
    low = x & (~x + jnp.uint32(1))  # lowest set bit (two's complement)
    return jnp.where(x == 0, jnp.uint32(32), _popcount32(low - jnp.uint32(1)))


def _fnv_words(words, seed):
    import jax.numpy as jnp
    h = jnp.full_like(words[0], jnp.uint32(seed))
    prime = jnp.uint32(16777619)
    for w in words:
        h = (h ^ w) * prime
        h = h ^ (h >> 15)
    return h


def _i32(x):
    import jax
    import jax.numpy as jnp
    return jax.lax.bitcast_convert_type(x, jnp.int32)


def _u32(x):
    import jax
    import jax.numpy as jnp
    return jax.lax.bitcast_convert_type(x, jnp.uint32)


def probe_check(table, s0, s1, s2, probes: int, H: int):
    """Check-only memo probe: ONE batched gather of all `probes`
    candidate slots. Returns (seen, ins_idx, has_empty) — `ins_idx`
    is each row's first-empty slot as of this read (the insert site),
    `has_empty` whether one exists. No table mutation: multi-level
    rounds batch their inserts into one end-of-round scatter."""
    import jax.numpy as jnp

    R = s0.shape[0]
    step = s1 | jnp.uint32(1)
    mysig = jnp.stack([s0, s1, s2], axis=1)                   # (R, 3)

    pr = jnp.arange(probes, dtype=jnp.uint32)
    idx_p = ((s0[:, None] + pr[None, :] * step[:, None])
             & jnp.uint32(H - 1)).astype(jnp.int32)           # (R, P)
    slots = table[idx_p.reshape(-1)].reshape(R, probes, 4)    # 1 gather
    occ = slots[:, :, 0] != 0
    eq = occ & jnp.all(slots[:, :, :3] == mysig[:, None, :], axis=2)
    seen = jnp.any(eq, axis=1)

    empt = ~occ
    has_empty = jnp.any(empt, axis=1)
    firstp = jnp.argmax(empt, axis=1).astype(jnp.int32)       # first empty
    onehot = firstp[:, None] == jnp.arange(probes,
                                           dtype=jnp.int32)[None, :]
    ins_idx = jnp.sum(jnp.where(onehot, idx_p, 0), axis=1)    # (R,)
    return seen, ins_idx, has_empty


def make_compact_frontier(K: int, C: int):
    """Compact-before-expand pre-pass, shared by wgl32 and wgln: sort-
    dedup the (K, C) packed beam BEFORE the O(W)-way expansion. Rows
    are exact packed configs, so equal neighbors after a
    lexicographic sort ARE duplicate configs; survivors repack
    densely. Liveness is its own leading sort key (same rationale as
    round_body_deep's signature sort). The returned function maps
    (fr, fr_cnt) -> (fr, fr_cnt, dups_dropped)."""
    import jax.numpy as jnp
    from jax import lax

    def compact(fr, fr_cnt):
        dead = (jnp.arange(K, dtype=jnp.int32)
                >= fr_cnt).astype(jnp.uint32)
        rid = jnp.arange(K, dtype=jnp.int32)
        cols = tuple(_u32(fr[:, c]) for c in range(C))
        srt = lax.sort((dead,) + cols + (rid,), num_keys=1 + C)
        dead_s, cols_s, perm = srt[0], srt[1:1 + C], srt[-1]
        live_s = dead_s == 0
        same = live_s & jnp.roll(live_s, 1)
        for c in cols_s:
            same = same & (c == jnp.roll(c, 1))
        same = same.at[0].set(False)
        keep = live_s & ~same
        n_keep = jnp.sum(keep.astype(jnp.int32))
        posk = jnp.cumsum(keep.astype(jnp.int32)) - 1
        kidx = jnp.where(keep, posk, K)
        nfr = jnp.zeros((K, C), dtype=jnp.int32).at[kidx].set(
            fr[perm], mode="drop")
        return nfr, n_keep, fr_cnt - n_keep

    return compact


def probe_insert(table, s0, s1, s2, explore, probes: int, H: int):
    """Memo-table dedup with one batched probe gather, one insert
    scatter, one verify gather (see module docstring). Returns
    (table, seen) — `seen` marks rows whose exact signature was
    already in the table (or lost an insert race to a twin this
    round). Shared with wgln.py."""
    import jax.numpy as jnp

    R = s0.shape[0]
    mysig = jnp.stack([s0, s1, s2], axis=1)                   # (R, 3)
    myrow = jnp.arange(R, dtype=jnp.uint32)
    seen, ins_idx, has_empty = probe_check(table, s0, s1, s2, probes, H)

    inserting = explore & ~seen & has_empty
    widx = jnp.where(inserting, ins_idx, H)
    entry = jnp.concatenate([mysig, myrow[:, None].astype(jnp.uint32)],
                            axis=1)
    table = table.at[widx].set(entry, mode="drop")            # 1 scatter
    verify = table[ins_idx]                                   # 1 gather
    v_eq = jnp.all(verify[:, :3] == mysig, axis=1)
    twin_lost = inserting & v_eq & (verify[:, 3] != myrow)
    seen = seen | twin_lost
    return table, seen


def _build_search32(n_pad: int, ic_pad: int, S: int, O: int,
                    K: int, H: int, B: int, chunk: int, probes: int,
                    W: int = 32, accel: bool = False, depth: int = 1,
                    compact: Optional[bool] = None,
                    pack: bool = False, batched: bool = False):
    """Build (init_fn, chunk_fn) for the W<=32 bitmask kernel. `W` is the
    window width actually materialized (pad the exact requirement to a
    small multiple — successor row count R = K*(W + ic_pad) drives the
    dedup traffic). Crashed-op masks use ceil(ic_pad/32) uint32 words.

    `accel` selects the accelerator layout (measured on the v5e, round
    5): the grand-table fused gather, top_k frontier compaction, and
    cond-guarded backlog — each trades vector work (free on the VPU)
    for serialized ~30 µs scatter/gather latency. On a CPU core the
    same trades LOSE (caches make scatters cheap, top_k dear), so the
    host build keeps the scatter-compaction layout.

    `compact` reorders the round compact-before-expand: the beam is
    sort-deduped (identical packed rows dropped, survivors repacked
    densely) BEFORE the O(W)-way successor expansion, so a duplicate
    config never pays its (W + ic) expansion + probe traffic again.
    Duplicates only arise where insert-time dedup has blind spots —
    twin-insert slot races, and the depth-fused accel path whose
    check-only probes can't see uninserted sibling levels — so the
    default is ON exactly there (depth > 1) and OFF for the
    single-level host build, where beam rows are unique by
    construction and the K-row sort would be pure overhead.

    `pack` stores the per-round lookup tables half-width: the fused
    grand table / meta rows (inv, ret, nst, suf) in int16 and the
    transition rows in int16 (int8 when S*O allows), halving the
    dominant gather stream's operand bytes. Only legal when every
    real event time fits int16 (the caller checks against PACK_MAX
    — times are event indices, < 2n+2, so every history under ~16k
    events qualifies, including the 10k headline). Bit-exact: the
    comparisons run in the packed dtype with PACK_INF as the masked
    sentinel, and every real time is strictly below it.

    `batched` returns `chunk_fn_batched` instead of the single-lane
    chunk_fn: consts/carry take a leading lane axis and the round
    loop runs ALL lanes inside one `lax.while_loop` (see its
    docstring for why this beats `jax.vmap(chunk_fn)` by ~two orders
    of magnitude). Single-level only (depth == 1)."""
    import jax.numpy as jnp
    from jax import lax

    assert 1 <= W <= 32
    if compact is None:
        compact = depth > 1
    pk_i = jnp.int16 if pack else jnp.int32
    # int8 transition rows need every state index in [-1, 127]
    pk_t = jnp.int8 if pack and S <= 127 else pk_i
    pinf = jnp.asarray(PACK_INF if pack else INF, pk_i)
    Il = max(1, (ic_pad + 31) // 32)
    C = 3 + Il  # packed config row: [base, win, mst, info words...]
    # Grand-table fusion: when the (pos, mst) product is small enough,
    # ONE row-gather per round serves op metadata, suffix-min tail,
    # AND both transition lookups (see chunk_fn). Small model state
    # spaces (register/cas/mutex: S <= ~64) always qualify; large ones
    # (queue models) fall back to the two-gather scheme.
    fused = accel and (n_pad + 1) * S + ic_pad * S <= (1 << 22)

    # Host-precomputed per-info-op word/bit masks: setting info op m.
    info_word = np.arange(ic_pad) // 32                     # (ic,)
    info_bit = (np.uint32(1) << (np.arange(ic_pad) % 32))   # (ic,)
    info_set_mask = np.zeros((ic_pad, Il), dtype=np.uint32)
    info_set_mask[np.arange(ic_pad), info_word] = info_bit

    def init_fn(mstate0):
        fr = jnp.zeros((K, C), dtype=jnp.int32).at[0, 2].set(mstate0)
        fr_cnt = jnp.int32(1)
        bk = jnp.zeros((B, C), dtype=jnp.int32)
        bk_cnt = jnp.int32(0)
        table = jnp.zeros((H, 4), dtype=jnp.uint32)
        flags = jnp.zeros(3, dtype=bool)   # found, overflow, exhausted
        # explored, rounds-in-chunk, max_base, memo_hits, inserted,
        # rounds_total — the last three feed the result's util block
        stats = jnp.zeros(6, dtype=jnp.int32)
        ring = jnp.zeros((RING_ROWS, RING_COLS), dtype=jnp.int32)
        return (fr, fr_cnt, bk, bk_cnt, table, flags, stats, ring)

    jinfo_word = jnp.asarray(info_word.astype(np.int32))
    jinfo_bit = jnp.asarray(info_bit)
    jinfo_set = jnp.asarray(info_set_mask)

    def _expand(consts, fr, fr_cnt):
        """One expansion level: frontier rows (K, C) -> packed
        successors (R, C) with legality/success masks and hash
        signatures. Shared by the single-level round and the
        depth-fused accel round."""
        (GT, iinv, iopc_c, n_ok, n_info, max_cfg) = consts

        fr_base = fr[:, 0]
        fr_win = _u32(fr[:, 1])
        fr_mst = fr[:, 2]
        fr_info = _u32(fr[:, 3:])                             # (K, Il)

        alive = jnp.arange(K, dtype=jnp.int32) < fr_cnt
        j = jnp.arange(W, dtype=jnp.int32)
        winbit = (fr_win[:, None] >> j[None, :].astype(jnp.uint32)) \
            & jnp.uint32(1)                                   # (K, W)
        linearized = winbit == 1

        # --- candidate discovery -------------------------------------
        pos = fr_base[:, None] + j                            # (K, W)
        posc = jnp.minimum(pos, n_pad - 1)
        tailp = jnp.minimum(fr_base + W, n_pad)               # (K,)
        m = jnp.arange(ic_pad, dtype=jnp.int32)
        if fused:
            # ONE row-gather serves window metadata + transitions,
            # the suffix-min tail, and the info-op transitions: GT is
            # indexed pos*S + mst for ok ops (rows [inv, ret, nst,
            # suf]) and (n_pad+1)*S + m*S + mst for info ops (rows
            # [iinv, 0, nst, 0]) — see chunk_fn.
            gidx = jnp.concatenate(
                [(posc * S + fr_mst[:, None]).reshape(-1),
                 tailp * S + fr_mst,
                 ((n_pad + 1) * S + m[None, :] * S
                  + fr_mst[:, None]).reshape(-1)])
            grows = GT[gidx]                                  # gather
            okrows = grows[:K * W].reshape(K, W, 4)
            invw, retw0, nst_ok = (okrows[..., 0], okrows[..., 1],
                                   okrows[..., 2])
            tail = grows[K * W:K * W + K, 3]                  # (K,)
            irows = grows[K * W + K:].reshape(K, ic_pad, 4)
            iinvw, nst_info = irows[..., 0], irows[..., 2]
        else:
            (meta, TK) = GT
            mrows = meta[posc.reshape(-1)].reshape(K, W, 4)   # gather
            invw, retw0, opw = (mrows[..., 0], mrows[..., 1],
                                mrows[..., 2])
            tail = meta[tailp][:, 3]                          # gather
            # index arithmetic in int32: packed meta rows may be
            # int16 and opw * S overflows there for big state spaces
            opw32 = opw.astype(jnp.int32)
            tidx = jnp.concatenate(
                [(opw32 * S + fr_mst[:, None]).reshape(-1),
                 (iopc_c[None, :] * S + fr_mst[:, None]).reshape(-1)])
            nst_all = TK[tidx][:, 0]                          # gather
            nst_ok = nst_all[:K * W].reshape(K, W)
            nst_info = nst_all[K * W:].reshape(K, ic_pad)
            iinvw = jnp.broadcast_to(iinv[None, :], (K, ic_pad))

        retw = jnp.where(linearized | (pos >= n_ok), pinf, retw0)
        minret = jnp.min(retw, axis=1)
        minret = jnp.minimum(minret, tail)                    # (K,)

        cand_ok = (~linearized) & (pos < n_ok) \
            & (invw < minret[:, None]) & alive[:, None]

        # info bit m of lane k: (fr_info[k, word(m)] & bit(m)) != 0
        if Il == 1:
            info_words = jnp.broadcast_to(fr_info[:, :1], (K, ic_pad))
        else:
            info_words = fr_info[:, jinfo_word]               # (K, ic)
        info_set = (info_words & jinfo_bit[None, :]) != 0
        cand_info = (~info_set) & (m[None, :] < n_info) \
            & (iinvw < minret[:, None]) & alive[:, None]

        legal_ok = cand_ok & (nst_ok >= 0)
        legal_info = cand_info & (nst_info >= 0)

        # --- successor construction (pure bit math) ------------------
        bit = (jnp.uint32(1) << j.astype(jnp.uint32))         # (W,)
        win_ok = fr_win[:, None] | bit[None, :]               # (K, W)
        t = _ctz32(~win_ok)                                   # trailing ones
        ti = t.astype(jnp.int32)
        shifted = jnp.where(t >= 32, jnp.uint32(0),
                            win_ok >> jnp.minimum(t, jnp.uint32(31)))
        # t in [1, 32]; t == 32 -> window fully drained
        base_ok = fr_base[:, None] + ti                       # (K, W)

        base_s = jnp.concatenate(
            [base_ok.reshape(-1),
             jnp.broadcast_to(fr_base[:, None], (K, ic_pad)).reshape(-1)])
        win_s = jnp.concatenate(
            [shifted.reshape(-1),
             jnp.broadcast_to(fr_win[:, None], (K, ic_pad)).reshape(-1)])
        info_ok = jnp.broadcast_to(fr_info[:, None, :], (K, W, Il))
        info_new = fr_info[:, None, :] | jinfo_set[None, :, :]  # (K, ic, Il)
        info_s = jnp.concatenate(
            [info_ok.reshape(-1, Il), info_new.reshape(-1, Il)])
        mst_s = jnp.concatenate(
            [nst_ok.reshape(-1),
             nst_info.reshape(-1)]).astype(jnp.int32)
        legal = jnp.concatenate(
            [legal_ok.reshape(-1), legal_info.reshape(-1)])   # (R,)

        success = legal & (base_s >= n_ok) & (win_s == 0)
        found = jnp.any(success)
        explore = legal & ~success

        # --- hash signatures -----------------------------------------
        words = ([base_s.astype(jnp.uint32), win_s, mst_s.astype(jnp.uint32)]
                 + [info_s[:, i] for i in range(Il)])
        s0 = _fnv_words(words, 0x811C9DC5) | jnp.uint32(1)  # never 0
        s1 = _fnv_words(words, 0x01000193)
        s2 = _fnv_words(words, 0xDEADBEEF)

        succ = jnp.concatenate(
            [base_s[:, None],
             _i32(win_s)[:, None],
             mst_s[:, None],
             _i32(info_s)], axis=1)                           # (R, C)
        base_max = jnp.max(jnp.where(legal, base_s, 0))
        return succ, explore, found, s0, s1, s2, base_max

    _compact_frontier = make_compact_frontier(K, C)

    def round_body(consts, carry, halt=None):
        # `halt` (scalar bool, lane-packed batched path only): a lane
        # that already decided runs the body as a NO-OP — zero legal
        # successors, every scatter drops, and the small state below
        # is frozen by per-lane selects. This is what lets the batched
        # chunk loop keep ONE while_loop with the lane axis inside it
        # instead of vmapping the loop (see chunk_fn_batched).
        (fr, fr_cnt, bk, bk_cnt, table, flags, stats, ring) = carry
        dups = jnp.int32(0)
        if compact:
            fr, fr_cnt, dups = _compact_frontier(fr, fr_cnt)
        fr_cnt_eff = (fr_cnt if halt is None
                      else jnp.where(halt, 0, fr_cnt))
        succ, explore, found, s0, s1, s2, base_max = \
            _expand(consts, fr, fr_cnt_eff)

        # --- memo dedup: 1 gather + 1 scatter + 1 verify gather ------
        table, seen = probe_insert(table, s0, s1, s2, explore, probes, H)
        new = explore & ~seen

        # --- compact survivors into frontier + backlog ---------------
        R = succ.shape[0]
        posn = jnp.cumsum(new.astype(jnp.int32)) - 1          # (R,)
        total = jnp.sum(new.astype(jnp.int32))

        if accel:
            # frontier = first K new rows, selected by top_k + row
            # gather (no scatter on the critical path)
            score = jnp.where(new, R - posn, 0)
            _, fsel = lax.top_k(score, K)                     # (K,)
            nfr = succ[fsel]                                  # gather
        else:
            to_front = new & (posn < K)
            fidx = jnp.where(to_front, posn, K)
            nfr = jnp.zeros((K, C), dtype=jnp.int32).at[fidx].set(
                succ, mode="drop")
        nfr_cnt = jnp.minimum(total, K)

        # backlog spill + refill are RARE on the fast path (the beam
        # usually swallows the whole wavefront): on the accel build
        # both ride lax.cond so the common-case round pays no scatter
        # for them. Under vmap (the batched mesh path) cond lowers to
        # select and both sides run — same cost as the unconditional
        # layout, no worse.
        spill = new & (posn >= K)
        sidx = jnp.where(spill, bk_cnt + posn - K, B)
        overflow = jnp.any(spill & (sidx >= B))
        sidx = jnp.minimum(sidx, B)

        def do_spill(b):
            return b.at[sidx].set(succ, mode="drop")

        bk = lax.cond(total > K, do_spill, lambda b: b, bk) if accel \
            else do_spill(bk)
        nbk_cnt = jnp.minimum(bk_cnt + jnp.maximum(total - K, 0), B)

        # refill frontier from the backlog top
        room = K - nfr_cnt
        take = jnp.minimum(room, nbk_cnt)
        if halt is not None:  # jaxlint: ok(J002) — static None check
            take = jnp.where(halt, 0, take)

        def do_refill(args):
            nfr, bk = args
            kidx = jnp.arange(K, dtype=jnp.int32)
            taking = kidx < take
            src = jnp.where(taking, jnp.maximum(nbk_cnt - 1 - kidx, 0), 0)
            dst = jnp.where(taking, nfr_cnt + kidx, K)
            return nfr.at[dst].set(bk[src], mode="drop")

        nfr = lax.cond(take > 0, do_refill, lambda a: a[0],
                       (nfr, bk)) if accel else do_refill((nfr, bk))
        nfr_cnt = nfr_cnt + take
        nbk_cnt = nbk_cnt - take

        nflags = jnp.stack([flags[0] | found,
                            flags[1] | overflow,
                            nfr_cnt == 0])
        # beam duplicates dropped by compact-before-expand count as
        # dedup hits: they are exactly the re-expansions saved
        seen_n = jnp.sum(seen.astype(jnp.int32)) + dups
        nstats = jnp.stack([
            stats[0] + fr_cnt,
            stats[1] + 1,
            jnp.maximum(stats[2], base_max),
            stats[3] + seen_n,
            stats[4] + total,
            stats[5] + 1])
        # occupancy ring row for THIS round; index stats[1] = rounds
        # already run this chunk, rows past RING_ROWS drop (mode=drop)
        row = jnp.stack([nstats[5], fr_cnt, seen_n, total,
                         nfr_cnt, nbk_cnt,
                         jnp.maximum(stats[2], base_max)])
        ridx = jnp.minimum(stats[1], RING_ROWS)
        if halt is not None:  # jaxlint: ok(J002) — static None check
            # freeze a halted lane: drop its ring write (index
            # RING_ROWS is the drop sink) and keep its small state
            ridx = jnp.where(halt, RING_ROWS, ridx)
            nfr = jnp.where(halt, fr, nfr)
            nfr_cnt = jnp.where(halt, fr_cnt, nfr_cnt)
            nbk_cnt = jnp.where(halt, bk_cnt, nbk_cnt)
            nflags = jnp.where(halt, flags, nflags)
            nstats = jnp.where(halt, stats, nstats)
        ring = ring.at[ridx].set(row, mode="drop")
        return (nfr, nfr_cnt, bk, nbk_cnt, table, nflags, nstats, ring)

    def round_body_deep(consts, carry):
        """Depth-fused accel round: `depth` expansion levels per
        memo/backlog commit. The per-level critical path shrinks to
        one grand-table gather + one check-only probe gather + a
        sort (sorts are ~free on the VPU); the insert scatter runs
        ONCE for all levels. Within a super-round a config reached
        at two different levels may be expanded twice (check-only
        probes can't see uninserted siblings) — bounded by depth,
        sound, and irrelevant on the near-linear wavefronts this
        path exists for."""
        (fr, fr_cnt, bk, bk_cnt, table, flags, stats, ring) = carry
        if compact:
            # cross-level twins from the previous super-round (check-
            # only probes can't see uninserted siblings) die here,
            # before paying another full expansion
            fr, fr_cnt, dups0 = _compact_frontier(fr, fr_cnt)
        else:
            dups0 = jnp.int32(0)
        found = flags[0]
        overflow = flags[1]
        base_max = stats[2]
        explored_add = jnp.int32(0)
        hits_add = dups0
        ins_add = jnp.int32(0)
        ins_widx = []
        ins_entry = []
        cur, cnt = fr, fr_cnt
        # bounded unroll BY DESIGN: depth is a static build parameter
        # (<= 4) and fusing levels per memo commit is this round's
        # whole reason to exist — lax.fori_loop would forbid the
        # per-level insert batching below
        for _lvl in range(depth):  # jaxlint: ok(J006)
            succ, explore, found_l, s0, s1, s2, bmax = \
                _expand(consts, cur, cnt)
            R = succ.shape[0]
            found = found | found_l
            base_max = jnp.maximum(base_max, bmax)
            explored_add = explored_add + cnt

            seen0, ins_idx, has_empty = probe_check(
                table, s0, s1, s2, probes, H)

            # sort-dedup in the signature domain. Liveness is its OWN
            # leading sort key — overloading the hash domain with a
            # sentinel would misclassify a live row whose s0 happens
            # to equal the sentinel (p ~ 2^-31/row, a silently
            # dropped subtree and a potential wrong False).
            live = explore & ~seen0
            dead = (~live).astype(jnp.uint32)
            rid = jnp.arange(R, dtype=jnp.int32)
            ds, k0s, k1s, k2s, perm = lax.sort(
                (dead, s0, s1, s2, rid), num_keys=4)
            live_s = ds == 0
            samep = (k0s == jnp.roll(k0s, 1)) \
                & (k1s == jnp.roll(k1s, 1)) \
                & (k2s == jnp.roll(k2s, 1)) \
                & live_s & jnp.roll(live_s, 1)
            samep = samep.at[0].set(False)
            new_s = live_s & ~samep                           # sorted dom
            n_new = jnp.sum(new_s.astype(jnp.int32))
            hits_add = hits_add \
                + jnp.sum((seen0 & explore).astype(jnp.int32)) \
                + jnp.sum((live_s & samep).astype(jnp.int32))
            ins_add = ins_add + n_new

            # collect this level's inserts (batched scatter at end);
            # entries carry the sorted position as the row id — only
            # uniqueness within the batch matters
            insable = new_s & has_empty[perm]
            ins_widx.append(jnp.where(insable, ins_idx[perm], H))
            ins_entry.append(jnp.stack(
                [k0s, k1s, k2s,
                 lax.convert_element_type(perm, jnp.uint32)], axis=1))

            # next level's frontier: first K unique rows (top_k, no
            # scatter), overflow spills to the backlog under cond
            rank = jnp.cumsum(new_s.astype(jnp.int32)) - 1
            score = jnp.where(new_s & (rank < K), R + K - rank, 0)
            _, sel = lax.top_k(score, K)
            rid_sel = perm[sel]
            cur = succ[rid_sel]
            spill_s = new_s & (rank >= K)
            n_spill = jnp.maximum(n_new - K, 0)
            sidx = jnp.where(spill_s, bk_cnt + rank - K, B)
            overflow = overflow | jnp.any(spill_s & (sidx >= B))
            sidx = jnp.minimum(sidx, B)

            def do_spill(b, sidx=sidx, perm=perm, succ=succ):
                return b.at[sidx].set(succ[perm], mode="drop")

            bk = lax.cond(n_spill > 0, do_spill, lambda b: b, bk)
            bk_cnt = jnp.minimum(bk_cnt + n_spill, B)
            cnt = jnp.minimum(n_new, K)

        # one insert scatter for every level's survivors; slot races
        # across levels lose soundly (re-explored later, never unsound)
        table = table.at[jnp.concatenate(ins_widx)].set(
            jnp.concatenate(ins_entry), mode="drop")

        nfr, nfr_cnt = cur, cnt
        room = K - nfr_cnt
        take = jnp.minimum(room, bk_cnt)

        def do_refill(args):
            nfr, bk = args
            kidx = jnp.arange(K, dtype=jnp.int32)
            taking = kidx < take
            src = jnp.where(taking, jnp.maximum(bk_cnt - 1 - kidx, 0), 0)
            dst = jnp.where(taking, nfr_cnt + kidx, K)
            return nfr.at[dst].set(bk[src], mode="drop")

        nfr = lax.cond(take > 0, do_refill, lambda a: a[0], (nfr, bk))
        nfr_cnt = nfr_cnt + take
        nbk_cnt = bk_cnt - take

        nflags = jnp.stack([found, overflow, nfr_cnt == 0])
        nstats = jnp.stack([
            stats[0] + explored_add,
            stats[1] + 1,
            base_max,
            stats[3] + hits_add,
            stats[4] + ins_add,
            stats[5] + depth])
        # one occupancy ring row per SUPER-round: `frontier` counts
        # expansions across all `depth` fused levels; the host
        # normalizes fill by the round span it reads off the ring's
        # rounds_total column deltas (occupancy.drain_chunk)
        row = jnp.stack([nstats[5], explored_add, hits_add, ins_add,
                         nfr_cnt, nbk_cnt, base_max])
        ring = ring.at[jnp.minimum(stats[1], RING_ROWS)].set(
            row, mode="drop")
        return (nfr, nfr_cnt, bk, nbk_cnt, table, nflags, nstats, ring)

    def _round_consts(consts):
        (inv, ret, opc, suf, iinv, iopc, T, n_ok, n_info, max_cfg) = consts
        # Fused lookup tables, built once per chunk call (hoisted out
        # of the round loop). Under `pack` every time column clamps
        # its INF sentinel to PACK_INF and narrows to int16 — legal
        # because the caller proved all real times < PACK_MAX — and
        # the transition rows narrow to pk_t, halving (or quartering)
        # the round's dominant gather stream.
        def _pk(x):
            if not pack:
                return x
            return jnp.minimum(x, jnp.asarray(PACK_INF,
                                              x.dtype)).astype(pk_i)

        inv_p = _pk(jnp.concatenate(
            [inv, jnp.full((1,), INF, jnp.int32)]))
        ret_p = _pk(jnp.concatenate(
            [ret, jnp.full((1,), INF, jnp.int32)]))
        opc_p = jnp.concatenate([opc, jnp.zeros((1,), jnp.int32)])
        suf_p = _pk(suf)
        iinv_p = _pk(iinv)
        if fused:
            # Grand table GT: rows (pos, mst) -> [inv, ret, nst, suf]
            # for ok ops, then (m, mst) -> [iinv, 0, nst, 0] for info
            # ops — the round's whole lookup plane in one gather.
            np1 = n_pad + 1
            nst_ok = T[:, opc_p].T.astype(pk_i)               # (np1, S)
            ok_rows = jnp.stack(
                [jnp.broadcast_to(inv_p[:, None], (np1, S)),
                 jnp.broadcast_to(ret_p[:, None], (np1, S)),
                 nst_ok,
                 jnp.broadcast_to(suf_p[:, None], (np1, S))],
                axis=2).reshape(np1 * S, 4)
            nst_i = T[:, iopc].T.astype(pk_i)                 # (ic, S)
            info_rows = jnp.stack(
                [jnp.broadcast_to(iinv_p[:, None], (ic_pad, S)),
                 jnp.zeros((ic_pad, S), pk_i),
                 nst_i,
                 jnp.zeros((ic_pad, S), pk_i)],
                axis=2).reshape(ic_pad * S, 4)
            GT = jnp.concatenate([ok_rows, info_rows])
        else:
            # meta rows [inv, ret, opcode, sufminret] with a sentinel
            # row at n_pad; TK[o * S + s] = T[s, o] rows.
            meta = jnp.stack([inv_p, ret_p,
                              opc_p.astype(pk_i), suf_p], axis=1)
            TK = jnp.broadcast_to(
                T.T.reshape(-1, 1).astype(pk_t), (S * O, 2))
            GT = (meta, TK)
        return (GT, iinv_p, iopc, n_ok, n_info, max_cfg)

    def chunk_fn(consts, carry):
        max_cfg = consts[-1]
        rconsts = _round_consts(consts)

        def cond(c):
            flags, stats = c[FLAGS], c[STATS]
            return (~flags[0]) & (c[FR_CNT] > 0) \
                & (stats[1] < chunk) & (stats[0] < max_cfg)

        def body(c):
            if depth > 1:
                return round_body_deep(rconsts, c)
            return round_body(rconsts, c)

        stats = carry[STATS]
        carry = carry[:STATS] + (stats.at[1].set(0),) \
            + carry[STATS + 1:]
        out = lax.while_loop(cond, body, carry)
        # one packed summary so the host polls with a SINGLE
        # device->host transfer per chunk (each transfer costs a full
        # runtime round-trip — ~75 ms through the tunneled v5e, which
        # dominated the headline wall before this): [fr_cnt, flags x3,
        # stats x6, bk_cnt] + the flattened per-round occupancy ring.
        # Existing consumers index the leading SUMMARY_HEAD words;
        # occupancy.drain_chunk reads the ring tail. bk_cnt feeds the
        # telemetry timeseries (metrics.py).
        summary = jnp.concatenate(
            [out[FR_CNT][None], out[FLAGS].astype(jnp.int32),
             out[STATS], out[BK_CNT][None],
             out[RING_BUF].reshape(-1)])
        return out, summary

    def chunk_fn_batched(consts, carry):
        """Lane-packed chunk loop: consts/carry carry a leading lane
        axis and ONE `lax.while_loop` drives every lane, with decided
        lanes masked INSIDE the body (`round_body(halt=...)`).

        `jax.vmap(chunk_fn)` would instead lower the while_loop to
        lockstep-with-select: every round re-materializes the WHOLE
        batched carry — dominated by the (lanes, H, 4) memo table, ~8
        MB/lane/round of pure copy — which measured ~120x the round's
        real work on a host build. Keeping the lane axis inside the
        loop makes a halted lane cost a few dozen selected words and
        lets the live lanes amortize the round's fixed op-dispatch
        overhead, which is the lane-packing win the mesh scheduler
        exists for."""
        import jax

        max_cfg = consts[-1]
        rconsts = jax.vmap(_round_consts)(consts)

        def live_of(c):
            flags, stats = c[FLAGS], c[STATS]
            return ((~flags[:, 0]) & (c[FR_CNT] > 0)
                    & (stats[:, 1] < chunk) & (stats[:, 0] < max_cfg))

        def cond(c):
            return jnp.any(live_of(c))

        def body(c):
            halt = ~live_of(c)
            return jax.vmap(
                lambda rc, cc, h: round_body(rc, cc, halt=h))(
                    rconsts, c, halt)

        stats = carry[STATS]
        carry = carry[:STATS] + (stats.at[:, 1].set(0),) \
            + carry[STATS + 1:]
        out = lax.while_loop(cond, body, carry)
        summary = jnp.concatenate(
            [out[FR_CNT][:, None], out[FLAGS].astype(jnp.int32),
             out[STATS], out[BK_CNT][:, None],
             out[RING_BUF].reshape(out[RING_BUF].shape[0], -1)],
            axis=1)
        return out, summary

    if batched:
        assert depth == 1, "batched chunk loop is single-level only"
        return init_fn, chunk_fn_batched
    return init_fn, chunk_fn


@functools.lru_cache(maxsize=48)
def compiled_search32(n_pad: int, ic_pad: int, S: int, O: int,
                      K: int, H: int, B: int, chunk: int, probes: int,
                      W: int = 32, accel: bool = False, depth: int = 1,
                      compact: Optional[bool] = None,
                      pack: bool = False):
    import jax

    init_fn, chunk_fn = _build_search32(n_pad, ic_pad, S, O,
                                        K, H, B, chunk, probes, W=W,
                                        accel=accel, depth=depth,
                                        compact=compact, pack=pack)
    return init_fn, jax.jit(chunk_fn, donate_argnums=(1,))
