"""Packed multi-lane WGL kernel: windows of 32 < W <= 1024 as L
uint32 lanes.

The general kernel (`wgl.py _build_search`) keeps the window as a
(K, W) bool tensor; profiling puts its per-round cost in the
(K, W, 2W) renormalization gathers and the 3-key sort over all
R = K*(W + ic) successor rows. The uint32 fast path (`wgl32.py`)
showed both costs are artifacts of the representation: with the
window packed into bit lanes, successor construction is elementwise
bit math and dedup is probe-only (racing twins detected at insert
time) — no sort, no W^2 intermediates.

This kernel generalizes the packing to L lanes:

  * window bit j lives in lane j//32, bit j%32; setting it is
    `win | set_mask[j]` with a host-precomputed (W, L) mask table —
    the successor tensor is (K, W, L) uint32, 8x smaller than the
    bool kernel's (K, W, 2W) machinery at W=512.
  * renormalization (advance base past the linearized prefix) is a
    cross-lane funnel shift: t = q*32 + r trailing ones, where q is
    the first lane with a zero bit and r its trailing-ones count;
    the shifted window is `(lane[l+q] >> r) | (lane[l+q+1] <<
    (32-r))` with gathers clamped past L.
  * dedup, backlog spill/refill, flags and stats are wgl32's,
    unchanged — same CONSTS contract as `_build_search`, so the host
    driver (`wgl.check`) dispatches by window width alone, and the
    mesh-sharded vmap batch path (`parallel/batched.py`) vmaps this
    kernel directly for wide lanes (carry indices 4/11/12 — fr_cnt,
    flags, stats — are layout-compatible with wgl32's).

Measured (cpu backend, adversarial_wave 6x14 span 5, W=71 -> L=3):
the bool kernel decides 811k configs in ~103 s; this kernel in ~9 s
— enough to decide the 2.2M-config bench shape inside the 60 s
budget ON CPU, where the host oracle DNFs.
"""

from __future__ import annotations

import functools

import numpy as np

from .wgl32 import _ctz32, _fnv_words

INF = np.int32(2**31 - 1)


def _build_searchN(n_pad: int, ic_pad: int, S: int, O: int,
                   K: int, H: int, B: int, chunk: int, probes: int,
                   W: int, L: int):
    """Build (init_fn, chunk_fn) for the packed L-lane kernel.
    W == 32*L is the materialized window width."""
    import jax.numpy as jnp
    from jax import lax

    assert W == 32 * L and L >= 2
    Il = max(1, (ic_pad + 31) // 32)

    # host-precomputed tables
    j_arr = np.arange(W)
    lane_of_j = (j_arr // 32).astype(np.int32)          # (W,)
    shift_of_j = (j_arr % 32).astype(np.uint32)         # (W,)
    set_mask = np.zeros((W, L), dtype=np.uint32)
    set_mask[j_arr, lane_of_j] = np.uint32(1) << shift_of_j
    info_word = np.arange(ic_pad) // 32
    info_bit = (np.uint32(1) << (np.arange(ic_pad) % 32))
    info_set_mask = np.zeros((ic_pad, Il), dtype=np.uint32)
    info_set_mask[np.arange(ic_pad), info_word] = info_bit

    def init_fn(mstate0):
        fr_base = jnp.zeros(K, dtype=jnp.int32)
        fr_win = jnp.zeros((K, L), dtype=jnp.uint32)
        fr_info = jnp.zeros((K, Il), dtype=jnp.uint32)
        fr_mst = jnp.zeros(K, dtype=jnp.int32).at[0].set(mstate0)
        fr_cnt = jnp.int32(1)
        bk_base = jnp.zeros(B, dtype=jnp.int32)
        bk_win = jnp.zeros((B, L), dtype=jnp.uint32)
        bk_info = jnp.zeros((B, Il), dtype=jnp.uint32)
        bk_mst = jnp.zeros(B, dtype=jnp.int32)
        bk_cnt = jnp.int32(0)
        table = jnp.zeros((H, 4), dtype=jnp.uint32)
        flags = jnp.zeros(3, dtype=bool)   # found, overflow, exhausted
        # explored, rounds-in-chunk, max_base, memo_hits, inserted,
        # rounds_total (util contract, wgl.py)
        stats = jnp.zeros(6, dtype=jnp.int32)
        return (fr_base, fr_win, fr_info, fr_mst, fr_cnt,
                bk_base, bk_win, bk_info, bk_mst, bk_cnt,
                table, flags, stats)

    jlane = jnp.asarray(lane_of_j)
    jshift = jnp.asarray(shift_of_j)
    jset = jnp.asarray(set_mask)
    jinfo_word = jnp.asarray(info_word.astype(np.int32))
    jinfo_bit = jnp.asarray(info_bit)
    jinfo_set = jnp.asarray(info_set_mask)

    def round_body(consts, carry):
        (inv, ret, opc, suf, iinv, iopc, T, n_ok, n_info, max_cfg) = consts
        (fr_base, fr_win, fr_info, fr_mst, fr_cnt,
         bk_base, bk_win, bk_info, bk_mst, bk_cnt,
         table, flags, stats) = carry

        alive = jnp.arange(K, dtype=jnp.int32) < fr_cnt
        j = jnp.arange(W, dtype=jnp.int32)
        # linearized flag of window slot j: bit j%32 of lane j//32
        winw = fr_win[:, jlane]                           # (K, W)
        linearized = ((winw >> jshift[None, :])
                      & jnp.uint32(1)) == 1

        # --- candidate discovery (identical shape to wgl32) ----------
        pos = fr_base[:, None] + j                        # (K, W)
        posc = jnp.minimum(pos, n_pad - 1)
        retw = jnp.where(linearized | (pos >= n_ok), INF, ret[posc])
        minret = jnp.min(retw, axis=1)
        tail = suf[jnp.minimum(fr_base + W, n_pad)]
        minret = jnp.minimum(minret, tail)                # (K,)

        invw = inv[posc]
        cand_ok = (~linearized) & (pos < n_ok) \
            & (invw < minret[:, None]) & alive[:, None]
        opw = opc[posc]
        nst_ok = T[fr_mst[:, None], opw]                  # (K, W)
        legal_ok = cand_ok & (nst_ok >= 0)

        m = jnp.arange(ic_pad, dtype=jnp.int32)
        info_words = fr_info[:, jinfo_word]               # (K, ic)
        info_set = (info_words & jinfo_bit[None, :]) != 0
        cand_info = (~info_set) & (m[None, :] < n_info) \
            & (iinv[None, :] < minret[:, None]) & alive[:, None]
        nst_info = T[fr_mst[:, None], iopc[None, :]]      # (K, ic)
        legal_info = cand_info & (nst_info >= 0)

        # --- ok successors: set bit j, then funnel-shift right -------
        win_ok = fr_win[:, None, :] | jset[None, :, :]    # (K, W, L)
        full = win_ok == jnp.uint32(0xFFFFFFFF)           # (K, W, L)
        # q: first lane with a zero bit (L if none — fully drained)
        q = jnp.argmin(full, axis=2).astype(jnp.int32)    # (K, W)
        all_full = jnp.all(full, axis=2)
        q = jnp.where(all_full, L, q)
        lane_q = jnp.take_along_axis(
            win_ok, jnp.minimum(q, L - 1)[:, :, None],
            axis=2)[:, :, 0]                              # (K, W)
        r = _ctz32(~lane_q)                               # (K, W) u32
        r = jnp.where(all_full, jnp.uint32(0), r)
        t = q * 32 + r.astype(jnp.int32)                  # (K, W)

        # shifted[l] = (win[l+q] >> r) | (win[l+q+1] << (32-r))
        lidx = jnp.arange(L, dtype=jnp.int32)             # (L,)
        src0 = lidx[None, None, :] + q[:, :, None]        # (K, W, L)
        src1 = src0 + 1
        gather0 = jnp.take_along_axis(
            jnp.concatenate([win_ok,
                             jnp.zeros((K, W, L), jnp.uint32)],
                            axis=2),
            jnp.minimum(src0, 2 * L - 1), axis=2)
        gather1 = jnp.take_along_axis(
            jnp.concatenate([win_ok,
                             jnp.zeros((K, W, L), jnp.uint32)],
                            axis=2),
            jnp.minimum(src1, 2 * L - 1), axis=2)
        ru = r[:, :, None]
        shifted = jnp.where(
            ru == 0, gather0,
            (gather0 >> ru) | (gather1 << (jnp.uint32(32) - ru)))
        base_ok = fr_base[:, None] + t                    # (K, W)

        # --- info successors: set info bit m, window unchanged -------
        info_new = fr_info[:, None, :] | jinfo_set[None, :, :]
        win_i = jnp.broadcast_to(fr_win[:, None, :], (K, ic_pad, L))
        info_ok = jnp.broadcast_to(fr_info[:, None, :], (K, W, Il))

        base_s = jnp.concatenate(
            [base_ok.reshape(-1),
             jnp.broadcast_to(fr_base[:, None], (K, ic_pad)).reshape(-1)])
        win_s = jnp.concatenate(
            [shifted.reshape(-1, L), win_i.reshape(-1, L)])  # (R, L)
        info_s = jnp.concatenate(
            [info_ok.reshape(-1, Il), info_new.reshape(-1, Il)])
        mst_s = jnp.concatenate(
            [nst_ok.reshape(-1), nst_info.reshape(-1)])
        legal = jnp.concatenate(
            [legal_ok.reshape(-1), legal_info.reshape(-1)])  # (R,)
        R = legal.shape[0]

        success = legal & (base_s >= n_ok) \
            & jnp.all(win_s == 0, axis=1)
        found = jnp.any(success)
        explore = legal & ~success

        # --- hash + probe dedup (wgl32's, L window words) ------------
        words = ([base_s.astype(jnp.uint32)]
                 + [win_s[:, i] for i in range(L)]
                 + [mst_s.astype(jnp.uint32)]
                 + [info_s[:, i] for i in range(Il)])
        s0 = _fnv_words(words, 0x811C9DC5) | jnp.uint32(1)
        s1 = _fnv_words(words, 0x01000193)
        s2 = _fnv_words(words, 0xDEADBEEF)
        myrow = jnp.arange(R, dtype=jnp.uint32)
        step = s1 | jnp.uint32(1)
        mysig = jnp.stack([s0, s1, s2], axis=1)           # (R, 3)

        def probe(_, st):
            table, pending, seen, pr = st
            idx = ((s0 + pr * step) & jnp.uint32(H - 1)).astype(jnp.int32)
            slot = table[idx]
            occupied = slot[:, 0] != 0
            sig_eq = jnp.all(slot[:, :3] == mysig, axis=1)
            equal = occupied & sig_eq
            seen = seen | (pending & equal)
            claim = pending & ~occupied
            widx = jnp.where(claim, idx, H)
            entry = jnp.concatenate([mysig, myrow[:, None]], axis=1)
            table = table.at[widx].set(entry, mode="drop")
            slot2 = table[idx]
            sig_eq2 = jnp.all(slot2[:, :3] == mysig, axis=1)
            won = claim & sig_eq2 & (slot2[:, 3] == myrow)
            twin = claim & sig_eq2 & ~won
            seen = seen | twin
            pending = pending & ~(equal | won | twin)
            pr = pr + pending.astype(jnp.uint32)
            return table, pending, seen, pr

        table, pending, seen, _ = lax.fori_loop(
            0, probes, probe,
            (table, explore, jnp.zeros(R, dtype=bool),
             jnp.zeros(R, dtype=jnp.uint32)))
        new = explore & ~seen

        # --- compact survivors into frontier + backlog ---------------
        posn = jnp.cumsum(new.astype(jnp.int32)) - 1
        total = jnp.sum(new.astype(jnp.int32))

        to_front = new & (posn < K)
        fidx = jnp.where(to_front, posn, K)
        nfr_base = jnp.zeros(K, dtype=jnp.int32).at[fidx].set(
            base_s, mode="drop")
        nfr_win = jnp.zeros((K, L), dtype=jnp.uint32).at[fidx].set(
            win_s, mode="drop")
        nfr_info = jnp.zeros((K, Il), dtype=jnp.uint32).at[fidx].set(
            info_s, mode="drop")
        nfr_mst = jnp.zeros(K, dtype=jnp.int32).at[fidx].set(
            mst_s, mode="drop")
        nfr_cnt = jnp.minimum(total, K)

        spill = new & (posn >= K)
        sidx = jnp.where(spill, bk_cnt + posn - K, B)
        overflow = jnp.any(spill & (sidx >= B))
        sidx = jnp.minimum(sidx, B)
        bk_base = bk_base.at[sidx].set(base_s, mode="drop")
        bk_win = bk_win.at[sidx].set(win_s, mode="drop")
        bk_info = bk_info.at[sidx].set(info_s, mode="drop")
        bk_mst = bk_mst.at[sidx].set(mst_s, mode="drop")
        nbk_cnt = jnp.minimum(bk_cnt + jnp.maximum(total - K, 0), B)

        room = K - nfr_cnt
        take = jnp.minimum(room, nbk_cnt)
        kidx = jnp.arange(K, dtype=jnp.int32)
        taking = kidx < take
        src = jnp.where(taking, jnp.maximum(nbk_cnt - 1 - kidx, 0), 0)
        dst = jnp.where(taking, nfr_cnt + kidx, K)
        nfr_base = nfr_base.at[dst].set(bk_base[src], mode="drop")
        nfr_win = nfr_win.at[dst].set(bk_win[src], mode="drop")
        nfr_info = nfr_info.at[dst].set(bk_info[src], mode="drop")
        nfr_mst = nfr_mst.at[dst].set(bk_mst[src], mode="drop")
        nfr_cnt = nfr_cnt + take
        nbk_cnt = nbk_cnt - take

        nflags = jnp.stack([flags[0] | found,
                            flags[1] | overflow,
                            nfr_cnt == 0])
        nstats = jnp.stack([
            stats[0] + fr_cnt,
            stats[1] + 1,
            jnp.maximum(stats[2], jnp.max(jnp.where(legal, base_s, 0))),
            stats[3] + jnp.sum(seen.astype(jnp.int32)),
            stats[4] + total,
            stats[5] + 1])
        return (nfr_base, nfr_win, nfr_info, nfr_mst, nfr_cnt,
                bk_base, bk_win, bk_info, bk_mst, nbk_cnt,
                table, nflags, nstats)

    def chunk_fn(consts, carry):
        max_cfg = consts[-1]

        def cond(c):
            flags, stats = c[11], c[12]
            return (~flags[0]) & (c[4] > 0) \
                & (stats[1] < chunk) & (stats[0] < max_cfg)

        def body(c):
            return round_body(consts, c)

        stats = carry[12]
        carry = carry[:12] + (stats.at[1].set(0),)
        return lax.while_loop(cond, body, carry)

    return init_fn, chunk_fn


@functools.lru_cache(maxsize=32)
def compiled_searchN(n_pad: int, ic_pad: int, S: int, O: int,
                     K: int, H: int, B: int, chunk: int, probes: int,
                     W: int, L: int):
    import jax

    init_fn, chunk_fn = _build_searchN(n_pad, ic_pad, S, O,
                                       K, H, B, chunk, probes, W, L)
    return init_fn, jax.jit(chunk_fn, donate_argnums=(1,))
