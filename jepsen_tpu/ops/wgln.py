"""Packed multi-lane WGL kernel: windows of 32 < W <= 1024 as L
uint32 lanes.

The general kernel (`wgl.py _build_search`) keeps the window as a
(K, W) bool tensor; profiling puts its per-round cost in the
(K, W, 2W) renormalization gathers and the 3-key sort over all
R = K*(W + ic) successor rows. The uint32 fast path (`wgl32.py`)
showed both costs are artifacts of the representation: with the
window packed into bit lanes, successor construction is elementwise
bit math and dedup is probe-only (racing twins detected at insert
time) — no sort, no W^2 intermediates.

This kernel generalizes the packing to L lanes:

  * window bit j lives in lane j//32, bit j%32; setting it is
    `win | set_mask[j]` with a host-precomputed (W, L) mask table —
    the successor tensor is (K, W, L) uint32, 8x smaller than the
    bool kernel's (K, W, 2W) machinery at W=512.
  * renormalization (advance base past the linearized prefix) is a
    cross-lane funnel shift: t = q*32 + r trailing ones, where q is
    the first lane with a zero bit and r its trailing-ones count;
    the shifted window is `(lane[l+q] >> r) | (lane[l+q+1] <<
    (32-r))` with gathers clamped past L.
  * memory layout and dedup are wgl32's scatter-lean scheme (see its
    module docstring for the measured cost model): each config is ONE
    int32 row [base, win lanes..., mst, info words...] so frontier
    (K, C) and backlog (B, C) update in one scatter each, op metadata
    and the transition table ride fused row-gathers, and the memo
    probe is `wgl32.probe_insert` (one gather + one scatter + one
    verify gather). Same consts contract as `wgl._build_search`; same
    packed carry (fr, fr_cnt, bk, bk_cnt, table, flags, stats, ring)
    as wgl32 — including the per-round occupancy ring — so the host
    driver (`wgl.check`) dispatches by window width alone and
    `parallel/batched.py` vmaps either kernel.

Measured (cpu backend, adversarial_wave 6x14 span 5, W=71 -> L=3):
the bool kernel decides 811k configs in ~103 s; this kernel in ~9 s
— enough to decide the 2.2M-config bench shape inside the 60 s
budget ON CPU, where the host oracle DNFs.
"""

from __future__ import annotations

import functools

import numpy as np

from .wgl32 import BK_CNT, FLAGS, FR_CNT, PACK_INF, RING_BUF, \
    RING_COLS, RING_ROWS, STATS, _ctz32, _fnv_words, _i32, _u32, \
    make_compact_frontier, probe_insert

INF = np.int32(2**31 - 1)


def _build_searchN(n_pad: int, ic_pad: int, S: int, O: int,
                   K: int, H: int, B: int, chunk: int, probes: int,
                   W: int, L: int, accel: bool = False,
                   compact: bool = False, pack: bool = False):
    """Build (init_fn, chunk_fn) for the packed L-lane kernel.
    W == 32*L is the materialized window width. `accel` picks the
    accelerator layout (see wgl32._build_search32). `compact` and
    `pack` are the compact-before-expand beam pre-pass and the
    int16/int8 packed lookup tables — the wgl32 docstring has both
    contracts; this kernel has no depth-fused path, so its beam is
    duplicate-free by construction and `compact` defaults off."""
    import jax.numpy as jnp
    from jax import lax

    assert W == 32 * L and L >= 2
    Il = max(1, (ic_pad + 31) // 32)
    C = 2 + L + Il  # [base, win lanes..., mst, info words...]
    MST = 1 + L     # column index of the model state
    fused = accel and (n_pad + 1) * S + ic_pad * S <= (1 << 22)
    pk_i = jnp.int16 if pack else jnp.int32
    pk_t = jnp.int8 if pack and S <= 127 else pk_i
    pinf = jnp.asarray(PACK_INF if pack else INF, pk_i)

    # host-precomputed tables
    j_arr = np.arange(W)
    lane_of_j = (j_arr // 32).astype(np.int32)          # (W,)
    shift_of_j = (j_arr % 32).astype(np.uint32)         # (W,)
    set_mask = np.zeros((W, L), dtype=np.uint32)
    set_mask[j_arr, lane_of_j] = np.uint32(1) << shift_of_j
    info_word = np.arange(ic_pad) // 32
    info_bit = (np.uint32(1) << (np.arange(ic_pad) % 32))
    info_set_mask = np.zeros((ic_pad, Il), dtype=np.uint32)
    info_set_mask[np.arange(ic_pad), info_word] = info_bit

    def init_fn(mstate0):
        fr = jnp.zeros((K, C), dtype=jnp.int32).at[0, MST].set(mstate0)
        fr_cnt = jnp.int32(1)
        bk = jnp.zeros((B, C), dtype=jnp.int32)
        bk_cnt = jnp.int32(0)
        table = jnp.zeros((H, 4), dtype=jnp.uint32)
        flags = jnp.zeros(3, dtype=bool)   # found, overflow, exhausted
        # explored, rounds-in-chunk, max_base, memo_hits, inserted,
        # rounds_total (util contract, wgl.py)
        stats = jnp.zeros(6, dtype=jnp.int32)
        # per-round occupancy ring (wgl32.RING_ROWS docs)
        ring = jnp.zeros((RING_ROWS, RING_COLS), dtype=jnp.int32)
        return (fr, fr_cnt, bk, bk_cnt, table, flags, stats, ring)

    jlane = jnp.asarray(lane_of_j)
    jshift = jnp.asarray(shift_of_j)
    jset = jnp.asarray(set_mask)
    jinfo_word = jnp.asarray(info_word.astype(np.int32))
    jinfo_bit = jnp.asarray(info_bit)
    jinfo_set = jnp.asarray(info_set_mask)

    _compact_frontier = make_compact_frontier(K, C)

    def round_body(consts, carry):
        (GT, iinv, iopc_c, n_ok, n_info, max_cfg) = consts
        (fr, fr_cnt, bk, bk_cnt, table, flags, stats, ring) = carry
        dups = jnp.int32(0)
        if compact:
            fr, fr_cnt, dups = _compact_frontier(fr, fr_cnt)

        fr_base = fr[:, 0]
        fr_win = _u32(fr[:, 1:1 + L])                     # (K, L)
        fr_mst = fr[:, MST]
        fr_info = _u32(fr[:, MST + 1:])                   # (K, Il)

        alive = jnp.arange(K, dtype=jnp.int32) < fr_cnt
        j = jnp.arange(W, dtype=jnp.int32)
        # linearized flag of window slot j: bit j%32 of lane j//32
        winw = fr_win[:, jlane]                           # (K, W)
        linearized = ((winw >> jshift[None, :])
                      & jnp.uint32(1)) == 1

        # --- candidate discovery (wgl32's fused-gather shape) --------
        pos = fr_base[:, None] + j                        # (K, W)
        posc = jnp.minimum(pos, n_pad - 1)
        tailp = jnp.minimum(fr_base + W, n_pad)           # (K,)
        m = jnp.arange(ic_pad, dtype=jnp.int32)
        if fused:
            gidx = jnp.concatenate(
                [(posc * S + fr_mst[:, None]).reshape(-1),
                 tailp * S + fr_mst,
                 ((n_pad + 1) * S + m[None, :] * S
                  + fr_mst[:, None]).reshape(-1)])
            grows = GT[gidx]                              # gather
            okrows = grows[:K * W].reshape(K, W, 4)
            invw, retw0, nst_ok = (okrows[..., 0], okrows[..., 1],
                                   okrows[..., 2])
            tail = grows[K * W:K * W + K, 3]              # (K,)
            irows = grows[K * W + K:].reshape(K, ic_pad, 4)
            iinvw, nst_info = irows[..., 0], irows[..., 2]
        else:
            (meta, TK) = GT
            mrows = meta[posc.reshape(-1)].reshape(K, W, 4)   # gather
            invw, retw0, opw = (mrows[..., 0], mrows[..., 1],
                                mrows[..., 2])
            tail = meta[tailp][:, 3]                      # gather
            # int32 index math: packed meta may be int16 (wgl32 note)
            opw32 = opw.astype(jnp.int32)
            tidx = jnp.concatenate(
                [(opw32 * S + fr_mst[:, None]).reshape(-1),
                 (iopc_c[None, :] * S + fr_mst[:, None]).reshape(-1)])
            nst_all = TK[tidx][:, 0]                      # gather
            nst_ok = nst_all[:K * W].reshape(K, W)
            nst_info = nst_all[K * W:].reshape(K, ic_pad)
            iinvw = jnp.broadcast_to(iinv[None, :], (K, ic_pad))

        retw = jnp.where(linearized | (pos >= n_ok), pinf, retw0)
        minret = jnp.min(retw, axis=1)
        minret = jnp.minimum(minret, tail)                # (K,)

        cand_ok = (~linearized) & (pos < n_ok) \
            & (invw < minret[:, None]) & alive[:, None]

        if Il == 1:
            info_words = jnp.broadcast_to(fr_info[:, :1], (K, ic_pad))
        else:
            info_words = fr_info[:, jinfo_word]           # (K, ic)
        info_set = (info_words & jinfo_bit[None, :]) != 0
        cand_info = (~info_set) & (m[None, :] < n_info) \
            & (iinvw < minret[:, None]) & alive[:, None]

        legal_ok = cand_ok & (nst_ok >= 0)
        legal_info = cand_info & (nst_info >= 0)

        # --- ok successors: set bit j, then funnel-shift right -------
        win_ok = fr_win[:, None, :] | jset[None, :, :]    # (K, W, L)
        full = win_ok == jnp.uint32(0xFFFFFFFF)           # (K, W, L)
        # q: first lane with a zero bit (L if none — fully drained)
        q = jnp.argmin(full, axis=2).astype(jnp.int32)    # (K, W)
        all_full = jnp.all(full, axis=2)
        q = jnp.where(all_full, L, q)
        lane_q = jnp.take_along_axis(
            win_ok, jnp.minimum(q, L - 1)[:, :, None],
            axis=2)[:, :, 0]                              # (K, W)
        r = _ctz32(~lane_q)                               # (K, W) u32
        r = jnp.where(all_full, jnp.uint32(0), r)
        t = q * 32 + r.astype(jnp.int32)                  # (K, W)

        # shifted[l] = (win[l+q] >> r) | (win[l+q+1] << (32-r))
        lidx = jnp.arange(L, dtype=jnp.int32)             # (L,)
        src0 = lidx[None, None, :] + q[:, :, None]        # (K, W, L)
        src1 = src0 + 1
        gather0 = jnp.take_along_axis(
            jnp.concatenate([win_ok,
                             jnp.zeros((K, W, L), jnp.uint32)],
                            axis=2),
            jnp.minimum(src0, 2 * L - 1), axis=2)
        gather1 = jnp.take_along_axis(
            jnp.concatenate([win_ok,
                             jnp.zeros((K, W, L), jnp.uint32)],
                            axis=2),
            jnp.minimum(src1, 2 * L - 1), axis=2)
        ru = r[:, :, None]
        shifted = jnp.where(
            ru == 0, gather0,
            (gather0 >> ru) | (gather1 << (jnp.uint32(32) - ru)))
        base_ok = fr_base[:, None] + t                    # (K, W)

        # --- info successors: set info bit m, window unchanged -------
        info_new = fr_info[:, None, :] | jinfo_set[None, :, :]
        win_i = jnp.broadcast_to(fr_win[:, None, :], (K, ic_pad, L))
        info_ok = jnp.broadcast_to(fr_info[:, None, :], (K, W, Il))

        base_s = jnp.concatenate(
            [base_ok.reshape(-1),
             jnp.broadcast_to(fr_base[:, None], (K, ic_pad)).reshape(-1)])
        win_s = jnp.concatenate(
            [shifted.reshape(-1, L), win_i.reshape(-1, L)])  # (R, L)
        info_s = jnp.concatenate(
            [info_ok.reshape(-1, Il), info_new.reshape(-1, Il)])
        mst_s = jnp.concatenate(
            [nst_ok.reshape(-1),
             nst_info.reshape(-1)]).astype(jnp.int32)
        legal = jnp.concatenate(
            [legal_ok.reshape(-1), legal_info.reshape(-1)])  # (R,)

        success = legal & (base_s >= n_ok) \
            & jnp.all(win_s == 0, axis=1)
        found = jnp.any(success)
        explore = legal & ~success

        # --- hash + probe dedup (shared with wgl32) ------------------
        words = ([base_s.astype(jnp.uint32)]
                 + [win_s[:, i] for i in range(L)]
                 + [mst_s.astype(jnp.uint32)]
                 + [info_s[:, i] for i in range(Il)])
        s0 = _fnv_words(words, 0x811C9DC5) | jnp.uint32(1)
        s1 = _fnv_words(words, 0x01000193)
        s2 = _fnv_words(words, 0xDEADBEEF)
        table, seen = probe_insert(table, s0, s1, s2, explore, probes, H)
        new = explore & ~seen

        # --- compact survivors into frontier + backlog ---------------
        succ = jnp.concatenate(
            [base_s[:, None],
             _i32(win_s),
             mst_s[:, None],
             _i32(info_s)], axis=1)                       # (R, C)

        R = succ.shape[0]
        posn = jnp.cumsum(new.astype(jnp.int32)) - 1
        total = jnp.sum(new.astype(jnp.int32))

        if accel:
            score = jnp.where(new, R - posn, 0)
            _, fsel = lax.top_k(score, K)                 # (K,)
            nfr = succ[fsel]                              # gather
        else:
            to_front = new & (posn < K)
            fidx = jnp.where(to_front, posn, K)
            nfr = jnp.zeros((K, C), dtype=jnp.int32).at[fidx].set(
                succ, mode="drop")
        nfr_cnt = jnp.minimum(total, K)

        spill = new & (posn >= K)
        sidx = jnp.where(spill, bk_cnt + posn - K, B)
        overflow = jnp.any(spill & (sidx >= B))
        sidx = jnp.minimum(sidx, B)

        def do_spill(b):
            return b.at[sidx].set(succ, mode="drop")

        bk = lax.cond(total > K, do_spill, lambda b: b, bk) if accel \
            else do_spill(bk)
        nbk_cnt = jnp.minimum(bk_cnt + jnp.maximum(total - K, 0), B)

        room = K - nfr_cnt
        take = jnp.minimum(room, nbk_cnt)

        def do_refill(args):
            nfr, bk = args
            kidx = jnp.arange(K, dtype=jnp.int32)
            taking = kidx < take
            src = jnp.where(taking, jnp.maximum(nbk_cnt - 1 - kidx, 0), 0)
            dst = jnp.where(taking, nfr_cnt + kidx, K)
            return nfr.at[dst].set(bk[src], mode="drop")

        nfr = lax.cond(take > 0, do_refill, lambda a: a[0],
                       (nfr, bk)) if accel else do_refill((nfr, bk))
        nfr_cnt = nfr_cnt + take
        nbk_cnt = nbk_cnt - take

        nflags = jnp.stack([flags[0] | found,
                            flags[1] | overflow,
                            nfr_cnt == 0])
        # compact-before-expand drops count as dedup hits (wgl32 note)
        seen_n = jnp.sum(seen.astype(jnp.int32)) + dups
        base_max = jnp.maximum(stats[2],
                               jnp.max(jnp.where(legal, base_s, 0)))
        nstats = jnp.stack([
            stats[0] + fr_cnt,
            stats[1] + 1,
            base_max,
            stats[3] + seen_n,
            stats[4] + total,
            stats[5] + 1])
        # per-round occupancy row (wgl32 ring contract)
        row = jnp.stack([nstats[5], fr_cnt, seen_n, total,
                         nfr_cnt, nbk_cnt, base_max])
        ring = ring.at[jnp.minimum(stats[1], RING_ROWS)].set(
            row, mode="drop")
        return (nfr, nfr_cnt, bk, nbk_cnt, table, nflags, nstats, ring)

    def chunk_fn(consts, carry):
        (inv, ret, opc, suf, iinv, iopc, T, n_ok, n_info, max_cfg) = consts

        # fused lookup tables (see wgl32.chunk_fn); `pack` narrows
        # the time columns to int16 / transitions to pk_t exactly as
        # the wgl32 build does
        def _pk(x):
            if not pack:
                return x
            return jnp.minimum(x, jnp.asarray(PACK_INF,
                                              x.dtype)).astype(pk_i)

        inv_p = _pk(jnp.concatenate(
            [inv, jnp.full((1,), INF, jnp.int32)]))
        ret_p = _pk(jnp.concatenate(
            [ret, jnp.full((1,), INF, jnp.int32)]))
        opc_p = jnp.concatenate([opc, jnp.zeros((1,), jnp.int32)])
        suf_p = _pk(suf)
        iinv_p = _pk(iinv)
        if fused:
            np1 = n_pad + 1
            nst_ok = T[:, opc_p].T.astype(pk_i)           # (np1, S)
            ok_rows = jnp.stack(
                [jnp.broadcast_to(inv_p[:, None], (np1, S)),
                 jnp.broadcast_to(ret_p[:, None], (np1, S)),
                 nst_ok,
                 jnp.broadcast_to(suf_p[:, None], (np1, S))],
                axis=2).reshape(np1 * S, 4)
            nst_i = T[:, iopc].T.astype(pk_i)             # (ic, S)
            info_rows = jnp.stack(
                [jnp.broadcast_to(iinv_p[:, None], (ic_pad, S)),
                 jnp.zeros((ic_pad, S), pk_i),
                 nst_i,
                 jnp.zeros((ic_pad, S), pk_i)],
                axis=2).reshape(ic_pad * S, 4)
            GT = jnp.concatenate([ok_rows, info_rows])
        else:
            meta = jnp.stack([inv_p, ret_p,
                              opc_p.astype(pk_i), suf_p], axis=1)
            TK = jnp.broadcast_to(
                T.T.reshape(-1, 1).astype(pk_t), (S * O, 2))
            GT = (meta, TK)
        rconsts = (GT, iinv_p, iopc, n_ok, n_info, max_cfg)

        def cond(c):
            flags, stats = c[FLAGS], c[STATS]
            return (~flags[0]) & (c[FR_CNT] > 0) \
                & (stats[1] < chunk) & (stats[0] < max_cfg)

        def body(c):
            return round_body(rconsts, c)

        stats = carry[STATS]
        carry = carry[:STATS] + (stats.at[1].set(0),) \
            + carry[STATS + 1:]
        out = lax.while_loop(cond, body, carry)
        # single packed host-poll summary + flattened occupancy ring
        # (see wgl32.chunk_fn)
        summary = jnp.concatenate(
            [out[FR_CNT][None], out[FLAGS].astype(jnp.int32),
             out[STATS], out[BK_CNT][None],
             out[RING_BUF].reshape(-1)])
        return out, summary

    return init_fn, chunk_fn


@functools.lru_cache(maxsize=48)
def compiled_searchN(n_pad: int, ic_pad: int, S: int, O: int,
                     K: int, H: int, B: int, chunk: int, probes: int,
                     W: int, L: int, accel: bool = False,
                     compact: bool = False, pack: bool = False):
    import jax

    init_fn, chunk_fn = _build_searchN(n_pad, ic_pad, S, O,
                                       K, H, B, chunk, probes, W, L,
                                       accel=accel, compact=compact,
                                       pack=pack)
    return init_fn, jax.jit(chunk_fn, donate_argnums=(1,))
