"""Shape-aware engine routing for single-history linearizability.

The device beam kernel earns its keep on branchy state spaces — wide
frontiers amortize its per-round dispatch. On NEAR-SERIAL histories the
frontier never fills (BENCH r3 `mutex_1k`: frontier_fill 0.136,
memo_hit_rate 0.0 — a beam of 16 doing a serial walk with vector
overhead) and the JIT-linearization sweep (`ops/jitlin.py`, the
knossos `linear` algorithm) decides in milliseconds.

Serial-ness is only partly visible from the history's interval
structure: `mutex_1k` and `register_500` have near-identical
concurrency depth (~3.6 vs ~4.0 mean pending ops), yet the mutex
frontier stays empty because the MODEL prunes almost every
interleaving (acquire-while-held is inconsistent) while the register
admits most of them (fill 0.88). So static interval stats cannot
route alone; this module measures shape statically AND probes
dynamically:

  1. a bounded jitlin PROBE (default 0.35 s / 30k configs): on
     near-serial or heavily-pruned shapes the sweep simply finishes —
     that IS the routing decision, and the verdict is already in hand;
  2. otherwise the device kernel runs with the remaining budget
     (branchy shapes blow the probe's config cap almost immediately,
     so the detour costs milliseconds);
  3. a device "unknown" falls back to the host oracle, competition
     style.

Every result carries `engine` and `route_reason`, plus the static
`shape` stats, so BENCH configs explain their engine choice
(VERDICT r3 #8: no config should sit on the device engine with
frontier_fill < 0.3).
"""

from __future__ import annotations

import time as _time
from typing import Optional

import numpy as np

from ..history import History
from ..models.core import Model
from .encode import Encoded, EncodingUnsupported, encode


def shape_stats(enc: Encoded) -> dict:
    """Static interval structure of an encoded history: how deep does
    concurrency run, and how wide must the search window be."""
    n = int(enc.n_ok)
    if n == 0:
        return {"n_ok": 0, "n_info": int(enc.n_info),
                "W_raw": enc.window_raw,
                "mean_depth": 0.0, "p95_depth": 0}
    inv = enc.inv[:n].astype(np.int64)
    ret = enc.ret[:n].astype(np.int64)
    order_i = np.sort(inv)
    order_r = np.sort(ret)
    # pending depth at each invocation t: ops with inv <= t < ret
    depth = (np.searchsorted(order_i, inv, side="right")
             - np.searchsorted(order_r, inv, side="right"))
    return {"n_ok": n, "n_info": int(enc.n_info),
            "W_raw": int(enc.window_raw),
            "mean_depth": round(float(depth.mean()), 2),
            "p95_depth": int(np.percentile(depth, 95))}


def check_routed(model: Model, history: History,
                 time_limit: Optional[float] = None,
                 probe_s: float = 0.35,
                 probe_configs: int = 30_000,
                 enc: Optional[Encoded] = None) -> dict:
    """Single-history check with shape-aware engine choice (see module
    docstring). Returns the winning engine's result dict, annotated
    with `engine`, `route_reason`, and `shape`."""
    from . import jitlin, wgl, wgl_ref

    t0 = _time.monotonic()
    try:
        enc = enc or encode(model, history)
    except EncodingUnsupported as e:
        r = wgl_ref.check(model, history, time_limit=time_limit)
        r["engine"] = "oracle"
        r["route_reason"] = f"encoding unsupported: {e}"
        return r
    shape = shape_stats(enc)

    # 1. jitlin probe — decides near-serial / model-pruned shapes
    #    outright; branchy shapes exhaust the config cap in ms.
    budget = (min(probe_s, time_limit / 4) if time_limit is not None
              else probe_s)
    r = jitlin.check(model, history, time_limit=budget,
                     max_configs=probe_configs)
    if r.get("valid?") != "unknown":
        r["engine"] = "jitlin"
        r["route_reason"] = (
            f"probe decided in {_time.monotonic() - t0:.3f}s "
            f"(near-serial or model-pruned shape)")
        r["shape"] = shape
        return r

    probe_cause = r.get("cause", "budget")

    # 2. device kernel on the remaining budget
    left = (time_limit - (_time.monotonic() - t0)
            if time_limit is not None else None)
    if left is not None and left <= 0.05:
        r["engine"] = "jitlin"
        r["route_reason"] = f"probe consumed the budget ({probe_cause})"
        r["shape"] = shape
        return r
    r = wgl.check(model, history, time_limit=left, enc=enc)
    if r.get("valid?") != "unknown":
        r["engine"] = "device"
        r["route_reason"] = (
            f"probe hit {probe_cause}; branchy shape "
            f"(mean_depth {shape['mean_depth']}, W {shape['W_raw']}) "
            f"-> device kernel on platform "
            f"{r.get('platform', 'unknown')}")
        r["shape"] = shape
        return r

    # 3. oracle sweep with whatever remains
    left = (time_limit - (_time.monotonic() - t0)
            if time_limit is not None else None)
    if left is None or left > 0.5:
        r2 = wgl_ref.check(model, history, time_limit=left)
        if r2.get("valid?") != "unknown":
            r2["engine"] = "oracle"
            r2["route_reason"] = "device unknown; oracle fallback"
            r2["shape"] = shape
            return r2
    r["engine"] = "device"
    r["route_reason"] = "no engine decided within budget"
    r["shape"] = shape
    return r


# -- Elle cycle-engine routing ----------------------------------------------

def elle_cycle_route(*, n: int, e: int, rw_edges: int,
                     accel: bool, device_ok: bool,
                     packed_cap: int = 32768,
                     sharded_cap: int = 131072,
                     n_shards: int = 0,
                     cpu_cap: int = 16384,
                     min_n: int = 384,
                     min_host_work: int = 2_000_000) -> tuple:
    """The elle extension of this module's shape-aware routing: decide
    host vs device for the cycle-query battery from static graph
    stats, and say why (`route_reason` on results, exactly like the
    WGL router above).

    The host engine's hot spot is the per-rw-edge BFS in
    DepGraph.find_cycle_with — O(rw_edges x E) when the history is
    valid (every BFS exhausts the reachable set; measured here: 5300
    rw edges x 25k edges =~ 9 s of the elle_append_3k host wall). The
    device battery answers every query from one closure, so routing
    is a host-work model against a capacity check:

      * no usable jax backend           -> host
      * n > packed closure capacity     -> "sharded" when an
                                           accelerator fleet yields
                                           >= 2 word-column shards and
                                           n fits the sharded cap —
                                           the mesh-sharded closure is
                                           the only engine that holds
                                           the bitset at all; host
                                           Tarjan otherwise (on
                                           XLA-cpu the sharded
                                           squaring never pays)
      * small graph AND small BFS bill  -> host (kernel dispatch +
                                           compile-cache lookup costs
                                           more than it saves)
      * otherwise                       -> device; elle/tpu.py picks
                                           the kernel per shape
                                           (bf16 / packed / sharded).

    Returns (backend, reason) with backend in {"host", "device",
    "sharded"} — "sharded" pins the kernel (the shape demands it);
    "device" leaves the kernel pick to elle/tpu per shape."""
    host_work = rw_edges * max(e, 1)
    if not device_ok:
        return ("host", "no usable jax backend (missing or init "
                        "timed out); host Tarjan/BFS")
    if n > packed_cap:
        if accel and n <= sharded_cap and n_shards >= 2:
            return ("sharded",
                    f"n {n} over packed closure capacity "
                    f"{packed_cap}; {n_shards}-shard word columns "
                    f"across the mesh hold it")
        return ("host", f"n {n} over packed closure capacity "
                        f"{packed_cap}"
                        + (f" and no shardable fleet "
                           f"({n_shards} shards)" if accel else "")
                        + "; host Tarjan/BFS")
    if not accel and n > cpu_cap:
        # past this the trim kernel's peel rounds (bounded by n_pad)
        # stop paying for themselves on a single XLA-cpu core, and
        # the dense squarings were never an option there
        return ("host", f"n {n} over cpu device cap {cpu_cap}; "
                        "host Tarjan/BFS")
    if n < min_n and host_work < min_host_work:
        return ("host", f"small graph (n {n}, rw*E {host_work}): "
                        "host BFS beats a kernel dispatch")
    plat = "accelerator" if accel else "cpu-XLA"
    return ("device", f"n {n}, E {e}, rw {rw_edges} "
                      f"(host BFS model ~{host_work} node-visits) "
                      f"-> device closure battery on {plat}")
