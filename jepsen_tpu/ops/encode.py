"""Host-side encoding: history -> tensors for the TPU WGL kernel.

Turns the prepared LinOp list (`linprep.prepare`) into the fixed-shape
integer arrays the device search consumes:

  * ok ops sorted by invocation: inv[], ret[], opcode[]
  * info (crashed) ops: inv_info[], opcode_info[]
  * a model transition table T[S, O] -> next-state index or -1, built by
    enumerating the model's reachable state space on the host under the
    history's distinct (f, value) op alphabet

This is the bridge between the object-form models (knossos.model parity,
`jepsen_tpu.models.core`) and the jitted search. The reference's checker
selects the search engine by :algorithm (jepsen/src/jepsen/checker.clj:
199-202); here the table-driven encoding is what makes a single generic
jitted kernel serve every model.

Window-width theory: with `base` = index of the first unlinearized ok op,
an ok op j can only be linearized when some unlinearized op i <= j has
ret(i) > inv(j); hence j < searchsorted(inv, ret(base)). So
  W_needed = max_i ( #{j >= i : inv(j) < ret(i)} )
bounds how far beyond `base` any linearizable op can sit, and a W-slot
window loses nothing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from ..history import History
from ..models.core import Model, is_inconsistent
from .linprep import LinOp, prepare

INF = np.int32(2**31 - 1)  # event indices are small; x64 stays off

# Kernel limits (the `encode()` defaults). Single source of truth:
# the preflight admission analyzer (analysis/preflight) predicts
# EncodingUnsupported against these same constants, so a cap change
# here moves the P004 rule with it.
MAX_WINDOW = 1024
MAX_INFO = 256


def window_requirement(inv_ok: np.ndarray,
                       ret_ok: np.ndarray) -> tuple[int, int]:
    """(w_needed, W_padded) for inv-sorted ok-op intervals — the
    window-width theory in the module docstring, shared by `encode()`
    and the preflight shape probe so the two can never disagree."""
    n = len(inv_ok)
    if n:
        hi = np.searchsorted(inv_ok, ret_ok)
        w_needed = int(np.max(hi - np.arange(n)))
    else:
        w_needed = 1
    # Narrow windows bucket at 32 (few shapes, cheap); wide ones at
    # 128 so adversarial long-tail runs don't compile a fresh kernel
    # per history length.
    return w_needed, _pad_to(w_needed, 32 if w_needed <= 256 else 128)


class EncodingUnsupported(Exception):
    """The history/model cannot be encoded within kernel limits; callers
    should fall back to the host oracle.

    Carries machine-readable coordinates of the offending op so the
    history analyzer (`analysis/history_lint`) and error reports can
    point at the exact op instead of re-deriving it from the message:
    `op_index` (the op's :index), `process`, `value`, and `rule`
    (which limit tripped: "info-cap" | "state-space" | "window")."""

    def __init__(self, message: str, *, op_index: Optional[int] = None,
                 process: Any = None, value: Any = None,
                 rule: Optional[str] = None):
        super().__init__(message)
        self.op_index = op_index
        self.process = process
        self.value = value
        self.rule = rule

    def to_dict(self) -> dict:
        return {"message": str(self), "rule": self.rule,
                "op_index": self.op_index, "process": self.process,
                "value": self.value}


def _hashable(v):
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def build_table(model: Model, alphabet: list, max_states: int = 1 << 16,
                op_counts: Optional[dict] = None) -> tuple[np.ndarray, list]:
    """Enumerate the model's reachable states under `alphabet` (a list of
    ops as seen by Model.step) and return (T, states) where
    T[s, o] = next-state index or -1.

    `op_counts` (f -> multiplicity in the history) lets models prune
    states the at-most-once search can never reach (Model.unreachable),
    keeping e.g. queue state spaces finite."""
    op_counts = op_counts or {}
    states: dict = {model: 0}
    order: list = [model]
    rows: list[list[int]] = []
    i = 0
    while i < len(order):
        s = order[i]
        row = []
        for op in alphabet:
            m2 = s.step(op)
            if is_inconsistent(m2) or m2.unreachable(op_counts):
                row.append(-1)
            else:
                j = states.get(m2)
                if j is None:
                    if len(order) >= max_states:
                        raise EncodingUnsupported(
                            f"model state space exceeds {max_states}",
                            op_index=op.index, process=op.process,
                            value=op.value, rule="state-space")
                    j = len(order)
                    states[m2] = j
                    order.append(m2)
                row.append(j)
        rows.append(row)
        i += 1
    return np.asarray(rows, dtype=np.int32), order


@dataclass
class Encoded:
    """Everything the device search needs, in numpy (host) form."""

    n_ok: int              # number of ok (must-linearize) ops
    n_info: int            # number of crashed (may-linearize) ops
    inv: np.ndarray        # (n_pad,) i64, INF beyond n_ok
    ret: np.ndarray        # (n_pad,) i64, INF beyond n_ok
    opcode: np.ndarray     # (n_pad,) i32, 0 beyond n_ok
    sufminret: np.ndarray  # (n_pad+1,) i64; sufminret[i] = min ret[i:]
    inv_info: np.ndarray   # (ic_pad,) i64, INF beyond n_info
    opcode_info: np.ndarray  # (ic_pad,) i32
    table: np.ndarray      # (S, O) i32 transition table
    states: list           # state index -> model object
    window: int            # W, multiple of 32
    window_raw: int        # exact W requirement before padding
    lin_ops: list          # LinOp list (ok ops then info ops), for reporting


def _pad_to(n: int, multiple: int) -> int:
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


def encode(model: Model, history: History, max_window: int = MAX_WINDOW,
           max_states: int = 1 << 16, max_info: int = MAX_INFO) -> Encoded:
    """History + model -> Encoded tensors, or raise EncodingUnsupported."""
    ops = prepare(history)
    ok_ops = [o for o in ops if o.ok]
    info_ops = [o for o in ops if not o.ok]
    n, ni = len(ok_ops), len(info_ops)
    if ni > max_info:
        first_over = info_ops[max_info]  # the op past the cap
        raise EncodingUnsupported(
            f"{ni} crashed ops exceeds cap {max_info}",
            op_index=first_over.orig_index, process=first_over.process,
            value=first_over.value, rule="info-cap")

    # Distinct op alphabet over every op the search might apply.
    key_of = {}
    alphabet = []
    codes_ok = np.zeros(n, dtype=np.int32)
    codes_info = np.zeros(ni, dtype=np.int32)
    for arr, group in ((codes_ok, ok_ops), (codes_info, info_ops)):
        for i, o in enumerate(group):
            k = (o.f, _hashable(o.value))
            c = key_of.get(k)
            if c is None:
                c = len(alphabet)
                key_of[k] = c
                alphabet.append(o.as_op())
            arr[i] = c

    op_counts: dict = {}
    for o in ok_ops + info_ops:
        op_counts[o.f] = op_counts.get(o.f, 0) + 1
    table, states = build_table(model, alphabet, max_states=max_states,
                                op_counts=op_counts)

    inv_ok = np.asarray([o.inv for o in ok_ops], dtype=np.int32)
    # crashed ops have ret = INF_TIME (2**62); clamp into int32 range
    ret_ok = np.asarray([min(o.ret, 2**31 - 1) for o in ok_ops],
                        dtype=np.int32)
    # ok ops are already inv-sorted (prepare sorts); assert the invariant.
    if n > 1:
        assert np.all(np.diff(inv_ok) > 0)

    # Exact window requirement (see module docstring; shared with the
    # preflight shape probe).
    w_needed, W = window_requirement(inv_ok, ret_ok)
    if W > max_window:
        # the op whose open window drives the requirement
        hi = np.searchsorted(inv_ok, ret_ok)
        widest = ok_ops[int(np.argmax(hi - np.arange(n)))] if n else None
        raise EncodingUnsupported(
            f"window {w_needed} exceeds max {max_window} "
            "(extremely skewed op latencies)",
            op_index=widest.orig_index if widest else None,
            process=widest.process if widest else None,
            value=widest.value if widest else None, rule="window")

    n_pad = _pad_to(n, 64)
    ic_pad = _pad_to(ni, 32)
    inv = np.full(n_pad, INF, dtype=np.int32)
    ret = np.full(n_pad, INF, dtype=np.int32)
    opc = np.zeros(n_pad, dtype=np.int32)
    inv[:n] = inv_ok
    ret[:n] = ret_ok
    opc[:n] = codes_ok
    suf = np.full(n_pad + 1, INF, dtype=np.int32)
    for i in range(n - 1, -1, -1):
        suf[i] = min(ret[i], suf[i + 1])
    suf[n:] = INF  # beyond real ops
    iinv = np.full(ic_pad, INF, dtype=np.int32)
    iopc = np.zeros(ic_pad, dtype=np.int32)
    if ni:
        iinv[:ni] = np.asarray([o.inv for o in info_ops], dtype=np.int32)
        iopc[:ni] = codes_info

    # Pad the table to power-of-two-ish shapes so shape buckets recur.
    S, O = table.shape
    Sp, Op_ = _pad_to(S, 16), _pad_to(O, 16)
    tpad = np.full((Sp, Op_), -1, dtype=np.int32)
    tpad[:S, :O] = table

    return Encoded(n_ok=n, n_info=ni, inv=inv, ret=ret, opcode=opc,
                   sufminret=suf, inv_info=iinv, opcode_info=iopc,
                   table=tpad, states=states, window=W,
                   window_raw=w_needed, lin_ops=ok_ops + info_ops)
