"""TPU Wing–Gong–Lowe linearizability search (the north star).

A JAX reimplementation of the WGL search the reference reaches through
knossos (`jepsen/src/jepsen/checker.clj:199-202` selects `wgl/analysis` by
:algorithm). Instead of the JVM's depth-first search with growable bitsets
and a hash-map memo table, the search here explores **thousands of
configurations in lockstep**:

  * A configuration is (base, window, info-mask, model-state):
      - `base`   — index of the first unlinearized :ok op (everything
                   below is linearized);
      - `window` — W boolean lanes: linearized flags for ok ops
                   [base, base+W). W is computed exactly per history
                   (encode.py) so no reachable config is lost;
      - `info`   — mask over crashed (:info) ops, which may linearize at
                   any point after invocation or never;
      - `state`  — index into the host-enumerated model transition table.
  * Real-time candidacy uses one reduction instead of precedence bitsets:
    op j may linearize iff  min{ret(i) : i unlinearized ok op} > inv(j).
  * Each round expands every frontier config by every legal candidate,
    packs + hashes the successors, **sort-uniques** them, probes a device
    open-addressing hash table (the memo cache that makes WGL tractable),
    and compacts survivors back into the fixed-capacity frontier, spilling
    overflow to a device backlog.
  * The whole search runs inside `lax.while_loop` in chunks; the host only
    checks deadlines between chunks.

Verdict soundness: "valid" requires a config with every ok op linearized;
"invalid" requires exhausting the reachable config space with no overflow;
anything cut short (deadline, config budget, backlog overflow) is
"unknown", and `checker.linearizable(algorithm="competition")` falls back
to the host oracle — mirroring how the reference races knossos engines
(`knossos.competition/analysis`). Hash signatures are ~95 bits, so a
false "seen" (the only unsound event) is astronomically unlikely; it is
documented here rather than hidden.
"""

from __future__ import annotations

import functools
import os
import time as _time
from typing import Callable, Optional

import numpy as np

from ..history import History
from ..models.core import Model
from . import wgl_ref
from .encode import Encoded, EncodingUnsupported, encode

INF = np.int32(2**31 - 1)


# ---------------------------------------------------------------------------
# Device kernel
# ---------------------------------------------------------------------------

def _pack_bits(bits):
    """(..., L) bool -> (..., L//32) uint32."""
    import jax.numpy as jnp
    *lead, L = bits.shape
    lanes = bits.reshape(*lead, L // 32, 32).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(lanes * weights, axis=-1, dtype=jnp.uint32)


def _fnv(words, seed):
    """Fold a list of (R,) uint32 arrays into one (R,) uint32 hash."""
    import jax.numpy as jnp
    h = jnp.full_like(words[0], jnp.uint32(seed))
    prime = jnp.uint32(16777619)
    for w in words:
        h = (h ^ w) * prime
        h = h ^ (h >> 15)
    return h


def _build_search(n_pad: int, ic_pad: int, W: int, S: int, O: int,
                  K: int, H: int, B: int, chunk: int, probes: int):
    """Build the chunked search for one shape bucket.

    Returns (init_fn, chunk_fn), both unjitted — `_compiled_search` jits
    chunk_fn for the single-history path, and `jepsen_tpu.parallel.batched`
    vmaps it over a leading key axis for the per-key sharded path. All
    capacities are static; the actual op count / info count / table
    contents are runtime args.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    Wl, Il = W // 32, ic_pad // 32

    def init_fn(mstate0):
        fr_base = jnp.zeros(K, dtype=jnp.int32)
        fr_win = jnp.zeros((K, W), dtype=bool)
        fr_info = jnp.zeros((K, ic_pad), dtype=bool)
        fr_mst = jnp.zeros(K, dtype=jnp.int32).at[0].set(mstate0)
        fr_cnt = jnp.int32(1)
        bk_base = jnp.zeros(B, dtype=jnp.int32)
        bk_win = jnp.zeros((B, W), dtype=bool)
        bk_info = jnp.zeros((B, ic_pad), dtype=bool)
        bk_mst = jnp.zeros(B, dtype=jnp.int32)
        bk_cnt = jnp.int32(0)
        table = jnp.zeros((H, 4), dtype=jnp.uint32)
        flags = jnp.zeros(3, dtype=bool)  # found, overflow, exhausted
        # explored, rounds-in-chunk, max_base, memo_hits, inserted,
        # rounds_total — the last three feed the result's util block
        stats = jnp.zeros(6, dtype=jnp.int32)
        return (fr_base, fr_win, fr_info, fr_mst, fr_cnt,
                bk_base, bk_win, bk_info, bk_mst, bk_cnt,
                table, flags, stats)

    def round_body(consts, carry):
        (inv, ret, opc, suf, iinv, iopc, T, n_ok, n_info, max_cfg) = consts
        (fr_base, fr_win, fr_info, fr_mst, fr_cnt,
         bk_base, bk_win, bk_info, bk_mst, bk_cnt,
         table, flags, stats) = carry

        alive = jnp.arange(K, dtype=jnp.int32) < fr_cnt

        # --- candidate discovery -------------------------------------
        pos = fr_base[:, None] + jnp.arange(W, dtype=jnp.int32)   # (K, W)
        posc = jnp.minimum(pos, n_pad - 1)
        retw = ret[posc]                                          # (K, W)
        retw = jnp.where(fr_win | (pos >= n_ok), INF, retw)
        minret = jnp.min(retw, axis=1)
        tail = suf[jnp.minimum(fr_base + W, n_pad)]
        minret = jnp.minimum(minret, tail)                        # (K,)

        invw = inv[posc]
        cand_ok = (~fr_win) & (pos < n_ok) & (invw < minret[:, None]) \
            & alive[:, None]
        opw = opc[posc]
        nst_ok = T[fr_mst[:, None], opw]                          # (K, W)
        legal_ok = cand_ok & (nst_ok >= 0)

        iidx = jnp.arange(ic_pad, dtype=jnp.int32)
        cand_info = (~fr_info) & (iidx[None, :] < n_info) \
            & (iinv[None, :] < minret[:, None]) & alive[:, None]
        nst_info = T[fr_mst[:, None], iopc[None, :]]              # (K, Ic)
        legal_info = cand_info & (nst_info >= 0)

        # --- successor construction ----------------------------------
        # ok successors: set window bit k, then renormalize (advance base
        # past the linearized prefix and shift the window down).
        eye_w = jnp.eye(W, dtype=bool)
        win2 = fr_win[:, None, :] | eye_w[None]                  # (K, W, W)
        ext = jnp.concatenate(
            [win2, jnp.zeros((K, W, 1), dtype=bool)],
            axis=-1).astype(jnp.int8)
        t = jnp.argmin(ext, axis=-1).astype(jnp.int32)           # (K, W)
        gidx = t[:, :, None] + jnp.arange(W, dtype=jnp.int32)    # (K, W, W)
        shifted = jnp.take_along_axis(
            jnp.concatenate([win2, jnp.zeros((K, W, W), dtype=bool)],
                            axis=-1),
            jnp.minimum(gidx, 2 * W - 1), axis=-1)               # (K, W, W)
        base_ok = fr_base[:, None] + t                           # (K, W)
        info_ok = jnp.broadcast_to(fr_info[:, None, :], (K, W, ic_pad))

        # info successors: set info bit m; window/base unchanged.
        eye_i = jnp.eye(ic_pad, dtype=bool)
        info2 = fr_info[:, None, :] | eye_i[None]                # (K, Ic, Ic)
        win_i = jnp.broadcast_to(fr_win[:, None, :], (K, ic_pad, W))
        base_i = jnp.broadcast_to(fr_base[:, None], (K, ic_pad))

        base_s = jnp.concatenate(
            [base_ok.reshape(-1), base_i.reshape(-1)])           # (R,)
        win_s = jnp.concatenate(
            [shifted.reshape(-1, W), win_i.reshape(-1, W)])      # (R, W)
        info_s = jnp.concatenate(
            [info_ok.reshape(-1, ic_pad), info2.reshape(-1, ic_pad)])
        mst_s = jnp.concatenate(
            [nst_ok.reshape(-1), nst_info.reshape(-1)])
        legal = jnp.concatenate(
            [legal_ok.reshape(-1), legal_info.reshape(-1)])      # (R,)
        R = legal.shape[0]

        success = legal & (base_s >= n_ok)
        found = jnp.any(success)
        explore = legal & ~success

        # --- hash + sort-unique --------------------------------------
        winp = _pack_bits(win_s)                                 # (R, Wl)
        infop = _pack_bits(info_s)                               # (R, Il)
        words = ([base_s.astype(jnp.uint32)]
                 + [winp[:, i] for i in range(Wl)]
                 + [infop[:, i] for i in range(Il)]
                 + [mst_s.astype(jnp.uint32)])
        s0 = _fnv(words, 0x811C9DC5) | jnp.uint32(1)
        s1 = _fnv(words, 0x01000193)
        s2 = _fnv(words, 0xDEADBEEF)
        big = jnp.uint32(0xFFFFFFFF)
        s0 = jnp.where(explore, s0, big)
        s1 = jnp.where(explore, s1, big)
        s2 = jnp.where(explore, s2, big)
        rid = jnp.arange(R, dtype=jnp.int32)
        s0s, s1s, s2s, perm = lax.sort((s0, s1, s2, rid), num_keys=3)
        ex_s = explore[perm]
        samep = (s0s == jnp.roll(s0s, 1)) & (s1s == jnp.roll(s1s, 1)) \
            & (s2s == jnp.roll(s2s, 1))
        samep = samep.at[0].set(False)
        uniq = ex_s & ~samep

        # --- memo-table probe (double hashing) -----------------------
        # NB: racing inserts can interleave words of two signatures into
        # one slot; the chimera matches nobody w.h.p. and only wastes the
        # slot (losers keep probing), so soundness is preserved.
        mysig = jnp.stack([s0s, s1s, s2s], axis=1)               # (R, 3)
        myrow = jnp.arange(R, dtype=jnp.uint32)
        step = (s1s | jnp.uint32(1))

        def probe(r, st):
            table, pending, seen = st
            ru = lax.convert_element_type(r, jnp.uint32)
            idx = ((s0s + ru * step) & jnp.uint32(H - 1)).astype(jnp.int32)
            slot = table[idx]                                    # (R, 4)
            occupied = slot[:, 0] != 0
            equal = occupied & jnp.all(slot[:, :3] == mysig, axis=1)
            seen = seen | (pending & equal)
            claim = pending & ~occupied
            widx = jnp.where(claim, idx, H)
            upd = jnp.concatenate([mysig, myrow[:, None]], axis=1)
            table = table.at[widx].set(upd, mode="drop")
            slot2 = table[idx]
            won = claim & jnp.all(slot2[:, :3] == mysig, axis=1) \
                & (slot2[:, 3] == myrow)
            pending = pending & ~equal & ~won
            return table, pending, seen

        table, pending, seen = lax.fori_loop(
            0, probes, probe, (table, uniq, jnp.zeros(R, dtype=bool)))
        # rows still pending after all probes: table too full to insert —
        # treat as unseen (sound; may re-explore later).
        new = uniq & ~seen

        # --- compact survivors into frontier + backlog ---------------
        posn = jnp.cumsum(new.astype(jnp.int32)) - 1             # (R,)
        total = jnp.sum(new.astype(jnp.int32))
        base_g = base_s[perm]
        mst_g = mst_s[perm]
        win_g = win_s[perm]
        info_g = info_s[perm]

        to_front = new & (posn < K)
        fidx = jnp.where(to_front, posn, K)
        nfr_base = jnp.zeros(K, dtype=jnp.int32).at[fidx].set(
            base_g, mode="drop")
        nfr_mst = jnp.zeros(K, dtype=jnp.int32).at[fidx].set(
            mst_g, mode="drop")
        nfr_win = jnp.zeros((K, W), dtype=bool).at[fidx].set(
            win_g, mode="drop")
        nfr_info = jnp.zeros((K, ic_pad), dtype=bool).at[fidx].set(
            info_g, mode="drop")
        nfr_cnt = jnp.minimum(total, K)

        spill = new & (posn >= K)
        sidx = jnp.where(spill, bk_cnt + posn - K, B)
        overflow = jnp.any(spill & (sidx >= B))
        sidx = jnp.minimum(sidx, B)
        bk_base = bk_base.at[sidx].set(base_g, mode="drop")
        bk_mst = bk_mst.at[sidx].set(mst_g, mode="drop")
        bk_win = bk_win.at[sidx].set(win_g, mode="drop")
        bk_info = bk_info.at[sidx].set(info_g, mode="drop")
        nbk_cnt = jnp.minimum(bk_cnt + jnp.maximum(total - K, 0), B)

        # refill frontier from backlog top if there is room
        room = K - nfr_cnt
        take = jnp.minimum(room, nbk_cnt)
        kidx = jnp.arange(K, dtype=jnp.int32)
        taking = kidx < take
        src = jnp.where(taking, jnp.maximum(nbk_cnt - 1 - kidx, 0), 0)
        dst = jnp.where(taking, nfr_cnt + kidx, K)
        nfr_base = nfr_base.at[dst].set(bk_base[src], mode="drop")
        nfr_mst = nfr_mst.at[dst].set(bk_mst[src], mode="drop")
        nfr_win = nfr_win.at[dst].set(bk_win[src], mode="drop")
        nfr_info = nfr_info.at[dst].set(bk_info[src], mode="drop")
        nfr_cnt = nfr_cnt + take
        nbk_cnt = nbk_cnt - take

        nflags = jnp.stack([flags[0] | found,
                            flags[1] | overflow,
                            nfr_cnt == 0])
        nstats = jnp.stack([
            stats[0] + fr_cnt,
            stats[1] + 1,
            jnp.maximum(stats[2], jnp.max(jnp.where(legal, base_s, 0))),
            # dedup hits: memo-table "seen" plus same-round duplicates
            # removed by the sort (all-equal-length paths arrive in the
            # same round, so sort-dedup is the hot dedup path here)
            stats[3] + jnp.sum(seen.astype(jnp.int32))
            + jnp.sum((ex_s & samep).astype(jnp.int32)),
            stats[4] + total,
            stats[5] + 1])
        return (nfr_base, nfr_win, nfr_info, nfr_mst, nfr_cnt,
                bk_base, bk_win, bk_info, bk_mst, nbk_cnt,
                table, nflags, nstats)

    def chunk_fn(consts, carry):
        max_cfg = consts[-1]

        def cond(c):
            flags, stats = c[11], c[12]
            return (~flags[0]) & (c[4] > 0) \
                & (stats[1] < chunk) & (stats[0] < max_cfg)

        def body(c):
            return round_body(consts, c)

        # reset the per-chunk round counter
        stats = carry[12]
        carry = carry[:12] + (stats.at[1].set(0),)
        return lax.while_loop(cond, body, carry)

    return init_fn, chunk_fn


@functools.lru_cache(maxsize=32)
def _compiled_search(n_pad: int, ic_pad: int, W: int, S: int, O: int,
                     K: int, H: int, B: int, chunk: int, probes: int):
    """Jitted single-history search for one shape bucket."""
    import jax

    init_fn, chunk_fn = _build_search(n_pad, ic_pad, W, S, O,
                                      K, H, B, chunk, probes)
    chunk_jit = jax.jit(chunk_fn, donate_argnums=(1,))
    return init_fn, chunk_jit


# ---------------------------------------------------------------------------
# Host driver
# ---------------------------------------------------------------------------

def _pad_to_mult(n: int, m: int) -> int:
    return max(m, ((n + m - 1) // m) * m)


def _pick_capacities(W: int, ic_pad: int, n: int,
                     accel: Optional[bool] = None):
    """Frontier capacity K, memo-table size H, backlog B scaled to the
    problem AND the platform. The (K, W, 2W) successor intermediate is
    the memory driver for the general kernel; the memo table must stay
    well under ~60% load or probe-based dedup degrades into
    re-exploration (each slot is 16 bytes, so even 2^23 slots is only
    128 MB)."""
    from ..util import safe_backend

    # An accelerator's HBM affords a much wider beam than host RAM —
    # and beam width is the general kernel's throughput knob (configs
    # decided per round scale ~linearly with K at fixed round cost on
    # the TPU, where the (K, W, 2W) gathers are bandwidth-cheap).
    if accel is None:
        accel = safe_backend() not in (None, "cpu")
    budget = (256 if accel else 32) * 1024 * 1024  # bool elements
    K = max(16, min(4096, budget // max(1, 2 * W * W)))
    K = 1 << (K.bit_length() - 1)
    if W > 32 or n > 5000:
        # Wide windows: reachable-config count scales with the
        # window's branching power (2^concurrency), not op count — a
        # 200-op adversarial history reaches millions of configs. An
        # undersized table degrades into ~2x re-exploration (measured
        # on the wave benchmark: H=2^19 at 850k configs).
        H = 1 << 23
    elif n > 2000:
        H = 1 << 22
    else:
        H = 1 << 19
    # Backlog absorbs beam spill; overflow degrades False -> unknown.
    # Wide windows carry wide BFS wavefronts (C(w, w/2)-scale), so the
    # backlog scales with a byte budget over the row width (a general-
    # kernel row is (W + ic_pad) unpacked bools); the fast path's
    # packed rows are cheap and its caller widens B separately.
    if W > 32:
        B = min(1 << 19, max(1 << 16, (64 << 20) // max(W, 1)))
        B = 1 << (B.bit_length() - 1)
    else:
        B = 1 << 16
    return K, H, B


# Beam escalation for the fast path: a valid history usually resolves
# within ~depth rounds at the narrow K; past this many explored configs
# the search is likely exhaustive, where breadth amortizes overhead.
# (The legacy one-shot jump, used only when the adaptive ladder is
# disabled — the ladder generalizes it, ops/adapt.py.)
_ESCALATE_AT = 200_000
_K_BIG = 512


def derive_plan(*, window_raw: int, W: int, ic_pad: int, n: int,
                n_info: int, accel: bool,
                frontier: Optional[int] = None,
                adaptive: Optional[bool] = None,
                shape_bucket: Optional[dict] = None) -> dict:
    """The static kernel-plan derivation: variant, capacities, ladder,
    effective widths. Pure scalar math — no arrays, no jax.

    This is the SINGLE source of truth for what `check()` below will
    run AND what `analysis/preflight.plan_wgl` admits against; keeping
    it one function is what stops the admission analyzer silently
    drifting from the kernel it models. Returns {kern, K, H, B, W_eff,
    ic_eff, L, chunk, depth, probes, ladder, use_adapt, buckets} —
    `buckets` is every frontier capacity the search may visit (the
    adaptive ladder, the legacy [K, 512] escalation, or a pinned
    frontier)."""
    from . import adapt as _adapt

    n_caps = max(n, int(shape_bucket.get("n_cap", 0))) \
        if shape_bucket else n
    K, H, B = _pick_capacities(W, ic_pad, max(n_caps, 1), accel=accel)
    use_adapt = (_adapt.enabled(True if adaptive is None else adaptive)
                 and not frontier and adaptive is not False)
    ladder: Optional[tuple] = None
    L = 0
    chunk = 4096 if accel else 1024
    depth = 1
    if window_raw <= 32:
        kern = "wgl32"
        K = 16
        if use_adapt:
            ladder = _adapt.LADDER32
            K = ladder[0]
        W_eff = max(8, _pad_to_mult(window_raw, 8))
        ic_eff = min(max(8, _pad_to_mult(n_info, 8)), ic_pad)
        if shape_bucket:
            W_eff = max(W_eff, int(shape_bucket.get("w_eff", 0)))
            ic_eff = min(ic_pad, max(
                ic_eff, int(shape_bucket.get("ic_eff", 0))))
        B = 1 << 18
        depth = 4 if accel else 1
        chunk = max(1, chunk // depth)
    else:
        kern = "wgln"
        W_eff = _pad_to_mult(window_raw, 32)
        ic_eff = min(max(8, _pad_to_mult(n_info, 8)), ic_pad)
        if shape_bucket:
            W_eff = max(W_eff, int(shape_bucket.get("w_eff", 0)))
            ic_eff = min(ic_pad, max(
                ic_eff, int(shape_bucket.get("ic_eff", 0))))
        L = W_eff // 32
        budget_bytes = (1024 if accel else 128) * 1024 * 1024
        K = max(64, min(4096 if accel else 1024,
                        budget_bytes // (W_eff * L * 4 * 3)))
        cap = int(os.environ.get("JEPSEN_TPU_MAX_FRONTIER", "0"))
        if cap:
            K = min(K, cap)
        K = 1 << (K.bit_length() - 1)
        B = min(1 << 20, max(1 << 18, (32 << 20) // (L * 4)))
        B = 1 << (B.bit_length() - 1)
        chunk = 512 if accel else 128
        if use_adapt:
            ladder = _adapt.ladder_for(K, k_min=max(32, K // 16),
                                       step=8)
            K = ladder[0]
    if frontier:
        K = frontier
    if ladder:
        buckets = list(ladder)
    elif kern == "wgl32" and not frontier and K < _K_BIG:
        buckets = [K, _K_BIG]  # legacy one-shot escalation
    else:
        buckets = [K]
    return {"kern": kern, "K": K, "H": H, "B": B, "W_eff": W_eff,
            "ic_eff": ic_eff, "L": L, "chunk": chunk, "depth": depth,
            "probes": 4, "ladder": ladder, "use_adapt": use_adapt,
            "buckets": buckets}


def _widen_frontier(carry, k_new: int):
    """Pad the packed frontier (K, C) of a wgl32 carry to k_new rows
    (zeros beyond fr_cnt are inert); backlog/memo/flags ride along."""
    import jax.numpy as jnp

    fr = carry[0]
    return (jnp.pad(fr, [(0, k_new - fr.shape[0]), (0, 0)]),
            *carry[1:])


def _packable(enc: Encoded) -> bool:
    """May this encoding run the int16/int8 packed lookup tables
    (wgl32 `pack`)? Times are event indices — every real (non-INF)
    inv/ret/sufminret entry must sit strictly under PACK_MAX, and
    state indices must fit int16. Bit-exact when true."""
    from .wgl32 import PACK_MAX
    m = 0
    for a in (enc.inv, enc.ret, enc.sufminret, enc.inv_info):
        finite = a[a < INF]
        if finite.size:
            m = max(m, int(finite.max()))
    return m < PACK_MAX and enc.table.shape[0] <= 32000


def _apply_bucket(enc: Encoded, bucket: dict) -> Encoded:
    """Pad an encoding into a shared shape bucket (host numpy only):
    inv/ret/sufminret/inv_info pad with INF, opcodes with 0, the
    transition table with -1. Padding ok-slots sit past n_ok and
    padding info-slots past n_info, so the kernel never treats them
    as candidates — verdicts are unchanged. This is what lets a
    per-key fan-out share ONE compiled kernel across keys whose raw
    shapes straddle several (n_pad, ic, S, O) buckets (the
    independent_100x2k straggler fix — see parallel/batched.py)."""
    import dataclasses

    n_pad = max(int(bucket.get("n_pad", len(enc.inv))), len(enc.inv))
    ic_pad = max(int(bucket.get("ic_pad", len(enc.inv_info))),
                 len(enc.inv_info))
    S = max(int(bucket.get("S", enc.table.shape[0])),
            enc.table.shape[0])
    O = max(int(bucket.get("O", enc.table.shape[1])),
            enc.table.shape[1])

    def pad1(a, size, fill):
        if len(a) == size:
            return a
        out = np.full(size, fill, dtype=a.dtype)
        out[:len(a)] = a
        return out

    table = enc.table
    if table.shape != (S, O):
        t = np.full((S, O), -1, dtype=np.int32)
        t[:table.shape[0], :table.shape[1]] = table
        table = t
    return dataclasses.replace(
        enc,
        inv=pad1(enc.inv, n_pad, INF),
        ret=pad1(enc.ret, n_pad, INF),
        opcode=pad1(enc.opcode, n_pad, 0),
        sufminret=pad1(enc.sufminret, n_pad + 1, INF),
        inv_info=pad1(enc.inv_info, ic_pad, INF),
        opcode_info=pad1(enc.opcode_info, ic_pad, 0),
        table=table)


def check(model: Model, history: History, time_limit: Optional[float] = None,
          max_configs: int = 200_000_000, frontier: Optional[int] = None,
          enc: Optional[Encoded] = None,
          stop: Optional[Callable[[], bool]] = None,
          platform: Optional[str] = None,
          metrics=None, tracer=None,
          profile_dir: Optional[str] = None,
          shape_bucket: Optional[dict] = None,
          adaptive: Optional[bool] = None) -> dict:
    """Decide linearizability on the accelerator.

    Returns {"valid?": True/False/"unknown", ...}. "unknown" (deadline,
    config budget, capacity overflow, or unsupported encoding) signals the
    caller to fall back to the host oracle. `enc` skips re-encoding when
    the caller already holds this history's Encoded (the streamed
    per-key fan-out does). `stop` is polled between device chunks;
    True cancels with cause "cancelled" (competition racing).

    `platform` overrides the engine's platform choice: "cpu" compiles
    the HOST layout and pins the kernel onto the CPU backend even when
    an accelerator is the jax default — platform-aware competition
    (`checker._race_competition`) races device@accel against
    device@cpu because small/near-serial shapes are latency-bound and
    the host core wins them (round-4 VERDICT #3). The result carries
    `platform` so route_reason/engine rows can name it.

    Telemetry (doc/OBSERVABILITY.md): `metrics` is a
    `jepsen_tpu.metrics.Registry` (default: the ambient registry —
    NULL unless enabled, so the instrumented path costs nothing);
    when enabled, every device chunk's packed poll summary lands in
    the `wgl_chunks` timeseries, the kernel's per-round occupancy
    ring drains into the `wgl_rounds` timeseries (occupancy.py —
    the rows ride the same packed summary, no extra transfer), and
    the result carries a `telemetry.chunks` copy plus an
    `occupancy` block (fill stats + roofline attribution).
    `tracer` is a `trace.Tracer`; phase
    spans (encode / compile / device-round / host-poll) nest under
    the caller's current span. `profile_dir` (or env
    JEPSEN_TPU_PROFILE_DIR) opt-in wraps the search in a
    `jax.profiler` capture whose Perfetto-ingestible trace lands in
    that directory; capture failures never block the verdict.

    `shape_bucket` pads the encoding into a caller-shared shape
    bucket ({n_pad, ic_pad, S, O, w_eff, ic_eff}) so a per-key
    fan-out compiles ONE kernel for the whole key set
    (`_apply_bucket`; parallel/batched.py builds it). `adaptive`
    overrides the occupancy-driven bucket-ladder scheduling
    (ops/adapt.py; default on unless JEPSEN_TPU_ADAPTIVE=0 or an
    explicit `frontier` pins the beam): the beam starts at the
    ladder's bottom bucket and the host grows/shrinks it between
    chunks from the polled occupancy counters — no retraces inside
    the device loop, one pre-compilable executable per bucket, and
    the `util.adapt` block records the path taken.
    """
    from .. import metrics as _metrics_mod
    from .. import trace as _trace_mod
    from ..util import backend_ready

    mx = metrics if metrics is not None else _metrics_mod.get_default()
    tracer = tracer if tracer is not None else _trace_mod.NULL_TRACER

    # The first device call triggers backend init, which hangs forever
    # on a wedged accelerator runtime (this environment's default
    # platform pin makes that reachable from any unpinned process) —
    # bound the wait and let callers fall back to the host oracle.
    # The init wait spends the CALLER'S budget (deadline is anchored
    # here, not after the wait): a 60 s time_limit must mean 60 s of
    # wall, matching the batched entry points' accounting.
    t_enter = _time.monotonic()
    if not backend_ready(min(60.0, time_limit) if time_limit
                         else None):
        return {"valid?": "unknown", "cause": "backend-init-timeout",
                "op_count": len(history)}

    import jax.numpy as jnp

    # Device stats are int32; cap the budget so the explored counter can
    # reach it without wrapping (it grows by at most K per round).
    max_configs = min(max_configs, 2**30)
    try:
        if enc is None:
            with tracer.span("encode", attrs={"ops": len(history)}):
                enc = encode(model, history)
    except EncodingUnsupported as e:
        # e carries the offending op's coordinates (encode.py) so
        # reports can point at the exact op, not just a message
        return {"valid?": "unknown", "cause": f"encoding: {e}",
                "encoding": e.to_dict(), "op_count": len(history)}
    n = enc.n_ok
    if n == 0:
        # with no must-linearize ops, skipping every crashed op is a
        # valid linearization
        return {"valid?": True, "op_count": enc.n_info}

    from ..util import safe_backend
    accel = (platform or safe_backend()) not in (None, "cpu")

    if shape_bucket:
        # shared-bucket fan-out: pad the encoding so every key in the
        # caller's batch compiles (and warms) the SAME kernel
        enc = _apply_bucket(enc, shape_bucket)

    W = enc.window
    ic_pad = len(enc.inv_info)
    # The whole static plan — kernel variant, K/H/B capacities,
    # adaptive ladder, effective widths, chunk/depth — comes from ONE
    # derivation shared with the admission analyzer
    # (analysis/preflight.plan_wgl), so what preflight admits against
    # is exactly what runs here. The measured rationale for every
    # branch lives on derive_plan.
    plan = derive_plan(window_raw=enc.window_raw, W=W, ic_pad=ic_pad,
                       n=n, n_info=enc.n_info, accel=accel,
                       frontier=frontier, adaptive=adaptive,
                       shape_bucket=shape_bucket)
    K, H, B = plan["K"], plan["H"], plan["B"]
    ladder = plan["ladder"]
    W_eff, ic_eff = plan["W_eff"], plan["ic_eff"]
    chunk, depth = plan["chunk"], plan["depth"]
    # Half-width packed lookup tables (wgl32 `pack`): bit-exact when
    # every event time fits int16 — true for every history under ~16k
    # events, including the 10k headline. Halves the per-round meta/
    # grand-table gather bytes (the roofline block proves it via the
    # compiler's own cost analysis). A shared bucket carries ONE
    # bucket-wide bit so sibling keys never split into two variants.
    pack = (bool(shape_bucket["pack"])
            if shape_bucket and "pack" in shape_bucket
            else _packable(enc))
    iinv, iopc = enc.inv_info[:ic_eff], enc.opcode_info[:ic_eff]
    W = W_eff  # the width the kernel actually runs at
    probes_used, row_cols = plan["probes"], W_eff + ic_eff
    if plan["kern"] == "wgl32":
        # Bitmask fast path: window in one uint32 lane, sort-free
        # dedup. Successor-row count R = K*(W_eff + ic_eff) drives
        # probe traffic, so only what the history needs materializes.
        from .wgl32 import compiled_search32

        def rebuild(k):
            return compiled_search32(
                n_pad=len(enc.inv), ic_pad=ic_eff,
                S=enc.table.shape[0], O=enc.table.shape[1],
                K=k, H=H, B=B, chunk=chunk, probes=4, W=W_eff,
                accel=accel, depth=depth, pack=pack)
    else:
        # Packed multi-lane kernel (wgln.py): window as L uint32
        # lanes. Successors are bit math + funnel shifts instead of
        # (K, W, 2W) bool gathers, dedup is probe-only instead of a
        # 3-key sort — measured ~11x over the bool kernel at W=71 on
        # cpu.
        from .wgln import compiled_searchN
        L = plan["L"]

        def rebuild(k):
            return compiled_searchN(
                n_pad=len(enc.inv), ic_pad=ic_eff,
                S=enc.table.shape[0], O=enc.table.shape[1],
                K=k, H=H, B=B, chunk=chunk, probes=4, W=W_eff, L=L,
                accel=accel, pack=pack)

    init_fn, chunk_jit = rebuild(K)

    import contextlib

    import jax
    dev_ctx = contextlib.nullcontext()
    if platform == "cpu" and safe_backend() not in (None, "cpu"):
        # pin the host layout onto the CPU backend that coexists with
        # the accelerator (platform-aware competition lane)
        try:
            dev_ctx = jax.default_device(
                jax.local_devices(backend="cpu")[0])
        except Exception as e:  # noqa: BLE001 — no cpu backend: stay
            # put, but record the decline (the lane then runs on the
            # default backend, which skews competition timings)
            from .. import fleet as _fleet
            _fleet.record_fault(_fleet.fault_event(
                e, stage="wgl/cpu-pin"))
    # Opt-in hardware profile: a jax.profiler capture around the whole
    # search, dropping a Perfetto/xprof-ingestible trace into the
    # run's artifact dir. start/stop (not the context manager) so a
    # capture failure is contained without re-running the search.
    profile_dir = profile_dir or os.environ.get("JEPSEN_TPU_PROFILE_DIR")
    profiled = False
    if profile_dir:
        try:
            jax.profiler.start_trace(profile_dir)
            profiled = True
        except Exception as e:  # noqa: BLE001 — profiling never
            # blocks the verdict, but a silently-missing capture
            # wastes the whole opted-in run: record the decline
            from .. import fleet as _fleet
            _fleet.record_fault(_fleet.fault_event(
                e, stage="wgl/profiler-start"))
    plat_label = platform or safe_backend() or "cpu"
    try:
        with dev_ctx:
            res = _run_search(enc, init_fn, chunk_jit, iinv, iopc, n,
                              max_configs, frontier, K, H, B, W, W_eff,
                              ic_eff, chunk, probes_used, row_cols,
                              accel, t_enter, time_limit, stop,
                              depth=depth, mx=mx, tracer=tracer,
                              plat=plat_label, ladder=ladder,
                              rebuild=rebuild, pack=pack)
    finally:
        if profiled:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — capture lost;
                # record it so the missing trace file is explicable
                from .. import fleet as _fleet
                _fleet.record_fault(_fleet.fault_event(
                    e, stage="wgl/profiler-stop"))
                profiled = False
    if profiled:
        res["profile_dir"] = profile_dir
    res.setdefault("platform", plat_label)
    return res


def _run_search(enc, init_fn, chunk_jit, iinv, iopc, n, max_configs,
                frontier, K, H, B, W, W_eff, ic_eff, chunk, probes_used,
                row_cols, accel, t_enter, time_limit, stop, depth=1,
                mx=None, tracer=None, plat="cpu", ladder=None,
                rebuild=None, pack=False):
    # Stall surveillance (watchdog.py): the loop below heartbeats once
    # per poll, so a device round that hangs INSIDE chunk_jit — which
    # the between-chunk deadline checks can never observe — stops
    # beating and the watchdog declares the source stalled. This thin
    # wrapper owns the source lifetime; the loop body lives in
    # _search_loop.
    from .. import watchdog as _watchdog_mod
    wd = _watchdog_mod.get_default()
    # grace until the first beat: the first chunk folds in XLA
    # compile (measured up to ~14 s at K=4096 on cpu, more on a cold
    # accelerator cache), which must not read as a stall
    hb = wd.register(f"wgl/{plat}", device=plat, grace_s=300.0)
    try:
        return _search_loop(enc, init_fn, chunk_jit, iinv, iopc, n,
                            max_configs, frontier, K, H, B, W, W_eff,
                            ic_eff, chunk, probes_used, row_cols,
                            accel, t_enter, time_limit, stop,
                            depth=depth, mx=mx, tracer=tracer,
                            plat=plat, wd=wd, hb=hb, ladder=ladder,
                            rebuild=rebuild, pack=pack)
    finally:
        wd.unregister(hb)


def _search_loop(enc, init_fn, chunk_jit, iinv, iopc, n, max_configs,
                 frontier, K, H, B, W, W_eff, ic_eff, chunk,
                 probes_used, row_cols, accel, t_enter, time_limit,
                 stop, depth=1, mx=None, tracer=None, plat="cpu",
                 wd=None, hb=None, ladder=None, rebuild=None,
                 pack=False):
    import jax.numpy as jnp

    from .. import devices as _devices_mod
    from .. import fleet as _fleet_mod
    from .. import metrics as _metrics_mod
    from .. import occupancy as _occ
    from .. import trace as _trace_mod
    from .. import watchdog as _watchdog_mod
    mx = mx if mx is not None else _metrics_mod.get_default()
    tracer = tracer if tracer is not None else _trace_mod.NULL_TRACER
    status = _fleet_mod.get_default()
    if wd is None:
        wd = _watchdog_mod.get_default()
        hb = None

    from ..analysis import guards as _guards

    consts = (jnp.asarray(enc.inv), jnp.asarray(enc.ret),
              jnp.asarray(enc.opcode), jnp.asarray(enc.sufminret),
              jnp.asarray(iinv), jnp.asarray(iopc),
              jnp.asarray(enc.table), jnp.int32(n), jnp.int32(enc.n_info),
              jnp.int32(min(max_configs, 2**31 - 1)))
    # the search's one const upload (analysis/guards budget point)
    _guards.note_transfer(
        "h2d",
        enc.inv.nbytes + enc.ret.nbytes + enc.opcode.nbytes
        + enc.sufminret.nbytes + iinv.nbytes + iopc.nbytes
        + enc.table.nbytes, what="wgl-consts")
    carry = init_fn(0)
    deadline = t_enter + time_limit if time_limit else None
    t0 = _time.monotonic()
    first_call_s = None
    n_chunks = 0
    bk_peak = 0
    # per-chunk telemetry: the kernel's cumulative device stats turn
    # into per-poll deltas here; None when disabled so the hot loop
    # pays nothing (metrics.py's zero-cost contract)
    tl_points: Optional[list] = [] if mx.enabled else None
    kern = "wgl32" if enc.window_raw <= 32 else "wgln"
    # per-round occupancy drain (occupancy.drain_chunk): the ring rows
    # ride the packed poll summary either way; draining them is pure
    # host numpy, paid only when metrics or the live status panel
    # consume them
    occ_rounds: list = []
    occ_dropped = 0
    occ_seen = 0
    rounds_before = 0
    # occupancy-adaptive bucket ladder (ops/adapt.py): decisions run
    # host-side between chunks off the counters already polled — the
    # device loop sees only a differently-shaped (pre-compiled)
    # executable and a padded/sliced frontier
    from . import adapt as _adapt_mod
    policy = None
    if ladder and rebuild is not None:
        policy = _adapt_mod.Policy(ladder=ladder, n_ok=n,
                                   backlog_cap=B, start_k=K)
    # beam-area accounting: frontier_fill must normalize each round
    # by the K it actually ran at, not the final K
    beam_area = 0
    prev_rounds_total = 0
    prev_explored_total = 0
    # the compute/transfer split below costs one extra device sync per
    # poll — only pay it when someone is recording (the disabled run
    # must keep the original single-transfer poll, overhead-free)
    instrumented = tl_points is not None or tracer.sampled
    # device observatory (devices.py): HBM accounting sampled at the
    # SAME poll boundaries — memory_stats() is a host-side allocator
    # query, so no device round-trip is added. The mark()/measured()
    # window puts hbm_peak_measured on the result beside preflight's
    # analytic prediction (the measured-vs-predicted closure).
    dm = _devices_mod.get_default()
    dmark = dm.mark(where=f"wgl/{plat}") if dm.enabled else None
    total_explored = 0
    max_lin = 0
    while True:
        if hb is not None and wd.cancelled(hb):
            # soft-cancel between chunks (an escalated stall elsewhere,
            # or an operator cancel): return partial progress instead
            # of burning budget on a run already declared stalled
            return {"valid?": "unknown", "cause": "stalled",
                    "op_count": n + enc.n_info,
                    "partial": {"configs_explored": total_explored,
                                "ops_linearized": max_lin,
                                "chunks": n_chunks},
                    "stall": _watchdog_mod.stall_result(hb)["stall"]}
        t_call = _time.monotonic()
        # the first call folds in compile (the cold/warm split every
        # result reports); later calls are pure device rounds
        with tracer.span("compile" if n_chunks == 0 else "device-round",
                         attrs={"chunk": n_chunks}):
            carry, summary = chunk_jit(consts, carry)
            # async dispatch returns immediately — when instrumented,
            # block here so the device-round span (and poll_s) covers
            # device compute and the host-poll span/transfer_s below
            # isolates the actual device->host transfer of the packed
            # (11,) summary [fr_cnt, found, overflow, exhausted,
            # stats x6, bk_cnt] (~75 ms round-trip, tunneled v5e)
            if instrumented:
                # the ONE designed poll sync: splits device compute
                # from the packed-summary transfer for the phase spans
                summary.block_until_ready()  # jaxlint: ok(J007)
            with tracer.span("host-poll"):
                t_xfer = _time.monotonic()
                # the ONE designed per-chunk drain: a single packed
                # (11,)+ring summary per poll, budgeted by CompileGuard
                s = np.asarray(summary)  # jaxlint: ok(J007)
                xfer_s = _time.monotonic() - t_xfer
                # one packed (11,) poll per chunk — the ONLY
                # device->host transfer in the loop by design; the
                # guard budget catches anyone adding another
                _guards.note_transfer("d2h", s.nbytes,
                                      what="wgl-poll")
        poll_s = _time.monotonic() - t_call
        fr_cnt, flags, stats = int(s[0]), s[1:4], s[4:10]
        bk_cnt = int(s[10])
        n_chunks += 1
        bk_peak = max(bk_peak, bk_cnt)
        max_lin = max(max_lin, int(stats[2]))
        if hb is not None:
            # heartbeat + partial-progress counters: what a stalled
            # verdict will report if the NEXT chunk never returns
            wd.beat(hb, configs_explored=int(stats[0]),
                    ops_linearized=max_lin, chunks=n_chunks,
                    frontier=fr_cnt, backlog=bk_cnt)
        if first_call_s is None:
            # compile + first chunk: the cold/warm split every result
            # reports (a persistent compilation cache turns this into
            # a deserialization — see util.enable_compilation_cache)
            first_call_s = _time.monotonic() - t0
        found, overflow = bool(flags[0]), bool(flags[1])
        total_explored = int(stats[0])
        if dmark is not None:
            # throttled HBM sample on the existing poll cadence (no
            # extra device round-trip — a host allocator query)
            dm.sample(where=f"wgl/{plat}", mx=mx)
        occ_new: list = []
        if tl_points is not None or status.enabled:
            # drain this chunk's per-round occupancy rows off the
            # packed summary already in host memory — no transfer,
            # no device work, just numpy over the ring tail
            occ_new, dropped = _occ.drain_chunk(s, rounds_before, K)
            occ_dropped += dropped
            occ_seen += len(occ_new)
            wall_now = _time.monotonic() - t0
            wall_prev = max(wall_now - poll_s, 0.0)
            n_new = len(occ_new)
            for i, r in enumerate(occ_new):
                # interpolated wall stamp: rounds are not host-timed
                # individually (that would mean per-round syncs), so
                # spread them across the chunk's wall for the
                # progress-overlay x axis
                r["wall_s"] = round(
                    wall_prev + (i + 1) / n_new * (wall_now
                                                   - wall_prev), 6)
        rounds_before = int(stats[5])
        if status.enabled:
            # live run status (fleet.RunStatus): one small dict per
            # poll — ~75 ms+ apart on accel, a few Hz on cpu — so the
            # /status.json panel and the JEPSEN_TPU_PROGRESS ticker
            # track frontier/backlog/rate mid-search. The search id
            # keys the rate bookkeeping: concurrent searches (streamed
            # workers, raced lanes) run one per thread, so the thread
            # id distinguishes their cumulative counters
            import threading as _threading
            status.search_poll({
                "kernel": kern, "platform": plat,
                "chunk": n_chunks - 1,
                "wall_s": round(_time.monotonic() - t0, 4),
                "poll_s": round(poll_s, 6),
                "frontier": fr_cnt, "backlog": bk_cnt,
                "explored": total_explored,
                "rounds": int(stats[5])},
                search_id=(_threading.get_ident(), plat))
            # the /occupancy panel's live block: last/mean fill plus
            # a bounded window of recent per-round points
            fills = [r["fill"] for r in occ_new]
            status.occupancy_poll({
                "mode": "single", "kernel": kern, "platform": plat,
                "K": K,
                "adapt": ({"ladder": list(policy.ladder),
                           "switches": len(policy.switches)}
                          if policy is not None else None),
                "fill_last": (fills[-1] if fills
                              else round(fr_cnt / max(K, 1), 4)),
                "fill_mean": (round(sum(fills) / len(fills), 4)
                              if fills else None),
                "rounds_seen": occ_seen,
                "rounds_dropped": occ_dropped,
                "recent_rounds": [
                    {"round": r["round"], "fill": r["fill"]}
                    for r in occ_new[-32:]]},
                search_id=(_threading.get_ident(), plat))
        if tl_points is not None:
            prev = tl_points[-1] if tl_points else {}
            memo_hits_c, inserted_c = int(stats[3]), int(stats[4])
            point = {
                "chunk": n_chunks - 1,
                "cold": n_chunks == 1,
                "wall_s": round(_time.monotonic() - t0, 6),
                "poll_s": round(poll_s, 6),
                "transfer_s": round(xfer_s, 6),
                "frontier": fr_cnt,
                "fill": round(fr_cnt / max(K, 1), 4),
                "backlog": bk_cnt,
                "K": K,
                "rounds": int(stats[5]),
                "explored": total_explored,
                "memo_hits": memo_hits_c,
                "memo_inserts": inserted_c,
                "memo_hit_rate": _occ.memo_hit_rate(memo_hits_c,
                                                    inserted_c),
                "rounds_delta": int(stats[5]) - prev.get("rounds", 0),
                "explored_delta": (total_explored
                                   - prev.get("explored", 0)),
                "kernel": kern,
                # platform distinguishes raced lanes: competition runs
                # device@accel and device@cpu over the SAME history
                # with the same kernel, concurrently
                "platform": plat,
            }
            tl_points.append(point)
            mx.series("wgl_chunks",
                      "per-chunk packed poll summaries of the WGL "
                      "device search").append(point)
            rounds_series = mx.series(
                "wgl_rounds",
                "per-round device occupancy counters drained from "
                "the kernel ring buffer")
            # epoch anchor for the interpolated wall stamps: rows are
            # appended in one burst per poll, and the default
            # append-time `t` would collapse a whole chunk's rounds
            # onto one Perfetto counter-track timestamp
            epoch_now = _time.time()
            wall_ref = _time.monotonic() - t0
            for r in occ_new:
                r.update(kernel=kern, platform=plat, K=K,
                         chunk=n_chunks - 1,
                         t=round(epoch_now - (wall_ref
                                              - r["wall_s"]), 6))
                rounds_series.append(r)
            if len(occ_rounds) < _occ.MAX_RESULT_ROUNDS:
                occ_rounds.extend(
                    occ_new[:_occ.MAX_RESULT_ROUNDS
                            - len(occ_rounds)])
            lbl = {"kernel": kern, "platform": plat}
            mx.counter("wgl_chunks_total",
                       "device chunk calls").inc(**lbl)
            mx.counter("wgl_rounds_total",
                       "search rounds executed on device").inc(
                point["rounds_delta"], **lbl)
            mx.counter("wgl_configs_explored_total",
                       "configurations expanded").inc(
                point["explored_delta"], **lbl)
            mx.counter("wgl_memo_hits_total",
                       "memo-table dedup hits").inc(
                memo_hits_c - prev.get("memo_hits", 0), **lbl)
            mx.counter("wgl_memo_inserts_total",
                       "memo-table inserts").inc(
                inserted_c - prev.get("memo_inserts", 0), **lbl)
            mx.gauge("wgl_frontier_size",
                     "beam occupancy at last poll").set(fr_cnt, **lbl)
            mx.gauge("wgl_backlog_size",
                     "backlog depth at last poll").set(bk_cnt, **lbl)
            mx.histogram("wgl_poll_seconds",
                         "host<->device chunk latency (device compute "
                         "+ packed-summary transfer)").observe(
                poll_s, **lbl)
        rounds_now = int(stats[5])
        rounds_delta = rounds_now - prev_rounds_total
        explored_delta = total_explored - prev_explored_total
        beam_area += rounds_delta * K
        if policy is not None and not found and fr_cnt > 0:
            d = policy.observe(explored=total_explored,
                               rounds_delta=rounds_delta,
                               explored_delta=explored_delta,
                               frontier=fr_cnt, backlog=bk_cnt)
            if d.switch:
                k_old = K
                _, chunk_jit = rebuild(d.to_k)
                carry = _adapt_mod.migrate_frontier(carry, d.to_k)
                K = d.to_k
                if tl_points is not None:
                    mx.series(
                        "wgl_adapt",
                        "bucket-ladder switch decisions of the "
                        "occupancy-adaptive WGL scheduler").append({
                            "chunk": n_chunks - 1,
                            "from_K": k_old, "to_K": K,
                            "reason": d.reason,
                            "fill": round(explored_delta
                                          / max(rounds_delta * k_old,
                                                1), 4),
                            "backlog": bk_cnt,
                            "explored": total_explored,
                            "kernel": kern, "platform": plat})
        prev_rounds_total = rounds_now
        prev_explored_total = total_explored
        if (policy is None and not found and fr_cnt > 0
                and not frontier
                and enc.window_raw <= 32 and K < _K_BIG
                and total_explored >= _ESCALATE_AT):
            # Exhaustion regime (legacy non-adaptive path): widen the
            # beam so per-round overhead amortizes over more configs.
            # The memo table rides along in the carry, so nothing is
            # re-explored.
            _, chunk_jit = rebuild(_K_BIG)
            carry = _widen_frontier(carry, _K_BIG)
            K = _K_BIG
        # result assembly only when a stop condition holds — the
        # common mid-search poll skips the util/occupancy block
        # construction entirely (it is per-poll host work otherwise)
        cancelled = stop is not None and stop()
        if not (found or fr_cnt == 0
                or total_explored >= max_configs or cancelled
                or (deadline is not None
                    and _time.monotonic() > deadline)):
            continue
        wall = _time.monotonic() - t0
        rounds_total = int(stats[5])
        memo_hits, inserted = int(stats[3]), int(stats[4])
        # Utilization accounting (what the device actually did): the
        # kernel is gather/scatter-bound, so the roofline currency is
        # successor rows processed and memo-table bytes touched per
        # round, not FLOPs. R = K * row_cols rows/round; each row costs
        # ~probes x 16 B of table traffic (the dominant stream) plus
        # its own pack/hash/sort. frontier_fill is the average fraction
        # of the beam occupied (approximate across escalation).
        util = {
            "configs_per_s": int(total_explored / max(wall, 1e-9)),
            "rounds": rounds_total,
            # beam-area weighted: each round normalized by the K it
            # ran at (the ladder moves K mid-search)
            "frontier_fill": round(
                total_explored / max(beam_area
                                     or rounds_total * K, 1), 4),
            # the ONE hit-rate definition (occupancy.memo_hit_rate) —
            # shared with the per-chunk points so they can't drift
            "memo_hit_rate": _occ.memo_hit_rate(memo_hits, inserted),
            "succ_rows_per_round": K * row_cols,
            "est_table_mb_per_round": round(
                K * row_cols * 16 * probes_used / 1e6, 3),
            "first_call_s": round(first_call_s, 3),
            "chunks": n_chunks,
            "backlog_peak": bk_peak,
            "packed_tables": bool(pack),
        }
        if policy is not None:
            util["adapt"] = policy.summary()
        # W is the history's actual window; W_pad the kernel's padded
        # width (equal for the narrow path, 32-padded for wide lanes)
        detail = {"W": enc.window_raw, "W_pad": W, "K": K,
                  "configs_explored": total_explored,
                  "wall_s": round(wall, 4), "util": util}
        if dmark is not None:
            # measured HBM peak for this search window — the number
            # the preflight drift gate compares against its analytic
            # hbm.peak_bytes (an explicit stats_unavailable marker
            # where the backend has no allocator stats, e.g. cpu)
            hbm_block = dm.measured(dmark, where=f"wgl/{plat}")
            detail["hbm"] = hbm_block
            if hbm_block.get("peak_measured") is not None:
                util["hbm_peak_measured"] = hbm_block["peak_measured"]
        if tl_points is not None:
            # the run's own copy of the per-chunk timeseries (the
            # registry keeps the cross-run series)
            detail["telemetry"] = {"chunks": tl_points}
            # the per-search occupancy block: drained rounds + fill
            # stats + roofline attribution. Cost analysis lowers the
            # jitted chunk WITHOUT a backend compile (Lowered.
            # cost_analysis), cached per shape bucket — safe under a
            # CompileGuard zero-compile budget.
            import jax as _jax

            def _lower():
                spec = _jax.tree.map(
                    lambda a: _jax.ShapeDtypeStruct(a.shape, a.dtype),
                    (consts, carry))
                return chunk_jit.lower(*spec)

            cost = _occ.cost_for(
                (kern, len(enc.inv), ic_eff, W_eff, K, chunk, depth,
                 accel, pack), _lower)
            detail["occupancy"] = _occ.build_block(
                occ_rounds, K=K, row_cols=row_cols,
                probes=probes_used, kernel=kern, platform=plat,
                wall_s=wall, rounds_total=rounds_total,
                configs_explored=total_explored,
                memo_hits=memo_hits, memo_inserts=inserted,
                rounds_dropped=occ_dropped, rounds_seen=occ_seen,
                device_kind=_occ.safe_device_kind(), cost=cost)
        if found:
            return {"valid?": True, "op_count": n + enc.n_info, **detail}
        if fr_cnt == 0:
            if overflow:
                return {"valid?": "unknown", "cause": "backlog-overflow",
                        "op_count": n + enc.n_info, **detail}
            return {"valid?": False, "op_count": n + enc.n_info,
                    "max_linearized": int(stats[2]), **detail}
        if total_explored >= max_configs:
            return {"valid?": "unknown", "cause": "config-limit",
                    "op_count": n + enc.n_info, **detail}
        if deadline is not None and _time.monotonic() > deadline:
            return {"valid?": "unknown", "cause": "timeout",
                    "op_count": n + enc.n_info, **detail}
        return {"valid?": "unknown", "cause": "cancelled",
                "op_count": n + enc.n_info, **detail}


def enrich_diagnostics(model: Model, history: History, res: dict,
                       time_limit: float = 30.0,
                       stop: Optional[Callable[[], bool]] = None,
                       tracer=None) -> dict:
    """On a device False verdict, re-run the host oracle briefly to
    extract counterexample diagnostics (final_paths / configs),
    matching the reference's expectation that invalid results explain
    themselves (checker.clj:205-212 renders linear.svg from them)."""
    from .. import trace as _trace_mod
    tracer = tracer if tracer is not None else _trace_mod.NULL_TRACER
    if res.get("valid?") is False and "final_paths" not in res \
            and not (stop is not None and stop()):
        with tracer.span("enrich"):
            ref = wgl_ref.check(model, history, time_limit=time_limit,
                                stop=stop)
        if ref.get("valid?") is False:
            for k in ("final_paths", "configs", "max_linearized"):
                if k in ref:
                    res[k] = ref[k]
    return res


def check_with_diagnostics(model: Model, history: History,
                           time_limit: Optional[float] = None,
                           stop: Optional[Callable[[], bool]] = None,
                           metrics=None, tracer=None) -> dict:
    """TPU verdict + counterexample enrichment (enrich_diagnostics)."""
    res = check(model, history, time_limit=time_limit, stop=stop,
                metrics=metrics, tracer=tracer)
    # stop still threads through: in a competition race the oracle
    # runs concurrently anyway, and the loser must stay cancellable
    return enrich_diagnostics(model, history, res, stop=stop,
                              tracer=tracer)
