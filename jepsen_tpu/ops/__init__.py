"""Checker compute kernels.

`wgl_ref` is the pure-Python Wing–Gong–Lowe search (correctness oracle and
counterexample extractor); `wgl` is the TPU kernel — the same search as a
vmapped lockstep frontier exploration under `jax.jit`. `linprep` is the
shared history → operation-table preprocessing both consume.
"""
