"""Pure-Python Wing–Gong–Lowe linearizability search.

The correctness oracle for the TPU kernel (`jepsen_tpu.ops.wgl`) and the
counterexample extractor. Capability parity with knossos.wgl/analysis (an
external dep of the reference, selected at
`jepsen/src/jepsen/checker.clj:199-202`): given a model and a history,
decide whether the history is linearizable, returning
`{"valid?": True/False/"unknown", ...}` with `final_paths` /
`configs` diagnostics on failure (truncated to 10, matching
`jepsen/src/jepsen/checker.clj:213-216` — "Writing these can take hours").

Algorithm: depth-first search over partial linearizations. A configuration
is (linearized-set, model-state); op i may be linearized next when every op
that *returned* before i was *invoked* is already linearized (the real-time
constraint) and the model accepts it. Configurations are memoized — the
cache is what makes WGL tractable (Lowe's "just-in-time linearization").
:info ops may be linearized or skipped; :ok ops must all be linearized.
"""

from __future__ import annotations

import time as _time
from typing import Any, Callable, Optional

from ..history import History
from ..models.core import Model, is_inconsistent
from .linprep import LinOp, prepare, precedence_masks


def _bits(mask: int):
    i = 0
    while mask:
        if mask & 1:
            yield i
        mask >>= 1
        i += 1


def check(model: Model, history: History, time_limit: Optional[float] = None,
          max_configs: int = 20_000_000,
          stop: Optional[Callable[[], bool]] = None) -> dict:
    """Decide linearizability of `history` under `model`.

    Returns {"valid?": bool | "unknown", "op_count": n, ...}. On False,
    includes "final_paths" (sample linearization prefixes that got
    furthest) and "configs" (the stuck configurations). On "unknown",
    includes "cause" ("timeout", "config-limit", or "cancelled" when
    the `stop` callable — polled every 4096 configs — returns True;
    competition racing uses it to cancel the losing engine).
    """
    ops = prepare(history)
    n = len(ops)
    if n == 0:
        return {"valid?": True, "op_count": 0}
    if n > 1000 and time_limit is None:
        time_limit = 3600.0
    pred = precedence_masks(ops)
    ok_mask = 0
    for i, o in enumerate(ops):
        if o.ok:
            ok_mask |= 1 << i
    full = (1 << n) - 1
    deadline = _time.monotonic() + time_limit if time_limit else None

    seen: set[tuple[int, Any]] = set()
    # Each stack frame: (linearized_mask, model, path tuple of op ids)
    stack: list[tuple[int, Model, tuple]] = [(0, model, ())]
    seen.add((0, model))
    # Track the deepest progress for diagnostics.
    best_count = -1
    best: list[tuple[int, Model, tuple]] = []
    explored = 0

    while stack:
        if explored % 4096 == 0:
            if deadline is not None and _time.monotonic() > deadline:
                return {"valid?": "unknown", "cause": "timeout",
                        "op_count": n, "configs_explored": explored}
            if stop is not None and stop():
                return {"valid?": "unknown", "cause": "cancelled",
                        "op_count": n, "configs_explored": explored}
        if explored > max_configs:
            return {"valid?": "unknown", "cause": "config-limit",
                    "op_count": n, "configs_explored": explored}
        mask, m, path = stack.pop()
        explored += 1
        if mask & ok_mask == ok_mask:
            return {"valid?": True, "op_count": n,
                    "configs_explored": explored,
                    "linearization": [ops[i].as_op().to_dict() for i in path]}
        cnt = bin(mask & ok_mask).count("1")
        if cnt > best_count:
            best_count = cnt
            best = [(mask, m, path)]
        elif cnt == best_count and len(best) < 10:
            best.append((mask, m, path))
        # Candidates: unlinearized ops whose real-time predecessors are all
        # linearized.
        cand = ~mask & full
        while cand:
            i = (cand & -cand).bit_length() - 1
            cand &= cand - 1
            if pred[i] & ~mask:
                continue
            m2 = m.step(ops[i].as_op())
            if is_inconsistent(m2):
                continue
            mask2 = mask | (1 << i)
            key = (mask2, m2)
            if key not in seen:
                seen.add(key)
                stack.append((mask2, m2, path + (i,)))

    # Exhausted: not linearizable. Build diagnostics from deepest configs.
    configs = []
    final_paths = []
    for mask, m, path in best[:10]:
        configs.append({
            "model": m,
            "linearized": sorted(_bits(mask)),
            "pending": [ops[i].as_op().to_dict()
                        for i in _bits(~mask & ok_mask)][:10],
        })
        final_paths.append([ops[i].as_op().to_dict() for i in path])
    return {"valid?": False, "op_count": n, "configs_explored": explored,
            "max_linearized": best_count,
            "configs": configs, "final_paths": final_paths}
