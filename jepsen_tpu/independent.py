"""Lift single-key tests to maps of keys (jepsen.independent parity).

Expensive checks (linearizability above all) only tolerate short
histories, so the reference splits a test into independent keys: values
become `[k v]` tuples, generators are lifted to emit them, and the checker
partitions the history into per-key subhistories
(`jepsen/src/jepsen/independent.clj:2-7,21-24,240-317`).

Two checker paths here:

  * `checker(c)` — capability parity: bounded-pmap the wrapped checker
    over per-key subhistories on host threads (independent.clj:266-317).
  * `tpu_checker(model)` — the TPU-native path (SURVEY.md P2): all per-key
    subhistories are batch-encoded and searched in one mesh-sharded WGL
    call (`jepsen_tpu.parallel.batched`), each device checking its own
    keys in lockstep.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional, Sequence

from .checker import Checker, check_safe, merge_valid
from .history import History, Op, strip_nemesis
from .models.core import Model
from .util import bounded_pmap

DIR = "independent"


@dataclass(frozen=True)
class KV:
    """A [k v] tuple value (independent.clj:21-29 uses MapEntry)."""

    k: Any
    v: Any

    def __iter__(self):
        return iter((self.k, self.v))

    def __repr__(self):
        return f"[{self.k!r} {self.v!r}]"


def tuple_(k, v) -> KV:
    return KV(k, v)


def is_tuple(value) -> bool:
    return isinstance(value, KV)


def history_keys(history: History) -> list:
    """The set of keys present in a history's tuple values
    (independent.clj:240-250). Returned as a list in first-seen order so
    results are deterministic."""
    seen: dict = {}
    for op in history:
        v = op.value
        if is_tuple(v) and v.k not in seen:
            seen[v.k] = True
    return list(seen)


def subhistory(k, history: History) -> History:
    """All ops that do not carry a *different* key, with tuple values
    unwrapped (independent.clj:252-264) — nemesis/info ops without tuple
    values are retained in every subhistory."""
    out = History()
    for op in history:
        v = op.value
        if not is_tuple(v):
            out.append(op)
        elif v.k == k:
            out.append(op.with_(value=v.v))
    return out


class IndependentChecker(Checker):
    """Host-parallel per-key checking (independent.clj:266-317)."""

    def __init__(self, checker: Checker):
        self.checker = checker

    def check(self, test, history, opts=None):
        opts = opts or {}
        ks = history_keys(history)

        def check_key(k):
            h = subhistory(k, history)
            subdir = list(opts.get("subdirectory", [])) + [DIR, str(k)]
            res = check_safe(self.checker, test, h,
                             {**opts, "subdirectory": subdir,
                              "history_key": k})
            _write_key_artifacts(test, subdir, h, res)
            return k, res

        results = dict(bounded_pmap(check_key, ks))
        failures = [k for k in ks if not results[k].get("valid?")]
        return {"valid?": merge_valid(r.get("valid?")
                                      for r in results.values()),
                "results": results,
                "failures": failures}


def checker(c: Checker) -> Checker:
    return IndependentChecker(c)


def _write_key_artifacts(test, subdir, h, res):
    """Persist per-key results/history under the test's store dir, when
    the test has one (independent.clj:295-303)."""
    d = (test or {}).get("store_dir")
    if not d:
        return
    import json
    import os
    path = os.path.join(d, *subdir)
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "results.json"), "w") as fh:
        json.dump(res, fh, indent=2, default=str)
    h.to_jsonl(os.path.join(path, "history.jsonl"))


class TPULinearizableIndependent(Checker):
    """Per-key linearizability in one mesh-sharded device search.

    The history is split into per-key subhistories exactly as
    `IndependentChecker` does, but instead of a host thread per key, the
    whole key set is checked by `parallel.check_batched` — the batch axis
    is laid out over the device mesh, so a v5e-8 checks 8 keys' frontiers
    at every step.
    """

    def __init__(self, model: Model, time_limit: Optional[float] = None,
                 mesh=None):
        self.model = model
        self.time_limit = time_limit
        self.mesh = mesh

    def check(self, test, history, opts=None):
        from .parallel import check_batched
        opts = opts or {}
        ks = history_keys(history)
        subs = [subhistory(k, history) for k in ks]
        res_list = check_batched(self.model,
                                 [strip_nemesis(s) for s in subs],
                                 time_limit=self.time_limit, mesh=self.mesh)
        results = dict(zip(ks, res_list))
        for k, h, res in zip(ks, subs, res_list):
            subdir = list(opts.get("subdirectory", [])) + [DIR, str(k)]
            _write_key_artifacts(test, subdir, h, res)
        failures = [k for k in ks if not results[k].get("valid?")]
        return {"valid?": merge_valid(r.get("valid?")
                                      for r in results.values()),
                "results": results,
                "failures": failures}


def tpu_checker(model: Model, time_limit: Optional[float] = None,
                mesh=None) -> Checker:
    return TPULinearizableIndependent(model, time_limit, mesh)
