"""Lift single-key tests to maps of keys (jepsen.independent parity).

Expensive checks (linearizability above all) only tolerate short
histories, so the reference splits a test into independent keys: values
become `[k v]` tuples, generators are lifted to emit them, and the checker
partitions the history into per-key subhistories
(`jepsen/src/jepsen/independent.clj:2-7,21-24,240-317`).

Two checker paths here:

  * `checker(c)` — capability parity: bounded-pmap the wrapped checker
    over per-key subhistories on host threads (independent.clj:266-317).
  * `tpu_checker(model)` — the TPU-native path (SURVEY.md P2): all per-key
    subhistories are batch-encoded and searched in one mesh-sharded WGL
    call (`jepsen_tpu.parallel.batched`), each device checking its own
    keys in lockstep.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional

import time as _time

from . import fleet as _fleet
from . import generator as gen
from .checker import Checker, check_safe, merge_valid
from .history import History, Op, strip_nemesis
from .models.core import Model
from .util import bounded_pmap

DIR = "independent"


@dataclass(frozen=True)
class KV:
    """A [k v] tuple value (independent.clj:21-29 uses MapEntry)."""

    k: Any
    v: Any

    def __iter__(self):
        return iter((self.k, self.v))

    def __repr__(self):
        return f"[{self.k!r} {self.v!r}]"


def tuple_(k, v) -> KV:
    return KV(k, v)


def is_tuple(value) -> bool:
    return isinstance(value, KV)


# ---------------------------------------------------------------------------
# Generator lifting (independent.clj:31-238)
# ---------------------------------------------------------------------------

def tuple_gen(k, g):
    """Wrap a generator so its invocations carry [k v] tuple values
    (independent.clj:96-103)."""
    def wrap(op):
        if op.get("type", "invoke") == "invoke":
            return {**op, "value": tuple_(k, op.get("value"))}
        return op
    return gen.map_(wrap, g)


def sequential_generator(keys: Iterable, fgen: Callable):
    """One key at a time: exhaust fgen(k1), move to k2, ... — each op's
    value wrapped as a [k v] tuple (independent.clj:31-47). fgen must be
    pure."""
    return [tuple_gen(k, fgen(k)) for k in keys]


def group_threads(n: int, ctx) -> list:
    """Partition the context's threads (sorted) into groups of n
    (independent.clj:49-76); asserts divisibility the same way."""
    threads = sorted(ctx.all_threads(), key=str)
    count = len(threads)
    assert n <= count, (
        f"with {count} worker threads, a concurrent generator cannot run "
        f"a key with {n} threads; raise concurrency to at least {n}")
    assert count % n == 0, (
        f"{count} worker threads cannot be evenly split into groups of "
        f"{n}; set concurrency to a multiple of {n}")
    return [frozenset(threads[i:i + n]) for i in range(0, count, n)]


class _KeyStream:
    """Memoized view over a (possibly infinite) key iterable. Cloned
    ConcurrentGenerator states share one stream and index into it; the
    buffer only grows, so `get(i)` is deterministic regardless of which
    clone asks first — pure-value semantics preserved over a lazy
    source (the reference's `(range)` infinite key seq,
    independent.clj:228)."""

    _EXHAUSTED = object()

    def __init__(self, keys: Iterable):
        self._it = iter(keys)
        self._buf: list = []
        self._done = False

    def get(self, i: int):
        """Key #i, or _EXHAUSTED if the source ran out."""
        while len(self._buf) <= i and not self._done:
            try:
                self._buf.append(next(self._it))
            except StopIteration:
                self._done = True
        return self._buf[i] if i < len(self._buf) else self._EXHAUSTED


class ConcurrentGenerator(gen.Generator):
    """Splits worker threads into groups of n per key; each group runs
    fgen(k) until exhaustion, then takes the next key. Ops are chosen by
    soonest-op selection across free groups; updates route to the
    owning group's generator (independent.clj:103-211). Keys may be an
    infinite iterable (wrap the whole thing in gen.time_limit/limit).

    Use via `concurrent_generator(...)`, which excludes the nemesis."""

    def __init__(self, n: int, keys, fgen: Callable,
                 groups: Optional[list] = None,
                 thread_group: Optional[dict] = None,
                 gens: Optional[list] = None,
                 pos: int = 0):
        assert n > 0 and isinstance(n, int)
        self.n = n
        self.keys = keys if isinstance(keys, _KeyStream) \
            else _KeyStream(keys)
        self.fgen = fgen
        self.groups = groups            # list of frozensets of threads
        self.thread_group = thread_group  # thread -> group index
        self.gens = gens                # per-group generator (or None)
        self.pos = pos                  # next key index in the stream

    def _next_key(self, pos: int):
        k = self.keys.get(pos)
        return (None, pos) if k is self.keys._EXHAUSTED else (k, pos + 1)

    def _grouped(self, ctx):
        groups = self.groups or group_threads(self.n, ctx)
        tg = self.thread_group or {t: i for i, g in enumerate(groups)
                                   for t in g}
        pos = self.pos
        if self.gens is None:
            gens = []
            for _ in groups:
                k, pos2 = self._next_key(pos)
                gens.append(tuple_gen(k, self.fgen(k))
                            if pos2 != pos else None)
                pos = pos2
        else:
            gens = list(self.gens)
        return groups, tg, gens, pos

    def op(self, test, ctx):
        groups, tg, gens, pos = self._grouped(ctx)
        free_groups = sorted({tg[t] for t in ctx.free_threads if t in tg})
        soonest = None
        for grp in free_groups:
            while True:
                g = gens[grp]
                if g is None:
                    break
                members = groups[grp]
                gctx = ctx.restrict(lambda t, s=members: t in s)
                res = gen.op(g, test, gctx)
                if res is None:
                    # exhausted: take the next key, or retire the group
                    k, pos2 = self._next_key(pos)
                    if pos2 != pos:
                        pos = pos2
                        gens[grp] = tuple_gen(k, self.fgen(k))
                        continue
                    gens[grp] = None
                    break
                o, g2 = res
                soonest = gen.soonest_op_map(
                    soonest, {"op": o, "group": grp, "gen": g2,
                              "weight": len(members)})
                if o is gen.PENDING:
                    gens[grp] = g2
                break
        if soonest is not None and soonest["op"] is not gen.PENDING:
            gens2 = list(gens)
            gens2[soonest["group"]] = soonest["gen"]
            return (soonest["op"],
                    ConcurrentGenerator(self.n, self.keys, self.fgen,
                                        groups, tg, gens2, pos))
        if any(g is not None for g in gens):
            # busy groups may still produce ops
            return (gen.PENDING,
                    ConcurrentGenerator(self.n, self.keys, self.fgen,
                                        groups, tg, gens, pos))
        return None

    def update(self, test, ctx, event):
        if self.thread_group is None or self.gens is None:
            return self
        t = ctx.process_to_thread(event.get("process"))
        grp = self.thread_group.get(t)
        if grp is None or self.gens[grp] is None:
            return self
        members = self.groups[grp]
        gctx = ctx.restrict(lambda th, s=members: th in s)
        gens = list(self.gens)
        gens[grp] = gen.update(gens[grp], test, gctx, event)
        return ConcurrentGenerator(self.n, self.keys, self.fgen,
                                   self.groups, self.thread_group, gens,
                                   self.pos)


def concurrent_generator(n: int, keys: Iterable, fgen: Callable):
    """Thread groups of n per key, soonest-op scheduling, nemesis
    excluded (independent.clj:213-238). keys may be infinite (e.g.
    itertools.count()); bound the workload with gen.time_limit."""
    return gen.clients(ConcurrentGenerator(n, keys, fgen))


def history_keys(history: History) -> list:
    """The set of keys present in a history's tuple values
    (independent.clj:240-250). Returned as a list in first-seen order so
    results are deterministic."""
    seen: dict = {}
    for op in history:
        v = op.value
        if is_tuple(v) and v.k not in seen:
            seen[v.k] = True
    return list(seen)


def subhistory(k, history: History) -> History:
    """All ops that do not carry a *different* key, with tuple values
    unwrapped (independent.clj:252-264) — nemesis/info ops without tuple
    values are retained in every subhistory."""
    out = History()
    for op in history:
        v = op.value
        if not is_tuple(v):
            out.append(op)
        elif v.k == k:
            out.append(op.with_(value=v.v))
    return out


def _record_fanout_ledger(test, name, out, ks, model=None,
                          engine=None) -> None:
    """One run-ledger record per independent fan-out: verdict, key
    count, failures, and the fleet summary's device/straggler columns
    (ledger.summarize_result lifts util.fleet). No-op without an
    installed ledger; never raises."""
    from . import ledger as _ledger
    wall = None
    fleet_sum = (out.get("util") or {}).get("fleet") or {}
    if fleet_sum.get("span_s") is not None:
        wall = fleet_sum["span_s"]
    _ledger.record_result(
        "independent", (test or {}).get("name") or name, out,
        wall_s=wall, model=model, engine=engine,
        extra={"keys": len(ks),
               "failures": len(out.get("failures") or [])})


class IndependentChecker(Checker):
    """Host-parallel per-key checking (independent.clj:266-317)."""

    def __init__(self, checker: Checker):
        self.checker = checker

    def check(self, test, history, opts=None):
        from .analysis import history_lint
        opts = opts or {}
        # One well-formedness pass over the WHOLE history before the
        # fan-out: a malformed run fast-fails with op-level diagnoses
        # instead of spending a device (or a thread pool) per key.
        bad = history_lint.gate(
            strip_nemesis(history), where="independent",
            rules=history_lint.INDEPENDENT_GATE_RULES)
        if bad is not None:
            return {**bad, "results": {}, "failures": []}
        ks = history_keys(history)
        key_idx = {k: i for i, k in enumerate(ks)}
        status = _fleet.get_default()
        if status.enabled and ks:
            status.begin_keys(len(ks))

        def check_key(k):
            i = key_idx[k]
            t0 = _time.monotonic()
            h = subhistory(k, history)
            subdir = list(opts.get("subdirectory", [])) + [DIR, str(k)]
            res = check_safe(self.checker, test, h,
                             {**opts, "subdirectory": subdir,
                              "history_key": k})
            shard = {"key_index": i, "key": str(k), "device": "host",
                     "engine": str(res.get("engine") or "host"),
                     "t0": round(t0, 4),
                     "wall_s": round(_time.monotonic() - t0, 4),
                     "valid?": res.get("valid?"),
                     "op_count": res.get("op_count")}
            res["shard"] = shard
            _fleet.record_shard(shard)
            _write_key_artifacts(test, subdir, h, res)
            return k, res

        results = dict(bounded_pmap(check_key, ks))
        failures = [k for k in ks if not results[k].get("valid?")]
        out = {"valid?": merge_valid(r.get("valid?")
                                     for r in results.values()),
               "results": results,
               "failures": failures,
               "util": {"fleet": _fleet.summarize(
                   [r.get("shard") for r in results.values()])}}
        _record_fanout_ledger(test, "independent", out, ks)
        return out


def checker(c: Checker) -> Checker:
    return IndependentChecker(c)


def _write_key_artifacts(test, subdir, h, res):
    """Persist per-key results/history under the test's store dir, when
    the test has one (independent.clj:295-303)."""
    d = (test or {}).get("store_dir")
    if not d:
        return
    import json
    import os
    path = os.path.join(d, *subdir)
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "results.json"), "w") as fh:
        json.dump(res, fh, indent=2, default=str)
    h.to_jsonl(os.path.join(path, "history.jsonl"))


class TPULinearizableIndependent(Checker):
    """Per-key linearizability in one mesh-sharded device search.

    The history is split into per-key subhistories exactly as
    `IndependentChecker` does, but instead of a host thread per key, the
    whole key set is checked by `parallel.check_batched` — the batch axis
    is laid out over the device mesh, so a v5e-8 checks 8 keys' frontiers
    at every step.
    """

    def __init__(self, model: Model, time_limit: Optional[float] = None,
                 mesh=None):
        self.model = model
        self.time_limit = time_limit
        self.mesh = mesh

    def check(self, test, history, opts=None):
        from .analysis import history_lint
        from .parallel import check_batched
        opts = opts or {}
        bad = history_lint.gate(
            strip_nemesis(history), where="independent.tpu",
            rules=history_lint.INDEPENDENT_GATE_RULES)
        if bad is not None:
            return {**bad, "results": {}, "failures": []}
        ks = history_keys(history)
        _fleet.get_default().phase("independent-check")
        subs = [subhistory(k, history) for k in ks]
        res_list = check_batched(self.model,
                                 [strip_nemesis(s) for s in subs],
                                 time_limit=self.time_limit, mesh=self.mesh)
        results = dict(zip(ks, res_list))
        for k, h, res in zip(ks, subs, res_list):
            if isinstance(res.get("shard"), dict):
                res["shard"]["key"] = str(k)
            subdir = list(opts.get("subdirectory", [])) + [DIR, str(k)]
            _write_key_artifacts(test, subdir, h, res)
        failures = [k for k in ks if not results[k].get("valid?")]
        out = {"valid?": merge_valid(r.get("valid?")
                                     for r in results.values()),
               "results": results,
               "failures": failures,
               "util": {"fleet": _fleet.summarize(
                   [r.get("shard") for r in res_list])}}
        _record_fanout_ledger(test, "independent", out, ks,
                              model=type(self.model).__name__,
                              engine="device-mesh")
        return out


def tpu_checker(model: Model, time_limit: Optional[float] = None,
                mesh=None) -> Checker:
    return TPULinearizableIndependent(model, time_limit, mesh)
