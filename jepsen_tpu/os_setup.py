"""OS preparation (parity with jepsen.os + os/{debian,ubuntu,centos},
`jepsen/src/jepsen/os.clj:4-8` and `os/debian.clj` etc.): hostfile setup,
package installation, and time sync, run once per node before DB setup."""

from __future__ import annotations

import logging
from typing import Optional, Sequence

from . import control as c
from .control import nodeutil as cu
from .control.core import lit

log = logging.getLogger("jepsen_tpu.os")


class OS:
    """os.clj:4-8."""

    def setup(self, test: dict, node: str) -> None:
        return None

    def teardown(self, test: dict, node: str) -> None:
        return None


class Noop(OS):
    """os.clj:10-14."""


noop = Noop


def setup_hostfile(test: dict, node: str) -> None:
    """Write /etc/hosts mapping every test node (os/debian.clj:13-31):
    nodes resolve each other by name even without cluster DNS."""
    from .control import netinfo
    lines = ["127.0.0.1 localhost"]
    for n in test.get("nodes", []):
        try:
            lines.append(f"{netinfo.ip(n)} {n}")
        except Exception:  # noqa: BLE001 - unresolvable in dummy tests
            continue
    content = "\n".join(lines) + "\n"
    with c.su():
        cu.write_file(content, "/etc/hosts")


class Debian(OS):
    """Debian preparation (os/debian.clj:80-205): hostfile, apt packages,
    ntp sync."""

    def __init__(self, packages: Sequence[str] = ()):
        self.packages = list(packages)

    def install(self, pkgs: Sequence[str]) -> None:
        """Install packages unless already present (os/debian.clj:60-80)."""
        if not pkgs:
            return
        with c.su():
            c.exec_(c.env({"DEBIAN_FRONTEND": "noninteractive"}),
                    "apt-get", "install", "-y", "--force-yes", *pkgs)

    def installed(self, pkg: str) -> bool:
        try:
            c.exec_("dpkg", "-s", pkg)
            return True
        except Exception:  # noqa: BLE001
            return False

    def install_jdk(self) -> None:
        """os/debian.clj:153-170."""
        self.install(["openjdk-17-jdk-headless"])

    def setup(self, test, node):
        log.info("Setting up debian on %s", node)
        setup_hostfile(test, node)
        with c.su():
            cu.meh(c.exec_, "apt-get", "update")
        self.install(["curl", "wget", "unzip", "iptables", "psmisc",
                      "tar", "bzip2", "ntpdate", "faketime", "rsyslog",
                      "logrotate"] + self.packages)
        with c.su():
            cu.meh(c.exec_, "service", "ntp", "stop")
            cu.meh(c.exec_, "ntpdate", "-p", "1", "-b",
                   "pool.ntp.org")


debian = Debian


class Ubuntu(Debian):
    """os/ubuntu.clj — identical shape to debian."""


ubuntu = Ubuntu


class CentOS(OS):
    """CentOS preparation (os/centos.clj)."""

    def __init__(self, packages: Sequence[str] = ()):
        self.packages = list(packages)

    def install(self, pkgs: Sequence[str]) -> None:
        if not pkgs:
            return
        with c.su():
            c.exec_("yum", "install", "-y", *pkgs)

    def setup(self, test, node):
        log.info("Setting up centos on %s", node)
        setup_hostfile(test, node)
        self.install(["curl", "wget", "unzip", "iptables", "psmisc",
                      "tar", "bzip2", "ntpdate"] + self.packages)
        with c.su():
            cu.meh(c.exec_, "ntpdate", "-p", "1", "-b", "pool.ntp.org")


centos = CentOS


class SmartOS(OS):
    """SmartOS/illumos preparation (os/smartos.clj:1-96): pkgin
    packages, loopback hostname entry, and the ipfilter-based Net
    backend instead of iptables (net.clj:113-145)."""

    def __init__(self, packages: Sequence[str] = ()):
        self.packages = list(packages)

    def installed(self, pkgs: Sequence[str]) -> set:
        """Subset of `pkgs` already installed (smartos.clj:46-58)."""
        want = set(pkgs)
        out = cu.meh(c.exec_, "pkgin", "-p", "list") or ""
        have = set()
        for line in out.splitlines():
            name = line.split(";", 1)[0]
            base = name.rsplit("-", 1)[0] if "-" in name else name
            have.add(base)
        return want & have

    def install(self, pkgs: Sequence[str]) -> None:
        have = self.installed(pkgs)
        missing = [p for p in pkgs if p not in have]
        if missing:
            with c.su():
                c.exec_("pkgin", "-y", "install", *missing)

    def setup_hostfile(self) -> None:
        """Append the local hostname to the loopback /etc/hosts line
        (smartos.clj:12-25) — SmartOS zones resolve themselves, not the
        whole cluster."""
        name = c.exec_("hostname").strip()
        hosts = c.exec_("cat", "/etc/hosts")
        out = []
        for line in hosts.splitlines():
            fields = line.split()
            if fields and fields[0] == "127.0.0.1" \
                    and name not in fields[1:]:
                line = f"{line} {name}"
            out.append(line)
        with c.su():
            cu.write_file("\n".join(out) + "\n", "/etc/hosts")

    def setup(self, test, node):
        log.info("Setting up smartos on %s", node)
        self.setup_hostfile()
        with c.su():
            cu.meh(c.exec_, "pkgin", "update")
        self.install(["curl", "wget", "gtar", "unzip"]
                     + self.packages)


smartos = SmartOS
