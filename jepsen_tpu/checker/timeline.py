"""HTML swimlane timeline of operations per process.

Capability parity with jepsen.checker.timeline
(`jepsen/src/jepsen/checker/timeline.clj`): one column per process,
one box per invoke/completion pair, colored by completion type, with
hover titles carrying the full op, duration, and error; capped at
10,000 ops so massive histories stay renderable (timeline.clj:12-14).
Pairing rides `History.pairs()` (the timeline.clj:38-57 algorithm).
Writes `timeline.html` into the test's store directory (or the per-key
subdirectory when run under `independent.checker`).
"""

from __future__ import annotations

import html as _html
from typing import Optional

from .. import store
from ..history import History
from . import Checker

OP_LIMIT = 10_000  # timeline.clj:12-14

COL_WIDTH = 100     # px
GUTTER_WIDTH = 106  # px
HEIGHT = 16         # px

STYLESHEET = """\
body        { font-family: sans-serif; }
.ops        { position: absolute; }
.op         { position: absolute; padding: 2px; border-radius: 2px;
              box-shadow: 0 1px 3px rgba(0,0,0,0.2); overflow: hidden;
              font-size: 11px; }
.op.invoke  { background: #eeeeee; }
.op.ok      { background: #79c7f7; }
.op.info    { background: #f7c36b; }
.op.fail    { background: #f7a8c8; }
.op:target  { box-shadow: 0 10px 20px rgba(0,0,0,0.3); }
.truncation-warning { background: #f7c36b; border: 1px solid #c08020;
              border-radius: 3px; padding: 8px 12px; margin: 8px 0;
              font-weight: bold; }
.nemesis-band { position: absolute; left: 0; z-index: -1;
              background: rgba(247, 195, 107, 0.30);
              border-left: 3px solid #c08020;
              border-top: 1px dashed #c08020;
              border-bottom: 1px dashed #c08020; }
"""


def _esc(x) -> str:
    return _html.escape(str(x), quote=True)


def _render_op(op) -> str:
    d = op.to_dict() if hasattr(op, "to_dict") else dict(op)
    core = {k: d.pop(k, None)
            for k in ("process", "type", "f", "index", "value")}
    lines = [f"process {core['process']}", f"type {core['type']}",
             f"f {core['f']}", f"index {core['index']}"]
    lines += [f"{k} {v!r}" for k, v in d.items()
              if k not in ("time",) and v is not None]
    lines.append(f"value {core['value']!r}")
    return "Op:\n" + "\n".join(" " + ln for ln in lines)


def _title(start, stop) -> str:
    parts = []
    if stop is not None and stop.time is not None \
            and start.time is not None:
        parts.append(f"Dur: {(stop.time - start.time) // 1_000_000} ms")
    err = getattr(stop or start, "error", None)
    if err is not None:
        parts.append(f"Err: {err!r}")
    parts.append("")
    parts.append(_render_op(stop or start))
    return "\n".join(parts)


def _body(start, stop) -> str:
    op = stop or start
    s = _esc(f"{op.process} {op.f}")
    if op.process != "nemesis":
        s += f" {_esc(repr(start.value))}"
    if stop is not None and stop.value != start.value:
        s += f"<br />{_esc(repr(stop.value))}"
    return s


def process_index(history) -> dict:
    """Map processes to columns: numeric processes sorted first, then
    named ones like "nemesis" (timeline.clj:161-167)."""
    procs = {op.process for op in history}
    nums = sorted(p for p in procs if isinstance(p, int))
    names = sorted((p for p in procs if not isinstance(p, int)), key=str)
    return {p: i for i, p in enumerate(nums + names)}


def nemesis_bands(history, pairs) -> list:
    """Fault windows in ROW coordinates: [(row_open, row_close, f)],
    using the same start/stop pairing the latency plots shade with
    (util.nemesis_intervals) so both renderings agree on what counts
    as a window. Ops truncated off the page clamp to the last row; a
    window still open at the end extends there too."""
    from ..util import nemesis_intervals
    row_of = {}
    for row, (start, stop) in enumerate(pairs):
        if start.index is not None:
            row_of[start.index] = row
        if stop is not None and stop.index is not None:
            row_of[stop.index] = row
    bands = set()
    for s, e in nemesis_intervals(history):
        r0 = row_of.get(s.index)
        if r0 is None:
            continue  # the opening op fell past the truncation cap
        r1 = (row_of.get(e.index, len(pairs))
              if e is not None else len(pairs))
        bands.add((r0, max(r1, r0 + 1), str(s.f)))
    return sorted(bands)


def render(test: dict, history: History, history_key=None) -> str:
    """The timeline page as an HTML string."""
    all_pairs = History(history).pairs()
    # row = order of invocation (timeline.clj:169-174)
    truncated = len(all_pairs) > OP_LIMIT
    pairs = all_pairs[:OP_LIMIT]
    pindex = process_index([s for s, _ in pairs])

    divs = []
    # nemesis fault windows as shaded bands BEHIND the op boxes, so
    # fault injection and the anomalies it provoked line up visually
    band_width = GUTTER_WIDTH * max(len(pindex), 1)
    for r0, r1, f in nemesis_bands(history, pairs):
        top = HEIGHT * (r0 + 1)
        height = HEIGHT * max(r1 - r0, 1)
        divs.append(
            f"<div class='nemesis-band' style='top:{top}px;"
            f"height:{height}px;width:{band_width}px' "
            f"title='nemesis window: {_esc(f)} "
            f"(rows {r0}&#8211;{r1})'></div>")
    for row, (start, stop) in enumerate(pairs):
        op = stop or start
        typ = op.type
        left = GUTTER_WIDTH * pindex.get(start.process, 0)
        top = HEIGHT * (row + 1)
        style = (f"width:{COL_WIDTH}px;left:{left}px;top:{top}px;"
                 f"height:{HEIGHT}px")
        idx = op.index if op.index is not None else row
        divs.append(
            f"<a href='#i{idx}'><div class='op {_esc(typ)}' id='i{idx}' "
            f"style='{style}' title='{_esc(_title(start, stop))}'>"
            f"{_body(start, stop)}</div></a>")

    head = f"<h1>{_esc(test.get('name'))}"
    if history_key is not None:
        head += f" key {_esc(history_key)}"
    head += "</h1>"
    warn = ""
    if truncated:
        # a VISIBLE banner (styled above): silently dropping the tail
        # made huge histories look complete
        warn = (f"<div class='truncation-warning'>&#9888; truncated: "
                f"showing {OP_LIMIT:,} of {len(all_pairs):,} ops "
                f"(the remaining {len(all_pairs) - OP_LIMIT:,} are in "
                f"history.txt)</div>")
    return (f"<!doctype html><html><head><meta charset='utf-8'>"
            f"<style>{STYLESHEET}</style></head><body>{head}{warn}"
            f"<div class='ops'>{''.join(divs)}</div></body></html>")


class TimelineHtml(Checker):
    """Writes timeline.html (timeline.clj:176-209)."""

    def check(self, test, history, opts=None):
        opts = opts or {}
        subdir = list(opts.get("subdirectory", []))
        doc = render(test, history, opts.get("history_key"))
        if test.get("name"):
            p = store.path_bang(test, *subdir, "timeline.html")
            with open(p, "w") as fh:
                fh.write(doc)
        return {"valid?": True}


def html() -> Checker:
    return TimelineHtml()
