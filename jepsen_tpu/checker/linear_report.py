"""linear.svg — render why a history is not linearizable.

Capability parity with `knossos.linear.report/render-analysis!`, which
the reference invokes whenever a linearizability analysis comes back
invalid (jepsen/src/jepsen/checker.clj:205-212): a per-process swimlane
of operation intervals with the furthest-reaching witnessed
linearization drawn as a path through the ops it managed to apply, and
the operation nobody could linearize highlighted.

Raw SVG strings — no plotting dependency; the store's web UI serves
image/svg+xml natively. Large histories are windowed around the
failure (the reference's renderer likewise falls over on huge
histories, hence knossos truncates analysis output)."""

from __future__ import annotations

import html
import logging
from typing import Optional

from .. import store
from ..history import History

log = logging.getLogger("jepsen_tpu.checker.linear_report")

MAX_OPS = 120         # ops rendered around the failure
BAR_H = 18
ROW_GAP = 8
X_SCALE = 26          # px per event index
LEFT = 90
TOP = 40

TYPE_FILL = {"ok": "#79c7f7", "info": "#f7c36b", "fail": "#f7a8c8"}


def _esc(s) -> str:
    return html.escape(str(s), quote=True)


def render(history: History, analysis: dict) -> Optional[str]:
    """The SVG document, or None when there is nothing to draw."""
    pairs = [(inv, comp) for inv, comp in History(history).pairs()
             if inv.is_invoke]
    if not pairs:
        return None

    # event-index timeline: x = position in the history
    n_events = max((c.index if c is not None else inv.index)
                   for inv, c in pairs) + 1

    # window around the failing op if the history is large: keep pairs
    # whose [invoke, complete] interval intersects it (the failing op's
    # return may trail its invoke by many events)
    bad = analysis.get("op") or {}
    bad_idx = bad.get("index")
    if len(pairs) > MAX_OPS:
        center = bad_idx if bad_idx is not None else n_events
        lo, hi = max(0, center - MAX_OPS), center + 8
        pairs = [p for p in pairs
                 if p[0].index <= hi
                 and (p[1].index if p[1] is not None
                      else n_events) >= lo]
        pairs = pairs[-MAX_OPS:]
    if not pairs:
        return None

    procs = []
    for inv, _ in pairs:
        if inv.process not in procs:
            procs.append(inv.process)
    rows = {p: i for i, p in enumerate(procs)}

    x0 = min(inv.index for inv, _ in pairs)

    def x_of(idx):
        return LEFT + (idx - x0) * X_SCALE

    def y_of(proc):
        return TOP + rows[proc] * (BAR_H + ROW_GAP)

    width = max(x_of(inv.index if c is None else c.index) + 160
                for inv, c in pairs)
    height = TOP + len(procs) * (BAR_H + ROW_GAP) + 60

    parts = [f"<svg xmlns='http://www.w3.org/2000/svg' width='{width}' "
             f"height='{height}' font-family='sans-serif' "
             f"font-size='11'>",
             f"<text x='{LEFT}' y='18' font-size='14'>"
             f"History is not linearizable — "
             f"{_esc(analysis.get('algorithm', ''))}</text>"]

    # search telemetry footer: how hard the kernel worked for this
    # verdict (the util block every device result carries)
    util = analysis.get("util") or {}
    if util or analysis.get("configs_explored") is not None:
        bits = []
        if analysis.get("configs_explored") is not None:
            bits.append(f"{analysis['configs_explored']} configs")
        if util.get("rounds") is not None:
            bits.append(f"{util['rounds']} rounds")
        if util.get("memo_hit_rate") is not None:
            bits.append(f"memo hit rate {util['memo_hit_rate']}")
        if analysis.get("wall_s") is not None:
            bits.append(f"{analysis['wall_s']} s")
        if bits:
            parts.append(
                f"<text x='{LEFT}' y='32' font-size='10' "
                f"fill='#666'>device search: "
                f"{_esc(', '.join(bits))}</text>")

    for p in procs:
        parts.append(f"<text x='8' y='{y_of(p) + 13}'>"
                     f"process {_esc(p)}</text>")

    # op bars
    centers = {}
    for inv, comp in pairs:
        end_idx = comp.index if comp is not None else inv.index + 1
        typ = comp.type if comp is not None else "info"
        x1, x2 = x_of(inv.index), x_of(end_idx) + X_SCALE - 6
        y = y_of(inv.process)
        is_bad = bad_idx is not None and (
            inv.index == bad_idx
            or (comp is not None and comp.index == bad_idx))
        stroke = "stroke='#d03030' stroke-width='2.5'" if is_bad \
            else "stroke='#888' stroke-width='0.5'"
        fill = TYPE_FILL.get(typ, "#dddddd")
        label = f"{inv.f} {comp.value if comp is not None else inv.value!r}"
        parts.append(
            f"<rect x='{x1}' y='{y}' width='{max(8, x2 - x1)}' "
            f"height='{BAR_H}' rx='3' fill='{fill}' {stroke}>"
            f"<title>{_esc(inv.to_dict())}</title></rect>")
        parts.append(
            f"<text x='{x1 + 3}' y='{y + 13}'>{_esc(label)}</text>")
        centers[inv.index] = (x1 + min(40, (x2 - x1) / 2), y + BAR_H / 2)

    # the furthest witnessed linearization as a numbered path
    paths = analysis.get("final_paths") or []
    best = max(paths, key=len) if paths else []
    pts = []
    for step, op in enumerate(best):
        idx = op.get("index") if isinstance(op, dict) else None
        if idx in centers:
            cx, cy = centers[idx]
            pts.append((cx, cy))
            parts.append(
                f"<circle cx='{cx}' cy='{cy}' r='8' fill='#205080' "
                f"opacity='0.85'/>"
                f"<text x='{cx - 3}' y='{cy + 4}' fill='#fff'>"
                f"{step + 1}</text>")
    if len(pts) > 1:
        d = "M " + " L ".join(f"{x:.0f} {y:.0f}" for x, y in pts)
        parts.append(f"<path d='{d}' fill='none' stroke='#205080' "
                     f"stroke-width='1.5' opacity='0.6'/>")

    if bad:
        parts.append(
            f"<text x='{LEFT}' y='{height - 20}' fill='#d03030'>"
            f"No configuration could linearize: "
            f"{_esc(bad.get('f'))} {_esc(bad.get('value'))} "
            f"(process {_esc(bad.get('process'))}, "
            f"index {_esc(bad_idx)})</text>")
    parts.append("</svg>")
    return "".join(parts)


def render_analysis(test: dict, history: History, analysis: dict,
                    opts: Optional[dict] = None) -> Optional[str]:
    """Write linear.svg into the test's store directory
    (checker.clj:205-212); returns the path, or None. Never raises —
    rendering failures must not mask the verdict."""
    try:
        doc = render(history, analysis)
        if doc is None or not test.get("name"):
            return None
        subdir = list((opts or {}).get("subdirectory", []))
        path = store.path_bang(test, *subdir, "linear.svg")
        with open(path, "w") as fh:
            fh.write(doc)
        return path
    except Exception:  # noqa: BLE001
        log.warning("linear.svg rendering failed", exc_info=True)
        return None
