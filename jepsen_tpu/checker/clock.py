"""Clock-skew plot over time.

Capability parity with jepsen.checker.clock
(`jepsen/src/jepsen/checker/clock.clj`): collects the
``clock_offsets`` maps the clock nemesis attaches to its ops
(nemesis/timefaults annotates ops exactly as nemesis/time.clj:98-146
does), producing per-node step series of offset-vs-time, rendered to
``clock-skew.png`` with common trailing node-name components stripped
(clock.clj:36-45)."""

from __future__ import annotations

import logging
from typing import Optional

from ..history import History
from .plots import _plt, _save

log = logging.getLogger("jepsen_tpu.checker.clock")


def history_datasets(history) -> dict:
    """{node: ([t_secs...], [offset...])} from ops carrying
    clock_offsets (clock.clj:13-34). Each series is extended to the
    final history time so step plots span the run."""
    series: dict = {}
    final_t = None
    for op in History(history):
        if op.time is not None and op.time >= 0:
            final_t = op.time / 1e9
        offsets = op.extra.get("clock_offsets") if op.extra else None
        if not offsets:
            continue
        t = op.time / 1e9 if op.time is not None and op.time >= 0 else 0.0
        for node, off in offsets.items():
            xs, ys = series.setdefault(node, ([], []))
            xs.append(t)
            ys.append(off)
    if final_t is not None:
        for xs, ys in series.values():
            if xs and xs[-1] < final_t:
                xs.append(final_t)
                ys.append(ys[-1])
    return series


def short_node_names(nodes) -> dict:
    """Strip common trailing domain components (clock.clj:36-45):
    ["n1.foo.com", "n2.foo.com"] -> {"n1.foo.com": "n1", ...}."""
    nodes = list(nodes)
    if len(nodes) < 2:
        return {n: n for n in nodes}
    parts = [str(n).split(".") for n in nodes]
    # how many trailing components are shared by all (proper suffix only)
    k = 0
    while (k < min(len(p) for p in parts) - 1
           and len({tuple(p[len(p) - k - 1:]) for p in parts}) == 1):
        k += 1
    return {n: ".".join(p[:len(p) - k]) for n, p in zip(nodes, parts)}


def plot(test, history, opts=None) -> Optional[str]:
    """Render clock-skew.png; None when no ops carry offsets
    (clock.clj:48-75)."""
    datasets = history_datasets(history)
    if not datasets:
        return None
    plt = _plt()
    names = short_node_names(datasets.keys())
    fig, ax = plt.subplots(figsize=(10, 4))
    for node in sorted(datasets, key=str):
        xs, ys = datasets[node]
        ax.step(xs, ys, where="post", label=names[node], lw=1.2)
    ax.set_xlabel("Time (s)")
    ax.set_ylabel("Skew (s)")
    ax.set_title(f"{test.get('name', '')} clock skew")
    ax.legend(loc="upper right", fontsize=8)
    out = _save(fig, test, opts, "clock-skew.png")
    plt.close(fig)
    return out
