"""Latency / rate plot rendering.

Capability parity with jepsen.checker.perf
(`jepsen/src/jepsen/checker/perf.clj`): raw latency scatter
(`latency-raw.png`, perf.clj:484-511), latency quantiles over time
(`latency-quantiles.png`, :513-556), completion-rate plot (`rate.png`,
:559-599), with nemesis activity rendered as shaded regions + event
lines (:240-340). Latencies are attached by pairing invocations with
completions; buckets are 30 s (quantiles) and 10 s (rate) as in the
reference.

Redesign: the reference shells out to gnuplot; here rendering is
matplotlib (Agg backend — no display needed), and bucketing/quantile
math is numpy over the history's column tensors rather than per-op
reduction: the columnar layout (`History.columns`) is already what the
TPU checkers consume, so the perf plane reuses it.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import numpy as np

from .. import store, util
from ..history import History

log = logging.getLogger("jepsen_tpu.checker.plots")

TYPES = ("ok", "info", "fail")
TYPE_COLORS = {"ok": "#3b82d0", "info": "#f0a030", "fail": "#e0509a"}
QUANTILES = (0.5, 0.95, 0.99, 1.0)
Q_COLORS = {0.5: "#7fbf6f", 0.95: "#4070c0", 0.99: "#9060c0",
            1.0: "#d05050"}
MARKERS = "ovs^Dpx+*"
NEMESIS_COLOR = "#cccccc"
NEMESIS_ALPHA = 0.35

DT_QUANTILES = 30.0  # seconds per bucket (perf.clj:519)
DT_RATE = 10.0       # perf.clj:563


def _plt():
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    return plt


def latency_points(history) -> list:
    """[(f, completion_type, t_secs, latency_ms)] for every completed
    client op (util/history->latencies + latency-point,
    perf.clj:144-149)."""
    out = []
    for inv, comp in History(history).pairs():
        if comp is None or not inv.is_invoke:
            continue
        if inv.process == "nemesis":
            continue
        if inv.time is None or comp.time is None or inv.time < 0:
            continue
        out.append((inv.f, comp.type, inv.time / 1e9,
                    (comp.time - inv.time) / 1e6))
    return out


def quantile_series(points, dt: float, qs=QUANTILES) -> dict:
    """{q: (times, values)} per-bucket latency quantiles
    (latencies->quantiles, perf.clj:64-88)."""
    if not points:
        return {}
    t = np.asarray([p[0] for p in points])
    lat = np.asarray([p[1] for p in points])
    buckets = np.floor(t / dt).astype(np.int64)
    out = {q: ([], []) for q in qs}
    for b in np.unique(buckets):
        sel = np.sort(lat[buckets == b])
        mid = b * dt + dt / 2
        for q in qs:
            out[q][0].append(mid)
            # floor-index quantile, exactly the reference's extract fn
            idx = min(len(sel) - 1, int(np.floor(len(sel) * q)))
            out[q][1].append(float(sel[idx]))
    return out


def _nemesis_spans(history):
    """[(t_start, t_stop_or_None)] in seconds, from nemesis
    start/stop-style intervals (util/nemesis-intervals)."""
    spans = []
    try:
        for start, stop in util.nemesis_intervals(history):
            if start is None or start.time is None or start.time < 0:
                continue
            t0 = start.time / 1e9
            t1 = stop.time / 1e9 if stop is not None and stop.time \
                is not None and stop.time >= 0 else None
            spans.append((t0, t1))
    except Exception:  # malformed nemesis histories never kill a plot
        log.debug("nemesis interval extraction failed", exc_info=True)
    return spans


def _shade_nemeses(ax, history, t_max: float):
    for t0, t1 in _nemesis_spans(history):
        ax.axvspan(t0, t1 if t1 is not None else t_max,
                   color=NEMESIS_COLOR, alpha=NEMESIS_ALPHA, lw=0)


def _save(fig, test, opts, filename) -> Optional[str]:
    if not test.get("name"):
        return None
    subdir = list((opts or {}).get("subdirectory", []))
    path = store.path_bang(test, *subdir, filename)
    fig.savefig(path, dpi=90, bbox_inches="tight")
    return path


def _fmarker(fs):
    order = sorted({str(f) for f in fs})
    return {f: MARKERS[i % len(MARKERS)] for i, f in enumerate(order)}


def point_graph(test, history, opts=None, pts=None) -> Optional[str]:
    """Raw latency scatter, log-y, one marker per f, one color per
    completion type (perf.clj:484-511). Pass precomputed pts to avoid
    re-pairing the history."""
    plt = _plt()
    if pts is None:
        pts = latency_points(history)
    if not pts:
        return None
    fig, ax = plt.subplots(figsize=(10, 4.5))
    t_max = max(p[2] for p in pts)
    _shade_nemeses(ax, history, t_max)
    markers = _fmarker(p[0] for p in pts)
    for f in sorted({str(p[0]) for p in pts}):
        for typ in TYPES:
            sel = [(p[2], p[3]) for p in pts
                   if str(p[0]) == f and p[1] == typ]
            if not sel:
                continue
            xs, ys = zip(*sel)
            ax.scatter(xs, ys, s=12, marker=markers[f],
                       color=TYPE_COLORS[typ], label=f"{f} {typ}",
                       alpha=0.7, linewidths=0)
    ax.set_yscale("log")
    ax.set_xlabel("Time (s)")
    ax.set_ylabel("Latency (ms)")
    ax.set_title(f"{test.get('name', '')} latency (raw)")
    ax.legend(loc="upper right", fontsize=7)
    out = _save(fig, test, opts, "latency-raw.png")
    plt.close(fig)
    return out


def quantiles_graph(test, history, opts=None, pts=None) -> Optional[str]:
    """Latency quantiles by f over time (perf.clj:513-556)."""
    plt = _plt()
    if pts is None:
        pts = latency_points(history)
    if not pts:
        return None
    fig, ax = plt.subplots(figsize=(10, 4.5))
    t_max = max(p[2] for p in pts)
    _shade_nemeses(ax, history, t_max)
    markers = _fmarker(p[0] for p in pts)
    for f in sorted({str(p[0]) for p in pts}):
        fpts = [p for p in pts if str(p[0]) == f]
        for q, (xs, ys) in quantile_series(
                [(p[2], p[3]) for p in fpts], DT_QUANTILES).items():
            ax.plot(xs, ys, marker=markers[f], markersize=4,
                    color=Q_COLORS.get(q, "#666666"), lw=1,
                    label=f"{f} q={q}")
    ax.set_yscale("log")
    ax.set_xlabel("Time (s)")
    ax.set_ylabel("Latency (ms)")
    ax.set_title(f"{test.get('name', '')} latency quantiles")
    ax.legend(loc="upper right", fontsize=7)
    out = _save(fig, test, opts, "latency-quantiles.png")
    plt.close(fig)
    return out


def search_progress_graph(test, chunks, opts=None,
                          rounds=None) -> Optional[str]:
    """search-progress.png: the WGL device search's own trajectory
    from the per-chunk telemetry timeseries (metrics.py `wgl_chunks`
    points / a result's `telemetry.chunks`): frontier + backlog
    occupancy, cumulative configs explored with the per-poll
    exploration rate, and the memo-table hit rate, all over search
    wall clock. `rounds` (a result's `occupancy.rounds` — per-round
    drained counters) overlays per-round frontier FILL on the
    hit-rate panel, so the progress graph shows occupancy alongside
    configs_explored. Never raises — a malformed point list must not
    mask the verdict it rides along with."""
    try:
        pts = [p for p in (chunks or []) if "wall_s" in p]
        if not pts:
            return None
        plt = _plt()
        t = [p["wall_s"] for p in pts]
        fig, axes = plt.subplots(3, 1, figsize=(10, 7), sharex=True)
        ax = axes[0]
        ax.plot(t, [p.get("frontier", 0) for p in pts], marker="o",
                markersize=3, lw=1, color=Q_COLORS[0.95],
                label="frontier")
        ax.plot(t, [p.get("backlog", 0) for p in pts], marker="s",
                markersize=3, lw=1, color=Q_COLORS[1.0],
                label="backlog")
        if any(p.get("K") for p in pts):
            ax.plot(t, [p.get("K", 0) for p in pts], lw=1, ls="--",
                    color="#888888", label="K (beam)")
        ax.set_yscale("symlog")
        ax.set_ylabel("configs")
        ax.legend(loc="upper right", fontsize=7)
        ax.set_title(f"{test.get('name', '')} search progress")

        ax = axes[1]
        ax.plot(t, [p.get("explored", 0) for p in pts], marker="o",
                markersize=3, lw=1, color=TYPE_COLORS["ok"],
                label="explored (cumulative)")
        rate = [p.get("explored_delta", 0) / max(p.get("poll_s", 0),
                                                 1e-9) for p in pts]
        ax2 = ax.twinx()
        ax2.plot(t, rate, marker="^", markersize=3, lw=1,
                 color=TYPE_COLORS["info"], label="configs/s")
        ax.set_ylabel("explored")
        ax2.set_ylabel("configs/s")
        h1, l1 = ax.get_legend_handles_labels()
        h2, l2 = ax2.get_legend_handles_labels()
        ax.legend(h1 + h2, l1 + l2, loc="upper left", fontsize=7)

        ax = axes[2]
        ax.plot(t, [p.get("memo_hit_rate", 0) for p in pts],
                marker="o", markersize=3, lw=1, color=Q_COLORS[0.99],
                label="memo hit rate")
        rpts = [r for r in (rounds or [])
                if r.get("wall_s") is not None
                and r.get("fill") is not None]
        if rpts:
            # per-round frontier fill (occupancy plane) on the same
            # 0..1 axis — the ROADMAP item-5 target line included
            from .. import occupancy as occupancy_mod
            target = occupancy_mod.TARGET_FILL
            ax.plot([r["wall_s"] for r in rpts],
                    [r["fill"] for r in rpts], lw=1,
                    color=TYPE_COLORS["fail"], alpha=0.7,
                    label="frontier fill (per round)")
            ax.axhline(target, lw=0.8, ls=":", color="#888888",
                       label=f"fill target {target}")
        ax.set_ylim(0, 1)
        ax.set_ylabel("hit rate / fill")
        ax.set_xlabel("Search wall clock (s)")
        ax.legend(loc="upper right", fontsize=7)

        out = _save(fig, test, opts, "search-progress.png")
        plt.close(fig)
        return out
    except Exception:  # noqa: BLE001
        log.warning("search-progress rendering failed", exc_info=True)
        return None


def occupancy_heatmap(test, points, opts=None,
                      filename="occupancy-heatmap.png",
                      out_path: Optional[str] = None,
                      events=None) -> Optional[str]:
    """occupancy-heatmap.png: frontier fill as a (lane x round) grid
    from occupancy points [{"round", "lane", "fill"}] — the
    single-search view is a 1-lane strip (occupancy.heatmap_points),
    the mesh-batched fan-out one lane per key (`wgl_batched_rounds`
    series), where stragglers show up as long hot rows and empty
    lanes as cold ones. Points carrying a `device` field (the mesh
    fan-out's lane->device attribution, parallel/batched.py) render
    an extra per-device column strip beside the lane axis, so the
    mesh layout is readable off the heatmap itself. `out_path`
    renders to an explicit file (the bench's artifact tree) instead
    of the test's store dir. Never raises — occupancy rendering must
    not mask a verdict. `events` (mesh scheduler actions from the
    `mesh_sched` series, each with a `round` coordinate) render as
    dashed vertical markers — steals grey, rebuckets labeled
    K->K' — so a scheduling decision is readable against the fill
    pattern that triggered it."""
    try:
        pts = [p for p in (points or [])
               if isinstance(p, dict)
               and isinstance(p.get("round"), int) and p["round"] >= 0
               and isinstance(p.get("lane"), int) and p["lane"] >= 0
               and isinstance(p.get("fill"), (int, float))]
        if not pts:
            return None
        plt = _plt()
        rounds = sorted({p["round"] for p in pts})
        lanes = sorted({p["lane"] for p in pts})
        ridx = {r: i for i, r in enumerate(rounds)}
        lidx = {la: i for i, la in enumerate(lanes)}
        grid = np.full((len(lanes), len(rounds)), np.nan)
        lane_dev: dict = {}
        for p in pts:
            grid[lidx[p["lane"]], ridx[p["round"]]] = p["fill"]
            if isinstance(p.get("device"), int):
                lane_dev[lidx[p["lane"]]] = p["device"]
        figsize = (10, max(2.0, 0.25 * len(lanes) + 1.5))
        if lane_dev and len(lanes) > 1:
            fig, (ax, axd) = plt.subplots(
                1, 2, figsize=figsize, sharey=True,
                gridspec_kw={"width_ratios": [40, 1], "wspace": 0.02})
        else:
            fig, ax = plt.subplots(figsize=figsize)
            axd = None
        im = ax.imshow(grid, aspect="auto", origin="lower",
                       interpolation="nearest", vmin=0.0, vmax=1.0,
                       cmap="viridis",
                       extent=(rounds[0] - 0.5, rounds[-1] + 0.5,
                               -0.5, len(lanes) - 0.5))
        ax.set_xlabel("round")
        ax.set_ylabel("lane" if len(lanes) > 1 else "")
        if len(lanes) > 1:
            ax.set_yticks(range(len(lanes)))
            ax.set_yticklabels([str(la) for la in lanes], fontsize=6)
        else:
            ax.set_yticks([])
        ax.set_title(f"{(test or {}).get('name', '')} frontier fill "
                     f"(round x lane)")
        if axd is not None:
            # the per-device column strip: one colored cell per lane,
            # banded by mesh-device index — contiguous bands ARE the
            # NamedSharding layout, so a straggler row reads straight
            # to its chip
            devcol = np.full((len(lanes), 1), np.nan)
            for li, d in lane_dev.items():
                # cycle the 10-color map past device 9 (clamping
                # would merge devices 9..N into one band); the text
                # label below keeps the true index readable
                devcol[li, 0] = d % 10
            axd.imshow(devcol, aspect="auto", origin="lower",
                       interpolation="nearest", cmap="tab10",
                       vmin=-0.5, vmax=9.5,
                       extent=(-0.5, 0.5, -0.5, len(lanes) - 0.5))
            axd.set_xticks([])
            axd.set_title("dev", fontsize=7)
            for li, d in sorted(lane_dev.items()):
                axd.text(0, li, str(int(d) % 100), fontsize=5,
                         ha="center", va="center", color="white")
        for ev in (events or []):
            if not isinstance(ev, dict) \
                    or not isinstance(ev.get("round"), int) \
                    or not (rounds[0] <= ev["round"] <= rounds[-1]):
                continue
            is_rebucket = ev.get("event") == "rebucket"
            ax.axvline(ev["round"], lw=0.9, ls="--",
                       color="#d62728" if is_rebucket else "#999999",
                       alpha=0.8)
            label = (f"K{ev.get('from_K')}→{ev.get('to_K')}"
                     if is_rebucket else "steal")
            ax.annotate(label, (ev["round"], len(lanes) - 0.5),
                        fontsize=5, ha="left", va="top",
                        color="#ffffff", rotation=90)
        fig.colorbar(im, ax=ax, label="fill")
        if out_path:
            parent = os.path.dirname(out_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            fig.savefig(out_path, dpi=90, bbox_inches="tight")
            plt.close(fig)
            return out_path
        out = _save(fig, test, opts, filename)
        plt.close(fig)
        return out
    except Exception:  # noqa: BLE001
        log.warning("occupancy-heatmap rendering failed", exc_info=True)
        return None


def bench_trajectory_graph(report: dict, out_path: str) -> Optional[str]:
    """bench-trajectory.png: wall-time trajectory across BENCH rounds
    from a `bench.compute_regressions` report — the headline number
    per round on top, per-config walls below, with flagged
    regressions marked red. Path-based (the bench has no test map);
    never raises — a malformed report must not mask the bench's JSON
    line."""
    try:
        rounds = list(report.get("rounds") or [])
        cur = report.get("current")
        if cur and cur.get("value") is not None:
            rounds = rounds + [cur]
        rounds = [r for r in rounds if r.get("value") is not None]
        if len(rounds) < 2:
            return None
        plt = _plt()
        xs = [r.get("round") for r in rounds]
        fig, axes = plt.subplots(2, 1, figsize=(10, 7), sharex=True)

        ax = axes[0]
        ax.plot(xs, [r["value"] for r in rounds], marker="o", lw=1.5,
                color=Q_COLORS[0.95], label="headline wall_s")
        if (report.get("headline") or {}).get("regressed"):
            ax.plot([xs[-1]], [rounds[-1]["value"]], marker="o",
                    markersize=10, color=Q_COLORS[1.0], ls="none",
                    label="REGRESSED")
        for x, r in zip(xs, rounds):
            ax.annotate(str(r.get("platform") or ""), (x, r["value"]),
                        fontsize=6, textcoords="offset points",
                        xytext=(0, 6))
        ax.set_yscale("log")
        ax.set_ylabel("headline wall (s)")
        ax.set_title("BENCH trajectory")
        ax.legend(loc="upper right", fontsize=7)

        ax = axes[1]
        names = sorted({n for r in rounds
                        for n in (r.get("configs") or {})})
        flagged = set(report.get("regressions") or [])
        # occupancy regressions ride the same flag list as
        # "<name>:fill" (bench.compute_regressions) — the config's
        # wall line marks them too, so an emptied-lanes regression is
        # as loud as a wall-time one
        fill_flagged = {f.rsplit(":", 1)[0] for f in flagged
                        if f.endswith(":fill")}
        for i, name in enumerate(names):
            pts = [(x, (r.get("configs") or {}).get(name))
                   for x, r in zip(xs, rounds)]
            pts = [(x, v) for x, v in pts if v is not None]
            if not pts:
                continue
            px, py = zip(*pts)
            hot = name in flagged or name in fill_flagged
            color = Q_COLORS[1.0] if hot else f"C{i % 10}"
            suffix = (" (REGRESSED)" if name in flagged
                      else " (FILL REGRESSED)" if name in fill_flagged
                      else "")
            ax.plot(px, py, marker=MARKERS[i % len(MARKERS)],
                    markersize=4, lw=1, color=color,
                    label=name + suffix)
        ax.set_yscale("log")
        ax.set_xlabel("BENCH round")
        ax.set_ylabel("config wall (s)")
        ax.legend(loc="upper left", fontsize=6, ncol=2)

        srcs = report.get("sources") or {}
        if srcs:
            # where the rounds came from: the run ledger is primary,
            # the BENCH_r*.json glob backfills pre-ledger rounds
            fig.text(0.01, 0.01,
                     "rounds: " + ", ".join(
                         f"{n} from {s}" for s, n in sorted(
                             srcs.items()) if n),
                     fontsize=6, color="#666666")

        parent = os.path.dirname(out_path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        fig.savefig(out_path, dpi=90, bbox_inches="tight")
        plt.close(fig)
        return out_path
    except Exception:  # noqa: BLE001
        log.warning("bench-trajectory rendering failed", exc_info=True)
        return None


def rate_graph(test, history, opts=None) -> Optional[str]:
    """Completion rate (hz) in 10 s buckets by f and type
    (perf.clj:559-599)."""
    plt = _plt()
    comps = [op for op in History(history)
             if not op.is_invoke and isinstance(op.process, int)
             and op.time is not None and op.time >= 0]
    if not comps:
        return None
    fig, ax = plt.subplots(figsize=(10, 4.5))
    t_max = max(op.time for op in comps) / 1e9
    _shade_nemeses(ax, history, t_max)
    markers = _fmarker(op.f for op in comps)
    n_buckets = int(np.floor(t_max / DT_RATE)) + 1
    centers = np.arange(n_buckets) * DT_RATE + DT_RATE / 2
    for f in sorted({str(op.f) for op in comps}):
        for typ in TYPES:
            sel = [op.time / 1e9 for op in comps
                   if str(op.f) == f and op.type == typ]
            if not sel:
                continue
            counts = np.bincount(
                np.floor(np.asarray(sel) / DT_RATE).astype(np.int64),
                minlength=n_buckets)
            ax.plot(centers, counts / DT_RATE, marker=markers[f],
                    markersize=4, lw=1, color=TYPE_COLORS[typ],
                    label=f"{f} {typ}")
    ax.set_xlabel("Time (s)")
    ax.set_ylabel("Throughput (hz)")
    ax.set_title(f"{test.get('name', '')} rate")
    ax.legend(loc="upper right", fontsize=7)
    out = _save(fig, test, opts, "rate.png")
    plt.close(fig)
    return out
