"""Checkers: history -> results map analysis.

Capability parity with jepsen.checker (jepsen/src/jepsen/checker.clj):
the `Checker` protocol (`check(test, history, opts) -> {"valid?": ...}`,
checker.clj:52-67), `check_safe` (:74-85), `compose` (:87-99) with
`merge_valid` priority false > unknown > true (:29-50), and the built-in
checkers (stats :166, linearizable :185, queue :218, set :240,
total-queue :628, unique-ids :689, counter :737, set-full :294,
unhandled-exceptions :124).

The `linearizable` checker is where the TPU plane plugs in: exactly as the
reference gates knossos behind `:algorithm` (checker.clj:199-202), this
one gates the JAX WGL kernel behind `algorithm="tpu-wgl"`.
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from typing import Any, Callable, Iterable, Optional

from ..history import History, Op
from ..models import core as models
from ..util import (Multiset, bounded_pmap, integer_interval_set_str,
                    polysort_key)

UNKNOWN = "unknown"


def valid_priority(v) -> int:
    """false > unknown > true (checker.clj:29-35)."""
    if v is False:
        return 0
    if v == UNKNOWN or v is None:
        return 1
    return 2


def merge_valid(valids: Iterable) -> Any:
    """Merge a collection of :valid? values, preferring the worst
    (checker.clj:36-50). Empty collection -> True."""
    out = True
    for v in valids:
        if valid_priority(v) < valid_priority(out):
            out = v
    return out


class Checker:
    """Base checker protocol. Subclasses implement check()."""

    def check(self, test: dict, history: History, opts: Optional[dict] = None
              ) -> dict:
        raise NotImplementedError

    def __call__(self, test, history, opts=None):
        return self.check(test, history, opts or {})


class FnChecker(Checker):
    def __init__(self, fn: Callable, name: str = "fn-checker"):
        self.fn = fn
        self.name = name

    def check(self, test, history, opts=None):
        return self.fn(test, history, opts or {})


def check_safe(checker: Checker, test: dict, history: History,
               opts: Optional[dict] = None) -> dict:
    """Like check, but captures exceptions as {"valid?": "unknown"}
    (checker.clj:74-85). The swallowed exception is recorded as a
    structured fault event (fleet_faults series + live status), not
    just a traceback string on the result."""
    try:
        return checker.check(test, history, opts or {})
    except Exception as e:  # noqa: BLE001
        from .. import fleet as _fleet
        ev = _fleet.fault_event(
            e, stage=f"checker/{type(checker).__name__}")
        _fleet.record_fault(ev)
        return {"valid?": UNKNOWN, "error": traceback.format_exc(),
                "fault": {k: ev[k] for k in ("type", "error", "stage")}}


class Compose(Checker):
    """Map of name -> checker, evaluated in parallel; valid? is the merge
    (checker.clj:87-99)."""

    def __init__(self, checker_map: dict):
        self.checker_map = dict(checker_map)

    def check(self, test, history, opts=None):
        names = list(self.checker_map)
        results = bounded_pmap(
            lambda n: check_safe(self.checker_map[n], test, history, opts),
            names)
        out = dict(zip(names, results))
        return {"valid?": merge_valid(r.get("valid?") for r in results),
                **out}


def compose(checker_map: dict) -> Checker:
    return Compose(checker_map)


class ConcurrencyLimit(Checker):
    """Bound concurrent executions of a memory-hungry checker
    (checker.clj:101-116)."""

    def __init__(self, limit: int, checker: Checker):
        self.sem = threading.Semaphore(limit)
        self.checker = checker

    def check(self, test, history, opts=None):
        with self.sem:
            return self.checker.check(test, history, opts)


def concurrency_limit(limit: int, checker: Checker) -> Checker:
    return ConcurrencyLimit(limit, checker)


class UnbridledOptimism(Checker):
    """Everything is awesoooommmmme! (checker.clj:118-122)"""

    def check(self, test, history, opts=None):
        return {"valid?": True}


def unbridled_optimism() -> Checker:
    return UnbridledOptimism()


noop = unbridled_optimism


class UnhandledExceptions(Checker):
    """Aggregate crashed ops by exception class (checker.clj:124-151)."""

    def check(self, test, history, opts=None):
        groups: dict = {}
        for op in history:
            if op.is_info and (op.error is not None
                               or op.extra.get("exception") is not None):
                cls = op.extra.get("exception") or op.error
                key = cls if isinstance(cls, str) else str(type(cls).__name__ if
                                                           not isinstance(cls, (list, tuple, dict)) else cls)
                groups.setdefault(key, []).append(op)
        if not groups:
            return {"valid?": True}
        exes = sorted(
            ({"class": k, "count": len(v), "example": v[0].to_dict()}
             for k, v in groups.items()),
            key=lambda e: -e["count"])
        return {"valid?": True, "exceptions": exes}


def unhandled_exceptions() -> Checker:
    return UnhandledExceptions()


def _stats_for(ops: list) -> dict:
    ok = sum(1 for o in ops if o.is_ok)
    fail = sum(1 for o in ops if o.is_fail)
    info = sum(1 for o in ops if o.is_info)
    return {"valid?": ok > 0, "count": ok + fail + info,
            "ok-count": ok, "fail-count": fail, "info-count": info}


class Stats(Checker):
    """ok/fail/info counts overall and by :f; valid only if every :f saw an
    ok op (checker.clj:153-183)."""

    def check(self, test, history, opts=None):
        ops = [o for o in history
               if not o.is_invoke and o.process != "nemesis"]
        by_f: dict = {}
        for o in ops:
            by_f.setdefault(o.f, []).append(o)
        groups = {f: _stats_for(v) for f, v in sorted(
            by_f.items(), key=lambda kv: str(kv[0]))}
        out = _stats_for(ops)
        out["by-f"] = groups
        out["valid?"] = merge_valid(g["valid?"] for g in groups.values())
        return out


def stats() -> Checker:
    return Stats()


class Linearizable(Checker):
    """Linearizability via WGL search (checker.clj:185-216 gates knossos
    behind :algorithm; this gates the TPU kernel behind "tpu-wgl").

    algorithm:
      "wgl"      — pure-Python DFS with memoization (the oracle)
      "tpu-wgl"  — JAX lockstep-frontier search on TPU (the north star)
      "linear"   — JIT linearization with a memoized config cache
      "queue-poly" — polynomial FIFO-queue constraint peeling
      "competition" — race tpu-wgl and wgl CONCURRENTLY; the first
                   definitive verdict wins and cancels the loser
                   (result carries "engine"); FIFOQueue models route
                   to queue-poly first
    """

    def __init__(self, model: models.Model, algorithm: str = "competition",
                 time_limit: Optional[float] = None):
        self.model = model
        self.algorithm = algorithm
        self.time_limit = time_limit

    def check(self, test, history, opts=None):
        from .. import fleet as _fleet
        from .. import ledger as _ledger
        from ..trace import NULL_TRACER
        # a test-map tracer nests the whole analysis under ONE trace
        # alongside client spans (core.py exports both to trace.jsonl):
        # the root span here parents the engine phase spans (encode /
        # compile / device-round / host-poll / oracle-race / enrich)
        tracer = (test or {}).get("tracer") or NULL_TRACER
        status = _fleet.get_default()
        if status.enabled and tracer.sampled:
            # live status follows the phase spans (fleet.RunStatus)
            tracer.add_listener(status.on_span)
        status.phase(f"check linearizable ({self.algorithm})")
        t0 = time.monotonic()
        res = None
        try:
            with tracer.span("check linearizable",
                             attrs={"algorithm": self.algorithm}):
                res = self._check(test, history, opts, tracer)
            return res
        finally:
            status.phase("analyze")
            if res is not None and (test or {}).get("name") \
                    and "history_key" not in (opts or {}):
                # run-ledger accounting (ledger.py): one record per
                # TOP-LEVEL analysis — no-op unless a ledger is
                # installed. Per-key sub-checks (opts carries
                # history_key under the independent fan-out) and
                # anonymous internal calls (bench configs record
                # their own kind="bench" entry) are skipped: they
                # would double-count device-seconds in aggregate()
                # and pollute the (name, platform) regression groups
                # with per-key walls.
                _ledger.record_result(
                    "checker", (test or {}).get("name"),
                    res, wall_s=time.monotonic() - t0,
                    model=type(self.model).__name__,
                    extra={"algorithm": self.algorithm})

    def _check(self, test, history, opts, tracer):
        from ..analysis import history_lint
        from ..history import strip_nemesis
        from ..ops import wgl_ref
        h = strip_nemesis(history)
        algo = self.algorithm
        # Pre-search well-formedness gate (doc/STATIC_ANALYSIS.md): a
        # malformed history (double-invoke race, unmatched completion,
        # clock regression, ...) silently corrupts the encoded tensors
        # — diagnose it here instead of burning device time on a
        # garbage verdict.
        with tracer.span("history-lint", attrs={"ops": len(h)}):
            bad = history_lint.gate(h, where="checker.linearizable")
        if bad is not None:
            bad["algorithm"] = algo
            return bad
        res: dict
        if algo in ("competition", "queue-poly") and isinstance(
                self.model, models.FIFOQueue):
            # FIFO queues defeat state-space search (ours and JVM
            # knossos alike); the polynomial checker decides 100k-op
            # histories in milliseconds when the history qualifies
            # (distinct values, known dequeue returns)
            from ..ops import queuecheck
            try:
                res = queuecheck.check(h)
                res["algorithm"] = algo
                return res
            except queuecheck.QueueUnsupported as e:
                if algo == "queue-poly":
                    res = {"valid?": UNKNOWN, "algorithm": algo,
                           "cause": f"queue-poly: {e}"}
                    return res
        elif algo == "queue-poly":
            return {"valid?": UNKNOWN, "algorithm": algo,
                    "cause": "queue-poly requires a FIFOQueue model, "
                             f"got {type(self.model).__name__}"}
        pf_bad = None
        if algo in ("tpu-wgl", "competition"):
            # Admission preflight (analysis/preflight): enumerate the
            # device plan statically and reject a request the device
            # engine could only discover infeasible by OOMing —
            # before any encode table, backend compile, or device
            # byte. Sits AFTER the queue fast-path so a 100k-op FIFO
            # history decided by the polynomial checker never pays the
            # probe. Feasible/degrade plans pass through untouched
            # (the verdict + plan land in the preflight series and,
            # for top-level analyses, a kind="preflight" ledger
            # record). Only "tpu-wgl" (device-only) rejects outright:
            # competition races device vs host, and an infeasible
            # DEVICE plan merely scratches the device racer — the
            # host oracle (no HBM budget) still decides the history.
            from ..analysis import preflight
            with tracer.span("preflight", attrs={"ops": len(h)}):
                pf_bad = preflight.gate_wgl(
                    self.model, h, where="checker.linearizable",
                    ledger_name=((test or {}).get("name")
                                 if "history_key" not in (opts or {})
                                 else None))
            if pf_bad is not None and algo != "competition":
                pf_bad["algorithm"] = algo
                return pf_bad
        if algo == "wgl":
            res = wgl_ref.check(self.model, h, time_limit=self.time_limit)
        elif algo == "linear":
            from ..ops import jitlin
            res = jitlin.check(self.model, h,
                               time_limit=self.time_limit)
        elif algo == "tpu-wgl":
            from ..ops import wgl as wgl_tpu
            res = wgl_tpu.check_with_diagnostics(
                self.model, h, time_limit=self.time_limit,
                tracer=tracer)
        elif algo == "competition":
            if pf_bad is not None:
                # device racer statically scratched: host-only heat
                res = wgl_ref.check(self.model, h,
                                    time_limit=self.time_limit)
                res["device_cause"] = "preflight"
                res["preflight"] = pf_bad.get("preflight")
            else:
                res = _race_competition(self.model, h, self.time_limit,
                                        tracer=tracer)
        else:
            raise ValueError(f"unknown linearizability algorithm {algo!r}")
        # Truncate expensive diagnostics (checker.clj:213-216).
        for k in ("final_paths", "configs"):
            if k in res and isinstance(res[k], list):
                res[k] = res[k][:10]
        res["algorithm"] = algo
        if res.get("valid?") is False:
            # render the counterexample (checker.clj:205-212)
            from . import linear_report
            p = linear_report.render_analysis(test, h, res, opts)
            if p:
                res["counterexample-svg"] = p
        if (res.get("telemetry") or {}).get("chunks") \
                and (test or {}).get("name"):
            # telemetry-enabled device runs get a search-progress
            # panel (with the per-round fill overlay) next to the
            # latency/rate plots, plus the occupancy heatmap
            from . import plots
            occ = res.get("occupancy") or {}
            p = plots.search_progress_graph(
                test, res["telemetry"]["chunks"], opts,
                rounds=occ.get("rounds"))
            if p:
                res["search-progress-png"] = p
            if occ.get("rounds"):
                from .. import occupancy as occupancy_mod
                hp = plots.occupancy_heatmap(
                    test, occupancy_mod.heatmap_points(occ["rounds"]),
                    opts)
                if hp:
                    res["occupancy-heatmap-png"] = hp
        return res


def _race_competition(model, h, time_limit, device=None,
                      max_configs=None, enc=None, tracer=None):
    """knossos.competition semantics: run the device search and the
    host oracle CONCURRENTLY; the first definitive verdict wins and
    cancels the loser (serial device-then-oracle left pathological
    cases — e.g. wide-window histories trivial for the oracle's DFS —
    paying the full device cost first).

    `device` pins the device-engine thread (jax.default_device is
    thread-local, so a caller's pin would not reach it otherwise);
    `max_configs`/`enc` pass through to the device search. `tracer`
    emits an "oracle-race" phase span around the race, and each
    engine thread's spans adopt it as an explicit parent
    (trace.Tracer.span nesting is thread-local)."""
    import importlib.util
    import queue
    import threading

    from ..ops import wgl_ref
    from ..trace import NULL_TRACER
    tracer = tracer or NULL_TRACER

    if importlib.util.find_spec("jax") is None:
        # no accelerator stack at all: the quiet, expected path — the
        # oracle decides alone, no doomed thread, no warning spam
        # (ops.wgl itself imports jax lazily, so probing the module
        # spec is the only reliable availability check)
        return wgl_ref.check(model, h, time_limit=time_limit)

    from ..ops import wgl as wgl_tpu
    from ..util import safe_backend

    def run_device(budget, stop=None):
        """The device engine under the caller's device pin — the single
        place the pin/kwargs policy lives (raced AND serial paths)."""
        import contextlib

        import jax
        kw = {}
        if max_configs is not None:
            kw["max_configs"] = max_configs
        pin = (jax.default_device(device) if device is not None
               else contextlib.nullcontext())
        with pin:
            return wgl_tpu.check(model, h, time_limit=budget,
                                 stop=stop, enc=enc, tracer=tracer,
                                 **kw)

    def enrich_spare(r, t_start):
        """Post-verdict counterexample enrichment riding only the
        REMAINING budget — a fixed slice could overrun time_limit
        after the engine already spent most of it. Shared by the
        serial ladder and the threaded race."""
        spare = (time_limit - (time.monotonic() - t_start)
                 if time_limit is not None else 10.0)
        if spare > 0.1:
            r = wgl_tpu.enrich_diagnostics(model, h, r,
                                           time_limit=min(10.0, spare),
                                           tracer=tracer)
        return r

    if safe_backend() == "cpu" and time_limit is not None:
        # On a CPU backend both engines contend for the same cores
        # (and the pure-Python oracle for the GIL), so racing buys
        # nothing — the same policy batched.py applies to its per-key
        # race. Run a serial LADDER instead:
        #   1. oracle on a short slice — near-serial shapes (wide
        #      long tails) decide in milliseconds, and paying kernel
        #      compile for them would be pure waste;
        #   2. device on most of the remainder — the packed wide-
        #      window kernel (wgln.py) decides adversarial shapes the
        #      oracle cannot (2.2M configs in ~50 s cold on cpu), and
        #      the narrow fast path wins by orders of magnitude;
        #   3. oracle on whatever is left, in case the device came up
        #      unknown with budget remaining.
        with tracer.span("oracle-race",
                         attrs={"mode": "serial-ladder"}):
            t0 = time.monotonic()
            slice1 = min(5.0, time_limit / 6)
            r = wgl_ref.check(model, h, time_limit=slice1)
            if r.get("valid?") != UNKNOWN:
                r["engine"] = "oracle"
                return r
            left = max(1.0, time_limit - (time.monotonic() - t0))
            try:
                r = run_device(left * 0.75)
            except Exception as e:  # noqa: BLE001 — encode/step failures
                from .. import fleet as _fleet
                logging.getLogger(__name__).warning(
                    "device engine failed in serial competition",
                    exc_info=True)
                _fleet.record_fault(_fleet.fault_event(
                    e, stage="competition/serial-device"))
                r = {"valid?": UNKNOWN, "cause": "engine-error"}
            if r.get("valid?") != UNKNOWN:
                r["engine"] = "device"
                return enrich_spare(r, t0)
            left = max(1.0, time_limit - (time.monotonic() - t0))
            r = wgl_ref.check(model, h, time_limit=left)
            if r.get("valid?") != UNKNOWN:
                r["engine"] = "oracle"
            return r

    winner = threading.Event()
    outcomes: queue.Queue = queue.Queue()
    race_ctx: dict = {}  # the oracle-race span's context, set below

    def arm(name, fn):
        def run():
            try:
                # engine spans adopt the race span as an explicit
                # parent (span nesting is thread-local otherwise)
                with tracer.span(f"engine {name}",
                                 parent=race_ctx.get("ctx")):
                    r = fn()
            except Exception as e:  # noqa: BLE001 — device init failure etc.
                from .. import fleet as _fleet
                logging.getLogger(__name__).warning(
                    "%s engine failed in competition", name,
                    exc_info=True)
                _fleet.record_fault(_fleet.fault_event(
                    e, stage=f"competition/{name}"))
                r = {"valid?": UNKNOWN, "cause": "engine-error"}
            outcomes.put((name, r))
            if r.get("valid?") != UNKNOWN:
                winner.set()
        # NON-daemon: the loser self-cancels at its next stop-poll
        # (one chunk, bounded seconds) and interpreter shutdown joins
        # it cleanly — a daemon thread killed mid-XLA-call aborts the
        # whole process ("FATAL: exception not rethrown")
        return threading.Thread(target=run, name=f"wgl-{name}")

    def oracle():
        return wgl_ref.check(model, h, time_limit=time_limit,
                             stop=winner.is_set)

    def device_cpu():
        # Platform-aware lane (round-4 VERDICT #3): with an accelerator
        # adopted, the SAME kernel on a host core wins small and
        # near-serial shapes (latency-bound rounds, ~9x measured on
        # the 10k headline) — so the cpu build races too, and the
        # winning engine names its platform.
        #
        # Init caveat (measured live): jax cannot bring up the cpu
        # backend alone — backends() initializes every plugin, so a
        # wedged accelerator runtime hangs `local_devices(backend=
        # "cpu")` too. When the default backend isn't up yet this lane
        # waits only BRIEFLY (the pure-Python oracle lane covers the
        # wedged-runtime case) and bows out.
        from ..util import backend_ready
        wait = min(10.0, time_limit / 4) if time_limit else 10.0
        if not backend_ready(wait):
            return {"valid?": UNKNOWN,
                    "cause": "backend-init-timeout (cpu lane; "
                             "pure-host lanes cover this case)"}
        kw = {}
        if max_configs is not None:
            kw["max_configs"] = max_configs
        return wgl_tpu.check(model, h, time_limit=time_limit,
                             stop=winner.is_set, enc=enc,
                             platform="cpu", tracer=tracer, **kw)

    def device_engine():
        # The engine's FIRST device call would trigger backend init,
        # which on a wedged accelerator runtime hangs forever rather
        # than raising — and a hung non-daemon engine thread blocks
        # interpreter exit even after the oracle's verdict (observed
        # live on a CLI run). So init waits behind the shared daemon
        # probe with a bounded timeout; on timeout this engine bows
        # out and the oracle decides alone.
        from ..util import backend_failed, backend_ready
        init_budget = min(60.0, time_limit) if time_limit else 60.0
        deadline = time.monotonic() + init_budget
        while not backend_ready(0.25):
            if backend_failed():  # init raised: don't spin the poll
                return {"valid?": UNKNOWN,
                        "cause": "backend-init-error"}
            if winner.is_set():  # oracle already decided: stand down
                return {"valid?": UNKNOWN, "cause": "cancelled"}
            if time.monotonic() > deadline:
                return {"valid?": UNKNOWN,
                        "cause": "backend-init-timeout"}
        # bare verdict — diagnostics are enriched AFTER the race so a
        # device False publishes (and cancels the oracle) immediately
        return run_device(time_limit, stop=winner.is_set)

    t_race0 = time.monotonic()
    threads = [arm("device", device_engine), arm("oracle", oracle)]
    if safe_backend() not in (None, "cpu"):
        # only when an accelerator is KNOWN to hold the default
        # backend: on an uninitialized or cpu default the "device"
        # lane already IS the cpu build, and a second identical
        # kernel would just contend for the same cores
        threads.append(arm("device@cpu", device_cpu))
    with tracer.span("oracle-race",
                     attrs={"engines": [t.name for t in threads]}):
        race_ctx["ctx"] = tracer.context()
        for t in threads:
            t.start()
        res: dict = {}
        unknowns: dict = {}
        for _ in range(len(threads)):  # take FIRST definitive verdict
            name, r = outcomes.get()
            if r.get("valid?") != UNKNOWN:
                r["engine"] = name
                res = r
                break
            unknowns[name] = r
        else:
            # all unknown: prefer the oracle's cause (it has
            # diagnostics)
            res = unknowns.get("oracle") or unknowns.get("device") \
                or unknowns.get("device@cpu") or {"valid?": UNKNOWN}
        # Reap the loser without gating the fast win (it self-cancels
        # at its next stop poll; an uninterruptible first compile can
        # outlive any wait) — flag a still-draining loser so
        # downstream timings are explicable.
        for t in threads:
            t.join(timeout=0.1)
            if t.is_alive():
                res["loser_draining"] = t.name
    if str(res.get("engine", "")).startswith("device"):
        res = enrich_spare(res, t_race0)
    return res


def linearizable(model=None, algorithm: str = "competition",
                 time_limit: Optional[float] = None) -> Checker:
    if model is None:
        model = models.cas_register()
    return Linearizable(model, algorithm, time_limit)


class QueueChecker(Checker):
    """Every dequeue must come from somewhere: assume every non-failing
    enqueue succeeded and only OK dequeues happened, then fold the model
    over that sequence (checker.clj:218-238). Use with an unordered queue
    model."""

    def __init__(self, model: models.Model):
        self.model = model

    def check(self, test, history, opts=None):
        m = self.model
        for op in history:
            take = (op.is_invoke if op.f == "enqueue"
                    else op.is_ok if op.f == "dequeue" else False)
            if take:
                m = m.step(op)
                if models.is_inconsistent(m):
                    return {"valid?": False, "error": m.msg}
        return {"valid?": True, "final-queue": m}


def queue(model=None) -> Checker:
    if model is None:
        model = models.unordered_queue()
    return QueueChecker(model)


class SetChecker(Checker):
    """Adds followed by a final read: every acknowledged add must be
    present; nothing unexpected may appear (checker.clj:240-291)."""

    def check(self, test, history, opts=None):
        attempts = {o.value for o in history if o.is_invoke and o.f == "add"}
        adds = {o.value for o in history if o.is_ok and o.f == "add"}
        final_read = None
        for o in history:
            if o.is_ok and o.f == "read":
                final_read = o.value
        if final_read is None:
            return {"valid?": UNKNOWN, "error": "set was never read"}
        final = set(final_read)
        ok = final & attempts
        unexpected = final - attempts
        lost = adds - final
        recovered = ok - adds
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": len(attempts),
            "acknowledged-count": len(adds),
            "ok-count": len(ok),
            "lost-count": len(lost),
            "recovered-count": len(recovered),
            "unexpected-count": len(unexpected),
            "ok": integer_interval_set_str(ok),
            "lost": integer_interval_set_str(lost),
            "unexpected": integer_interval_set_str(unexpected),
            "recovered": integer_interval_set_str(recovered),
        }


def set_checker() -> Checker:
    return SetChecker()


def expand_queue_drain_ops(history: History) -> History:
    """Expand :drain ops (value = list of drained elements) into dequeue
    invoke/ok pairs (checker.clj:594-627). An INCOMPLETE drain (:info
    carrying the elements drained before the failure) expands the same
    way — those elements were acknowledged off the server and must be
    accounted — but its incompleteness taints any "lost" verdict
    (TotalQueue downgrades lost -> unknown when a drain didn't
    finish). A crashed drain with no element list is unanswerable and
    still raises."""
    out = History()
    for op in history:
        if op.f != "drain":
            out.append(op)
        elif op.is_invoke or op.is_fail:
            continue
        elif op.is_ok or (op.is_info and isinstance(op.value, list)):
            for el in (op.value or []):
                out.append(op.with_(type="invoke", f="dequeue", value=None))
                out.append(op.with_(type="ok", f="dequeue", value=el))
        else:
            raise ValueError(f"can't handle crashed drain op {op!r}")
    return out


class TotalQueue(Checker):
    """What goes in must come out (multiset accounting over
    enqueues/dequeues, checker.clj:628-687)."""

    def check(self, test, history, opts=None):
        # an info drain means the queue was never provably emptied:
        # leftovers are indistinguishable from losses
        incomplete_drain = any(o.f == "drain" and o.is_info
                               and isinstance(o.value, list)
                               for o in history)
        history = expand_queue_drain_ops(history)
        attempts = Multiset(o.value for o in history
                            if o.is_invoke and o.f == "enqueue")
        enqueues = Multiset(o.value for o in history
                            if o.is_ok and o.f == "enqueue")
        dequeues = Multiset(o.value for o in history
                            if o.is_ok and o.f == "dequeue")
        ok = dequeues.intersect(attempts)
        unexpected = Multiset(x for x in dequeues if x not in attempts)
        duplicated = dequeues.minus(attempts).minus(unexpected)
        lost = enqueues.minus(dequeues)
        recovered = ok.minus(enqueues)
        if len(unexpected):
            valid: Any = False
        elif len(lost):
            # undrained-but-present is indistinguishable from lost
            # when a drain never finished
            valid = UNKNOWN if incomplete_drain else False
        else:
            valid = True
        return {
            "valid?": valid,
            "incomplete-drain": incomplete_drain,
            "attempt-count": len(attempts),
            "acknowledged-count": len(enqueues),
            "ok-count": len(ok),
            "unexpected-count": len(unexpected),
            "duplicated-count": len(duplicated),
            "lost-count": len(lost),
            "recovered-count": len(recovered),
            "lost": lost.to_sorted_list(),
            "unexpected": unexpected.to_sorted_list(),
            "duplicated": duplicated.to_sorted_list(),
            "recovered": recovered.to_sorted_list(),
        }


def total_queue() -> Checker:
    return TotalQueue()


class UniqueIds(Checker):
    """A unique-id generator must emit unique ids (checker.clj:689-734)."""

    def check(self, test, history, opts=None):
        attempted = sum(1 for o in history
                        if o.is_invoke and o.f == "generate")
        acks = [o.value for o in history if o.is_ok and o.f == "generate"]
        counts: dict = {}
        for v in acks:
            counts[v] = counts.get(v, 0) + 1
        dups = {k: c for k, c in counts.items() if c > 1}
        rng = [min(acks), max(acks)] if acks else [None, None]
        dup_sample = dict(sorted(dups.items(), key=lambda kv: -kv[1])[:48])
        return {
            "valid?": not dups,
            "attempted-count": attempted,
            "acknowledged-count": len(acks),
            "duplicated-count": len(dups),
            "duplicated": dup_sample,
            "range": rng,
        }


def unique_ids() -> Checker:
    return UniqueIds()


class Counter(Checker):
    """A monotonically increasing counter: each read must land between the
    sum of acknowledged adds (lower) and the sum of attempted adds (upper)
    at that moment (checker.clj:737-795)."""

    def check(self, test, history, opts=None):
        # Invocations of ops that completed :fail never happened — drop both
        # halves (the reference runs history/complete, which marks them,
        # then removes them: checker.clj:747-751).
        failed = set()
        for inv, c in history.pairs():
            if c is not None and c.is_fail:
                failed.add(id(inv))
                failed.add(id(c))
        lower = 0
        upper = 0
        pending: dict = {}  # process -> lower bound captured at invoke
        reads: list = []
        for op in history:
            if id(op) in failed or op.process == "nemesis":
                continue
            if op.f == "read":
                if op.is_invoke:
                    pending[op.process] = lower
                elif op.is_ok:
                    lo = pending.pop(op.process, None)
                    if lo is not None:
                        reads.append([lo, op.value, upper])
            elif op.f == "add":
                if op.is_invoke:
                    if not isinstance(op.value, (int, float)) or op.value < 0:
                        raise ValueError(
                            "counter checker assumes non-negative numeric "
                            f"adds, got {op.value!r}")
                    upper += op.value
                elif op.is_ok:
                    lower += op.value
        errors = [r for r in reads
                  if not (r[0] <= r[1] <= r[2])]
        return {"valid?": not errors, "reads": reads, "errors": errors}


def counter() -> Checker:
    return Counter()


# -- set-full (checker.clj:294-592) -----------------------------------------

class _SetFullElement:
    """Per-element timeline state (checker.clj:295-338): when the
    element became known (add completion or first observing read,
    whichever first), the latest read invocation that observed it, and
    the latest read invocation that missed it."""

    __slots__ = ("element", "known", "last_present", "last_absent")

    def __init__(self, element):
        self.element = element
        self.known = None         # completion op that proved existence
        self.last_present = None  # latest read INVOCATION observing it
        self.last_absent = None   # latest read INVOCATION missing it

    def add_ok(self, op):
        if self.known is None:
            self.known = op

    def read_present(self, inv, op):
        if self.known is None:
            self.known = op
        if self.last_present is None or \
                self.last_present.index < inv.index:
            self.last_present = inv

    def read_absent(self, inv, op):
        if self.last_absent is None or \
                self.last_absent.index < inv.index:
            self.last_absent = inv

    def results(self) -> dict:
        """Outcome classification (checker.clj:345-404). stable = some
        read invoked after the last absence observed the element; lost =
        known, then a read invoked after both the add and the last
        presence missed it (an absent read *concurrent* with the add is
        never-read, not lost)."""
        absent_idx = self.last_absent.index if self.last_absent else -1
        present_idx = self.last_present.index if self.last_present else -1
        stable = self.last_present is not None and \
            absent_idx < present_idx
        lost = bool(self.known is not None and self.last_absent is not None
                    and present_idx < absent_idx
                    and self.known.index < absent_idx)
        known_time = self.known.time if self.known else None
        stable_latency = lost_latency = None
        if stable:
            t = self.last_absent.time + 1 if self.last_absent else 0
            stable_latency = max(0, t - known_time) // 1_000_000
        if lost:
            t = self.last_present.time + 1 if self.last_present else 0
            lost_latency = max(0, t - known_time) // 1_000_000
        return {
            "element": self.element,
            "outcome": ("stable" if stable
                        else "lost" if lost else "never-read"),
            "stable-latency": stable_latency,
            "lost-latency": lost_latency,
            "known": self.known,
            "last-absent": self.last_absent,
        }


def frequency_distribution(points, values) -> Optional[dict]:
    """{quantile: value} at the given 0-1 points (checker.clj:406-420)."""
    s = sorted(values)
    if not s:
        return None
    n = len(s)
    return {p: s[min(n - 1, int(n * p))] for p in points}


class SetFull(Checker):
    """Per-element stable/lost/never-read analysis with latency
    quantiles (checker.clj:462-592). With linearizable=True, stale
    elements (observed only after a delay) are failures too."""

    def __init__(self, linearizable: bool = False):
        self.linearizable = linearizable

    def check(self, test, history, opts=None):
        elements: dict = {}
        reads: dict = {}  # process -> read invocation
        dups: dict = {}   # element -> max multiplicity > 1 in one read
        for op in history:
            # only numeric client processes (checker.clj:545)
            if not isinstance(op.process, int) or \
                    isinstance(op.process, bool):
                continue
            if op.f == "add":
                if op.is_invoke:
                    elements.setdefault(op.value,
                                        _SetFullElement(op.value))
                elif op.is_ok and op.value in elements:
                    elements[op.value].add_ok(op)
            elif op.f == "read":
                if op.is_invoke:
                    reads[op.process] = op
                elif op.is_fail:
                    reads.pop(op.process, None)
                elif op.is_ok:
                    inv = reads.pop(op.process, op)
                    seen: dict = {}
                    for v in (op.value or []):
                        seen[v] = seen.get(v, 0) + 1
                    for v, n in seen.items():
                        if n > 1:
                            dups[v] = max(dups.get(v, 0), n)
                    vs = set(seen)
                    for el, state in elements.items():
                        if el in vs:
                            state.read_present(inv, op)
                        else:
                            state.read_absent(inv, op)
        rs = [elements[k].results() for k in sorted(elements,
                                                    key=polysort_key)]
        outcomes: dict = {}
        for r in rs:
            outcomes.setdefault(r["outcome"], []).append(r)
        stable = outcomes.get("stable", [])
        lost = outcomes.get("lost", [])
        never_read = outcomes.get("never-read", [])
        stale = [r for r in stable if r["stable-latency"] > 0]
        worst_stale = sorted(stale, key=lambda r: -r["stable-latency"])[:8]
        if lost:
            valid = False
        elif not stable:
            valid = UNKNOWN
        elif self.linearizable and stale:
            valid = False
        else:
            valid = True
        out = {
            "valid?": (valid if not dups else False),
            "attempt-count": len(rs),
            "stable-count": len(stable),
            "lost-count": len(lost),
            "lost": sorted((r["element"] for r in lost), key=polysort_key),
            "never-read-count": len(never_read),
            "never-read": sorted((r["element"] for r in never_read),
                                 key=polysort_key),
            "stale-count": len(stale),
            "stale": sorted((r["element"] for r in stale), key=polysort_key),
            "worst-stale": worst_stale,
            "duplicated-count": len(dups),
            "duplicated": dups,
        }
        points = (0, 0.5, 0.95, 0.99, 1)
        sl = frequency_distribution(
            points, [r["stable-latency"] for r in rs
                     if r["stable-latency"] is not None])
        if sl is not None:
            out["stable-latencies"] = sl
        ll = frequency_distribution(
            points, [r["lost-latency"] for r in rs
                     if r["lost-latency"] is not None])
        if ll is not None:
            out["lost-latencies"] = ll
        return out


def set_full(linearizable: bool = False) -> Checker:
    return SetFull(linearizable)


# -- log-file-pattern (checker.clj:839-881) ---------------------------------

class LogFilePattern(Checker):
    """Greps each node's downloaded log file in the store directory for
    a pattern; matches mean invalid."""

    def __init__(self, pattern: str, filename: str):
        import re as _re
        self.pattern = _re.compile(pattern)
        self.filename = filename

    def check(self, test, history, opts=None):
        import os as _os
        from .. import store as _store
        matches = []
        for node in (test.get("nodes") or []):
            p = _store.path(test, node, self.filename)
            if not _os.path.exists(p):
                continue
            try:
                with open(p, errors="replace") as fh:
                    for line in fh:
                        if self.pattern.search(line):
                            matches.append({"node": node,
                                            "line": line.rstrip("\n")})
            except OSError as e:
                return {"valid?": UNKNOWN,
                        "error": f"{type(e).__name__}: {e}"}
        return {"valid?": not matches,
                "count": len(matches),
                "matches": matches}


def log_file_pattern(pattern: str, filename: str) -> Checker:
    return LogFilePattern(pattern, filename)


# -- plot checkers (checker.clj:797-837) ------------------------------------

class LatencyGraph(Checker):
    """latency-raw.png + latency-quantiles.png (checker.clj:797-809)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}

    def check(self, test, history, opts=None):
        from . import plots as perf_mod
        o = {**self.opts, **(opts or {})}
        pts = perf_mod.latency_points(history)  # pair history once
        perf_mod.point_graph(test, history, o, pts=pts)
        perf_mod.quantiles_graph(test, history, o, pts=pts)
        return {"valid?": True}


def latency_graph(opts: Optional[dict] = None) -> Checker:
    return LatencyGraph(opts)


class RateGraph(Checker):
    """rate.png (checker.clj:811-821)."""

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}

    def check(self, test, history, opts=None):
        from . import plots as perf_mod
        perf_mod.rate_graph(test, history, {**self.opts, **(opts or {})})
        return {"valid?": True}


def rate_graph(opts: Optional[dict] = None) -> Checker:
    return RateGraph(opts)


def perf(opts: Optional[dict] = None) -> Checker:
    """Latency + rate graphs composed (checker.clj:823-831)."""
    return compose({"latency-graph": latency_graph(opts),
                    "rate-graph": rate_graph(opts)})


class ClockPlot(Checker):
    """clock-skew.png (checker.clj:831-837)."""

    def check(self, test, history, opts=None):
        from . import clock as clock_mod
        clock_mod.plot(test, history, opts or {})
        return {"valid?": True}


def clock_plot() -> Checker:
    return ClockPlot()
