"""Client protocol: applying operations to the system under test.

Capability parity with jepsen.client
(`jepsen/src/jepsen/client.clj:9-27`): a Client has a five-phase
lifecycle — open (connect to one node), setup (initialize DB state),
invoke (apply one op, returning its completion), teardown, close. The
optional `Reusable` marker (client.clj:29-43) lets a crashed client be
reused by a fresh process instead of being reopened; the `Validate`
wrapper (client.clj:64-109) enforces the completion invariants the rest
of the framework relies on (same process/f, completion type ok|info|fail).
"""

from __future__ import annotations

from typing import Any, Optional


class Client:
    """Base client. Subclasses override what they need; invoke! is
    mandatory."""

    def open(self, test: dict, node: str) -> "Client":
        """Connect to `node`; returns a client ready for invoke. Must not
        alter logical test state."""
        return self

    def close(self, test: dict) -> None:
        return None

    def setup(self, test: dict) -> None:
        return None

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        return None


class Reusable:
    """Mixin marker: crashed clients may be reused by the replacement
    process (client.clj:29-34)."""


def is_reusable(client, test) -> bool:
    return isinstance(client, Reusable)


class Noop(Client):
    """Does nothing; every op completes :ok (client.clj:46-53)."""

    def invoke(self, test, op):
        return {**op, "type": "ok"}


noop = Noop


class InvalidCompletion(Exception):
    def __init__(self, op, op2, problems):
        super().__init__(
            f"Client completed {op!r} with invalid completion {op2!r}: "
            + "; ".join(problems))
        self.op = op
        self.op2 = op2
        self.problems = problems


class Validate(Client):
    """Wraps a client, validating completions (client.clj:64-109)."""

    def __init__(self, client: Client):
        self.client = client

    def open(self, test, node):
        res = self.client.open(test, node)
        if not isinstance(res, Client):
            raise TypeError(
                f"expected open to return a Client, got {res!r}")
        return Validate(res)

    def close(self, test):
        self.client.close(test)

    def setup(self, test):
        self.client.setup(test)

    def invoke(self, test, op):
        op2 = self.client.invoke(test, op)
        problems = []
        if not isinstance(op2, dict):
            problems.append("should be a dict")
        else:
            if op2.get("type") not in ("ok", "info", "fail"):
                problems.append("type should be ok, info, or fail")
            if op2.get("process") != op.get("process"):
                problems.append("process should be the same")
            if op2.get("f") != op.get("f"):
                problems.append("f should be the same")
        if problems:
            raise InvalidCompletion(op, op2, problems)
        return op2

    def teardown(self, test):
        self.client.teardown(test)


def is_validate_reusable(client, test) -> bool:
    """Reusability of a possibly-wrapped client: Validate and any other
    wrapper exposing its inner client as `.client` (e.g.
    trace.TracedClient) are unwrapped transitively."""
    seen = set()
    c = client
    while id(c) not in seen and isinstance(getattr(c, "client", None),
                                           Client):
        seen.add(id(c))
        c = c.client
    return is_reusable(c, test)


def validate(client: Client) -> Validate:
    return Validate(client)
