"""Clock-skew faults (parity with jepsen.nemesis.time,
`jepsen/src/jepsen/nemesis/time.clj`): uploads the C++ clock tools from
`native/clock/` to each node, compiles them there (time.clj:20-61), and
exposes a nemesis handling reset/strobe/bump/check-offsets ops, each
annotated with per-node clock offsets (time.clj:98-146). Generators
mirror the reference's randomized magnitudes (time.clj:148-205: bumps
±2^2..2^18 ms, strobes delta 4 ms–262 s / period 1 ms–1 s / ≤32 s).
"""

from __future__ import annotations

import logging
import os
import time as _time
from typing import Callable, Optional

from .. import control as c
from ..control import nodeutil as cu
from . import RNG, Nemesis

log = logging.getLogger("jepsen_tpu.nemesis.time")

DIR = "/opt/jepsen"
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "clock")

_TOOLS = {"bump-time": "bump_time.cc", "strobe-time": "strobe_time.cc"}


def compile_tool(bin_name: str) -> str:
    """Upload + compile one tool on the bound node unless present
    (time.clj:20-49)."""
    with c.su():
        if not cu.file_exists(f"{DIR}/{bin_name}"):
            log.info("Compiling %s", bin_name)
            c.exec_("mkdir", "-p", DIR)
            c.exec_("chmod", "a+rwx", DIR)
            src = os.path.join(_SRC_DIR, _TOOLS[bin_name])
            c.upload(src, f"{DIR}/{bin_name}.cc")
            with c.cd(DIR):
                c.exec_("g++", "-O2", "-o", bin_name, f"{bin_name}.cc")
    return bin_name


def install() -> None:
    """Install the clock tools, adding a compiler if needed
    (time.clj:52-61)."""
    try:
        for b in _TOOLS:
            compile_tool(b)
    except Exception:  # noqa: BLE001
        from ..os_setup import CentOS, Debian
        try:
            Debian().install(["build-essential", "g++"])
        except Exception:  # noqa: BLE001
            CentOS().install(["gcc-c++"])
        for b in _TOOLS:
            compile_tool(b)


def parse_time(s: str) -> float:
    return float(s.strip())


def clock_offset(remote_time: float) -> float:
    """Remote clock minus control-node clock, seconds (time.clj:69-74)."""
    return remote_time - _time.time()


def current_offset() -> float:
    """Offset of the bound node's clock, in seconds (time.clj:76-79)."""
    return clock_offset(parse_time(c.exec_("date", "+%s.%N")))


def reset_time() -> None:
    """NTP-reset the bound node's clock (time.clj:81-85)."""
    with c.su():
        c.exec_("ntpdate", "-p", "1", "-b", "time.google.com")


def reset_time_all(test: dict) -> None:
    c.on_nodes(test, lambda t, n: reset_time())


def bump_time(delta_ms: float) -> float:
    """Adjust the bound node's clock by delta ms; returns offset seconds
    (time.clj:86-90)."""
    with c.su():
        return clock_offset(parse_time(
            c.exec_(f"{DIR}/bump-time", delta_ms)))


def strobe_time(delta_ms: float, period_ms: float, duration_s: float) -> None:
    """time.clj:92-96."""
    with c.su():
        c.exec_(f"{DIR}/strobe-time", delta_ms, period_ms, duration_s)


class ClockNemesis(Nemesis):
    """Handles {"f": "reset", "value": [nodes]},
    {"f": "strobe", "value": {node: {delta,period,duration}}},
    {"f": "bump", "value": {node: delta_ms}}, {"f": "check-offsets"}
    (time.clj:98-146). Completions carry clock_offsets per node."""

    def setup(self, test):
        def prep(t, node):
            install()
            cu.meh(lambda: c.exec_("service", "ntpd", "stop"))
            reset_time()
        c.on_nodes(test, prep)
        return self

    def invoke(self, test, op):
        f = op.get("f")
        if f == "reset":
            res = c.on_nodes(test, lambda t, n: (reset_time(),
                                                 current_offset())[1],
                             op.get("value"))
        elif f == "check-offsets":
            res = c.on_nodes(test, lambda t, n: current_offset())
        elif f == "strobe":
            m = op["value"]

            def do_strobe(t, node):
                spec = m[node]
                strobe_time(spec["delta"], spec["period"], spec["duration"])
                return current_offset()
            res = c.on_nodes(test, do_strobe, list(m.keys()))
        elif f == "bump":
            m = op["value"]
            res = c.on_nodes(test, lambda t, n: bump_time(m[n]),
                             list(m.keys()))
        else:
            raise ValueError(f"clock nemesis can't handle {f!r}")
        return {**op, "type": "info", "clock_offsets": res}

    def teardown(self, test):
        reset_time_all(test)

    def fs(self):
        return {"reset", "strobe", "bump", "check-offsets"}


def clock_nemesis() -> ClockNemesis:
    return ClockNemesis()


def random_nonempty_subset(nodes) -> list:
    ns = [n for n in nodes if RNG.random() < 0.5]
    return ns or [RNG.choice(list(nodes))]


def reset_gen(test, ctx):
    """Randomized reset op (time.clj:148-160)."""
    return {"type": "info", "f": "reset",
            "value": random_nonempty_subset(test["nodes"])}


def bump_gen(test, ctx):
    """Bumps ±2^2..2^18 ms, exponentially distributed (time.clj:162-177)."""
    return {"type": "info", "f": "bump",
            "value": {n: int(RNG.choice([-1, 1])
                             * 2 ** (2 + RNG.random() * 16))
                      for n in random_nonempty_subset(test["nodes"])}}


def strobe_gen(test, ctx):
    """Strobes: delta 4 ms–262 s, period 1 ms–1 s, ≤32 s
    (time.clj:179-197)."""
    return {"type": "info", "f": "strobe",
            "value": {n: {"delta": int(2 ** (2 + RNG.random() * 16)),
                          "period": int(2 ** (RNG.random() * 10)),
                          "duration": RNG.random() * 32}
                      for n in random_nonempty_subset(test["nodes"])}}


def clock_gen():
    """Random schedule of clock faults, starting with a check
    (time.clj:199-205)."""
    from .. import generator as gen
    return gen.phases({"type": "info", "f": "check-offsets"},
                      gen.mix([reset_gen, bump_gen, strobe_gen]))
