"""Fault injection: nemeses alter the cluster through the same
invoke-shaped interface clients use.

Capability parity with jepsen.nemesis (`jepsen/src/jepsen/nemesis.clj`):
the `Nemesis` protocol (:11-16) and `Reflection.fs` (:18-21), grudge
algebra (complete_grudge :120-132, invert_grudge, bridge :144-155,
majorities_ring :202-275 in exact ≤5-node and stochastic variants),
partitioners (:157-200), `f_map` (:285-327) and `compose` (:329-428)
for building composite nemeses, clock scrambling (:435-450),
node start/stoppers and SIGSTOP hammering (:452-511), and file
truncation (:513-539).
"""

from __future__ import annotations

import logging
import random as _random
import threading
import time as _time
from typing import Any, Callable, Iterable, Optional, Sequence

from .. import control as c
from .. import net as jnet
from ..util import majority, timeout as util_timeout

log = logging.getLogger("jepsen_tpu.nemesis")

RNG = _random.Random()


class Nemesis:
    """nemesis.clj:11-16."""

    def setup(self, test: dict) -> "Nemesis":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        return None

    def fs(self) -> set:
        """Reflection: which :f values this nemesis handles
        (nemesis.clj:18-21)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support Reflection/fs")


class Noop(Nemesis):
    """nemesis.clj:40-47."""

    def invoke(self, test, op):
        return op

    def fs(self):
        return set()


noop = Noop


class InvalidNemesisCompletion(Exception):
    pass


class Validate(Nemesis):
    """Validates setup/invoke responses (nemesis.clj:49-90)."""

    def __init__(self, nemesis: Nemesis):
        self.nemesis = nemesis

    def setup(self, test):
        res = self.nemesis.setup(test)
        if not isinstance(res, Nemesis):
            raise TypeError(f"expected setup to return a Nemesis, "
                            f"got {res!r}")
        return Validate(res)

    def invoke(self, test, op):
        op2 = self.nemesis.invoke(test, op)
        problems = []
        if not isinstance(op2, dict):
            problems.append("should be a dict")
        else:
            if op2.get("type") != "info":
                problems.append("type should be info")
            if op2.get("process") != op.get("process"):
                problems.append("process should be the same")
            if op2.get("f") != op.get("f"):
                problems.append("f should be the same")
        if problems:
            raise InvalidNemesisCompletion(
                f"nemesis completed {op!r} with {op2!r}: "
                + "; ".join(problems))
        return op2

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return self.nemesis.fs()


def validate(nemesis: Nemesis) -> Validate:
    return Validate(nemesis)


class Timeout(Nemesis):
    """Bound invoke time; timed-out ops get value "timeout"
    (nemesis.clj:92-107)."""

    def __init__(self, timeout_s: float, nemesis: Nemesis):
        self.timeout_s = timeout_s
        self.nemesis = nemesis

    def setup(self, test):
        return Timeout(self.timeout_s, self.nemesis.setup(test))

    def invoke(self, test, op):
        res = util_timeout(self.timeout_s,
                           lambda: self.nemesis.invoke(test, op),
                           default={**op, "value": "timeout"})
        return res

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return self.nemesis.fs()


# ---------------------------------------------------------------------------
# Grudge algebra (nemesis.clj:109-275)
# ---------------------------------------------------------------------------

def bisect(coll: Sequence) -> list:
    """Cut a sequence in half; smaller half first (nemesis.clj:109-112)."""
    n = len(coll) // 2
    return [list(coll[:n]), list(coll[n:])]


def split_one(coll: Sequence, loner=None) -> list:
    """Split one node from the rest (nemesis.clj:114-119)."""
    if loner is None:
        loner = RNG.choice(list(coll))
    return [[loner], [x for x in coll if x != loner]]


def complete_grudge(components: Iterable[Sequence]) -> dict:
    """{node: set of nodes it cannot talk to}, isolating each component
    (nemesis.clj:120-132)."""
    comps = [set(comp) for comp in components]
    universe = set().union(*comps) if comps else set()
    grudge = {}
    for comp in comps:
        for node in comp:
            grudge[node] = universe - comp
    return grudge


def invert_grudge(nodes: Iterable, conns: dict) -> dict:
    """Connections -> non-connections (nemesis.clj:134-142)."""
    ns = set(nodes)
    return {a: ns - set(conns.get(a, set())) for a in sorted(ns, key=str)}


def bridge(nodes: Sequence) -> dict:
    """Cut the network in half, preserving one bridge node connected to
    both sides (nemesis.clj:144-155)."""
    comps = bisect(nodes)
    br = comps[1][0]
    grudge = complete_grudge(comps)
    grudge.pop(br, None)
    return {k: v - {br} for k, v in grudge.items()}


def majorities_ring_perfect(nodes: Sequence) -> dict:
    """Exact variant for <=5 nodes (nemesis.clj:202-218)."""
    U = set(nodes)
    n = len(nodes)
    m = majority(n)
    shuffled = list(nodes)
    RNG.shuffle(shuffled)
    ring = shuffled * 2
    grudge = {}
    for i in range(n):
        maj = ring[i:i + m]
        center = maj[len(maj) // 2]
        grudge[center] = U - set(maj)
    return grudge


def majorities_ring_stochastic(nodes: Sequence) -> dict:
    """Stochastic variant for larger clusters (nemesis.clj:220-258)."""
    n = len(nodes)
    m = majority(n)
    conns = {x: {x} for x in nodes}
    while True:
        degrees = sorted(((len(v), k) for k, v in conns.items()),
                         key=lambda dk: (dk[0], RNG.random()))
        a_deg, a = degrees[0]
        if a_deg >= m:
            return invert_grudge(nodes, conns)
        for b_deg, b in degrees[1:]:
            if b not in conns[a]:
                conns[a].add(b)
                conns[b].add(a)
                break
        else:
            return invert_grudge(nodes, conns)


def majorities_ring(nodes: Sequence) -> dict:
    """Every node sees a majority; no two see the same one
    (nemesis.clj:260-275)."""
    if len(nodes) <= 5:
        return majorities_ring_perfect(nodes)
    return majorities_ring_stochastic(nodes)


# ---------------------------------------------------------------------------
# Partitioners (nemesis.clj:157-200, 277-281)
# ---------------------------------------------------------------------------

class Partitioner(Nemesis):
    """start -> apply a grudge; stop -> heal (nemesis.clj:157-183). The
    grudge comes from the op's value, or from grudge_fn(test nodes)."""

    def __init__(self, grudge_fn: Optional[Callable] = None):
        self.grudge_fn = grudge_fn

    def setup(self, test):
        test["net"].heal(test)
        return self

    def invoke(self, test, op):
        f = op.get("f")
        if f == "start":
            grudge = op.get("value")
            if grudge is None:
                if self.grudge_fn is None:
                    raise ValueError(
                        f"expected op {op!r} to have a grudge for a value")
                grudge = self.grudge_fn(list(test["nodes"]))
            jnet.drop_all(test, grudge)
            log.info("Cut off %r", grudge)
            return {**op, "type": "info",
                    "value": ["isolated", {k: sorted(v, key=str)
                                           for k, v in grudge.items()}]}
        if f == "stop":
            test["net"].heal(test)
            log.info("Network healed")
            return {**op, "type": "info", "value": "network-healed"}
        raise ValueError(f"partitioner can't handle {f!r}")

    def teardown(self, test):
        test["net"].heal(test)

    def fs(self):
        return {"start", "stop"}


def partitioner(grudge_fn: Optional[Callable] = None) -> Partitioner:
    return Partitioner(grudge_fn)


def partition_halves() -> Partitioner:
    """First half vs second half (nemesis.clj:185-190)."""
    return Partitioner(lambda nodes: complete_grudge(bisect(nodes)))


def partition_random_halves() -> Partitioner:
    """Random halves (nemesis.clj:192-195)."""
    def f(nodes):
        nodes = list(nodes)
        RNG.shuffle(nodes)
        return complete_grudge(bisect(nodes))
    return Partitioner(f)


def partition_random_node() -> Partitioner:
    """Isolate one random node (nemesis.clj:197-200)."""
    return Partitioner(lambda nodes: complete_grudge(split_one(nodes)))


def partition_majorities_ring() -> Partitioner:
    """nemesis.clj:277-281."""
    return Partitioner(majorities_ring)


# ---------------------------------------------------------------------------
# Composition (nemesis.clj:283-428)
# ---------------------------------------------------------------------------

class FMap(Nemesis):
    """Remap the :f values a nemesis accepts (nemesis.clj:285-327)."""

    def __init__(self, lift: Callable, nemesis: Nemesis):
        self.lift = lift
        self.nemesis = nemesis
        self.unlift = {lift(f): f for f in nemesis.fs()}

    def setup(self, test):
        return FMap(self.lift, self.nemesis.setup(test))

    def invoke(self, test, op):
        inner = {**op, "f": self.unlift[op["f"]]}
        res = self.nemesis.invoke(test, inner)
        return {**res, "f": self.lift(res["f"])}

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return {self.lift(f) for f in self.nemesis.fs()}


def f_map(lift: Callable, nemesis: Nemesis) -> FMap:
    return FMap(lift, nemesis)


class Compose(Nemesis):
    """Route ops to child nemeses by :f (nemesis.clj:329-428). Takes
    either a dict {f-mapping: nemesis} — where f-mapping is a set of fs
    or a dict renaming outer fs to inner fs — or a list of nemeses
    supporting Reflection."""

    def __init__(self, nemeses):
        self.nemeses = nemeses
        if isinstance(nemeses, dict):
            self.routes = None
        else:
            routes: dict = {}
            for i, n in enumerate(nemeses):
                for f in n.fs():
                    assert f not in routes, (
                        f"nemeses {n!r} and {nemeses[routes[f]]!r} are "
                        f"mutually incompatible; both use f {f!r}")
                    routes[f] = i
            self.routes = routes

    def setup(self, test):
        if isinstance(self.nemeses, dict):
            return Compose({k: n.setup(test)
                            for k, n in self.nemeses.items()})
        return Compose([n.setup(test) for n in self.nemeses])

    def invoke(self, test, op):
        f = op.get("f")
        if self.routes is not None:
            i = self.routes.get(f)
            if i is None:
                raise ValueError(
                    f"no nemesis can handle f {f!r} "
                    f"(expected one of {sorted(self.routes, key=str)})")
            return self.nemeses[i].invoke(test, op)
        for fmapping, nem in self.nemeses.items():
            if isinstance(fmapping, dict):
                f2 = fmapping.get(f)
            elif f in fmapping:
                f2 = f
            else:
                f2 = None
            if f2 is not None:
                res = nem.invoke(test, {**op, "f": f2})
                return {**res, "f": f}
        raise ValueError(f"no nemesis can handle {f!r}")

    def teardown(self, test):
        ns = (self.nemeses.values() if isinstance(self.nemeses, dict)
              else self.nemeses)
        for n in ns:
            n.teardown(test)

    def fs(self):
        if self.routes is not None:
            return set(self.routes)
        out: set = set()
        for fmapping in self.nemeses:
            if isinstance(fmapping, dict):
                out |= set(fmapping.keys())
            elif isinstance(fmapping, (set, frozenset)):
                out |= set(fmapping)
            else:
                raise TypeError(
                    "can only infer fs from dict- or set-keyed compose")
        return out


def compose(nemeses) -> Compose:
    return Compose(nemeses if isinstance(nemeses, dict) else list(nemeses))


# ---------------------------------------------------------------------------
# Clock + process faults (nemesis.clj:430-539)
# ---------------------------------------------------------------------------

def set_time(t: float) -> None:
    """Set node time in POSIX seconds (nemesis.clj:430-433)."""
    with c.su():
        c.exec_("date", "+%s", "-s", f"@{int(t)}")


class ClockScrambler(Nemesis):
    """Randomize node clocks within a dt-second window
    (nemesis.clj:435-450)."""

    def __init__(self, dt: float):
        self.dt = dt

    def invoke(self, test, op):
        def f(t, node):
            set_time(_time.time() + RNG.uniform(-self.dt, self.dt))
        value = c.on_nodes(test, f)
        return {**op, "type": "info", "value": value}

    def teardown(self, test):
        def f(t, node):
            set_time(_time.time())
        c.on_nodes(test, f)

    def fs(self):
        return {"scramble-clock"}


def clock_scrambler(dt: float) -> ClockScrambler:
    return ClockScrambler(dt)


class NodeStartStopper(Nemesis):
    """start -> run start_fn on targeted nodes; stop -> stop_fn
    (nemesis.clj:452-495)."""

    def __init__(self, targeter: Callable, start_fn: Callable,
                 stop_fn: Callable):
        self.targeter = targeter
        self.start_fn = start_fn
        self.stop_fn = stop_fn
        self.nodes: Optional[list] = None
        self.lock = threading.Lock()

    def invoke(self, test, op):
        with self.lock:
            f = op.get("f")
            if f == "start":
                try:
                    ns = self.targeter(test, list(test["nodes"]))
                except TypeError:
                    ns = self.targeter(list(test["nodes"]))
                if ns is None:
                    value = "no-target"
                elif self.nodes is not None:
                    value = f"nemesis already disrupting {self.nodes!r}"
                else:
                    if not isinstance(ns, (list, tuple, set)):
                        ns = [ns]
                    ns = list(ns)
                    value = c.on_many(
                        ns, lambda: self.start_fn(test, c.state.host))
                    self.nodes = ns
            elif f == "stop":
                if self.nodes is None:
                    value = "not-started"
                else:
                    value = c.on_many(
                        self.nodes,
                        lambda: self.stop_fn(test, c.state.host))
                    self.nodes = None
            else:
                raise ValueError(f"can't handle {f!r}")
            return {**op, "type": "info", "value": value}

    def fs(self):
        return {"start", "stop"}


def node_start_stopper(targeter, start_fn, stop_fn) -> NodeStartStopper:
    return NodeStartStopper(targeter, start_fn, stop_fn)


def hammer_time(process: str, targeter: Optional[Callable] = None
                ) -> NodeStartStopper:
    """SIGSTOP/SIGCONT a process on targeted nodes (nemesis.clj:497-511)."""
    if targeter is None:
        targeter = lambda nodes: RNG.choice(nodes)  # noqa: E731

    def start(test, node):
        with c.su():
            c.exec_("killall", "-s", "STOP", process)
        return ["paused", process]

    def stop(test, node):
        with c.su():
            c.exec_("killall", "-s", "CONT", process)
        return ["resumed", process]

    return NodeStartStopper(targeter, start, stop)


class TruncateFile(Nemesis):
    """Drop the last bytes of files on nodes (nemesis.clj:513-539); op
    value is {node: {"file": path, "drop": bytes}}."""

    def invoke(self, test, op):
        assert op.get("f") == "truncate"
        plan = op["value"]

        def f(t, node):
            spec = plan[node]
            with c.su():
                c.exec_("truncate", "-c", "-s", f"-{spec['drop']}",
                        spec["file"])
        c.on_nodes(test, f, list(plan.keys()))
        return {**op, "type": "info"}

    def fs(self):
        return {"truncate"}


def truncate_file() -> TruncateFile:
    return TruncateFile()
