"""Filesystem fault injection — the CharybdeFS-equivalent layer.

Capability parity with the reference's charybdefs wrapper
(`charybdefs/src/jepsen/charybdefs.clj:40-86`), which builds a
C++/Thrift FUSE passthrough on each node and drives EIO "cookbook"
recipes over RPC. Two native backends, both in `native/faultfs/`:

  * **faultfs** (`faultfs.cc`) — the FUSE passthrough. Mounts a
    backing dir with a `.faultfs_ctl` control file; one-line commands
    injected through the control layer flip global / probabilistic /
    path-targeted EIO and latency. Needs libfuse3-dev + /dev/fuse on
    the node; compiled there exactly like the reference compiles
    charybdefs on-node (charybdefs.clj:40-66).

  * **faultlib** (`faultlib.cc`) — an LD_PRELOAD libc interposer (the
    libfaketime mechanism, faketime.clj:8-22): wrap the DB daemon's
    environment and its writes/fsyncs to targeted paths fail with EIO,
    steerable at runtime through a config file the nemesis rewrites.
    No privileges needed — this backend runs in CI against live toykv
    clusters.

`FaultLibNemesis` ops:  {"f": "start", "value": {"eio_p": 1.0,
"path": "state.log", "delay_ms": 0, "eio_after": N}} begins injection
on every node; {"f": "stop"} clears it.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from .. import control
from ..control import nodeutil
from . import Nemesis

log = logging.getLogger("jepsen_tpu.nemesis.faultfs")

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..",
                          "native", "faultfs")
REMOTE_DIR = "faultfs-src"
CONF_NAME = "faultlib.conf"


def _upload_sources():
    control.exec_("mkdir", "-p", REMOTE_DIR)
    for name in ("faultfs.cc", "faultlib.cc", "Makefile"):
        control.upload(os.path.join(NATIVE_DIR, name),
                       f"{REMOTE_DIR}/{name}")


def install_faultlib() -> str:
    """Compile faultlib.so on the node (g++ only); returns its node
    path. Mirrors nemesis/time.clj:20-39's compile-on-node."""
    _upload_sources()
    control.exec_("make", "-C", REMOTE_DIR, "build/faultlib.so")
    return f"{REMOTE_DIR}/build/faultlib.so"


def install_faultfs() -> str:
    """Compile the FUSE faultfs binary on the node (needs
    libfuse3-dev; the caller installs it, e.g. via the OS layer —
    charybdefs.clj:48-51 does apt-get there too)."""
    _upload_sources()
    control.exec_("make", "-C", REMOTE_DIR, "faultfs")
    return f"{REMOTE_DIR}/build/faultfs"


def preload_env(so_path: str, conf_path: str = CONF_NAME,
                path_substr: Optional[str] = None) -> dict:
    """Environment for a DB daemon to run under faultlib (merge into
    start_daemon's env), steerable later via the conf file."""
    env = {"LD_PRELOAD": so_path, "FAULTLIB_CONF": conf_path}
    if path_substr:
        env["FAULTLIB_PATH"] = path_substr
    return env


class FaultFS:
    """Mount manager + cookbook for the FUSE backend
    (charybdefs.clj:58-86). All methods run under a bound control
    session."""

    def __init__(self, backing: str = "/real", mount: str = "/faulty"):
        self.backing = backing
        self.mount = mount
        self.bin: Optional[str] = None

    def setup(self):
        self.bin = install_faultfs()
        control.exec_("mkdir", "-p", self.backing, self.mount)
        nodeutil.meh(control.exec_, "fusermount", "-u", self.mount)
        control.exec_(self.bin, self.backing, self.mount)

    def _ctl(self, command: str):
        control.exec_("bash", "-c",
                      f"echo {control.escape(command)} > "
                      f"{control.escape(self.mount)}/.faultfs_ctl")

    def break_all(self):
        self._ctl("eio all")           # charybdefs.clj:73-76

    def break_percent(self, p: float = 0.01):
        self._ctl(f"eio p {p}")        # charybdefs.clj:78-81

    def break_path(self, substr: str):
        self._ctl(f"eio path {substr}")

    def delay(self, ms: int, p: float = 1.0):
        self._ctl(f"delay ms {ms} p {p}")

    def clear(self):
        self._ctl("clear")             # charybdefs.clj:83-86

    def teardown(self):
        nodeutil.meh(control.exec_, "fusermount", "-u", self.mount)


class FaultLibNemesis(Nemesis):
    """Drives faultlib's conf file on every node: "start" writes the
    fault spec, "stop" clears it (the preload rereads the file on each
    intercepted call)."""

    def __init__(self, conf_path: str = CONF_NAME):
        self.conf_path = conf_path

    def setup(self, test):
        return self

    def invoke(self, test, op):
        f = op.get("f")
        if f == "start":
            spec = op.get("value") or {}
            lines = []
            for k in ("eio_p", "eio_after", "delay_ms", "path"):
                if spec.get(k) is not None:
                    lines.append(f"{k}={spec[k]}")
            body = "\\n".join(lines)
            cmd = (f"printf '{body}\\n' > "
                   f"{control.escape(self.conf_path)}")
        elif f == "stop":
            cmd = f"rm -f {control.escape(self.conf_path)}"
        else:
            return {**op, "value": ["unknown-f", f]}
        res = control.on_nodes(
            test, lambda t, n: control.exec_("bash", "-c", cmd))
        return {**op, "value": {n: "ok" for n in res}}

    def teardown(self, test):
        try:
            control.on_nodes(
                test, lambda t, n: nodeutil.meh(
                    control.exec_, "rm", "-f", self.conf_path))
        except Exception:  # noqa: BLE001 — sessions may be gone
            pass

    def fs(self):
        return ["start", "stop"]
