"""Cluster membership nemesis (parity with jepsen.nemesis.membership +
membership/state, `jepsen/src/jepsen/nemesis/membership{,.state}.clj`):
standardized support for nemeses that grow and shrink clusters. A
`State` models Jepsen's view of the cluster: per-node views polled on an
interval, a merged authoritative view, and the set of pending operations
whose resolution we must confirm before making further changes."""

from __future__ import annotations

import logging
import threading
import time as _time
from typing import Any, Optional

from .. import control as c
from .. import generator as gen
from . import Nemesis

log = logging.getLogger("jepsen_tpu.nemesis.membership")

NODE_VIEW_INTERVAL = 5  # seconds between node view refreshes (:60-62)


class State:
    """The membership state machine protocol (membership/state.clj:21-59).
    Implementations carry three standard fields, maintained by the
    nemesis: node_views (node -> view), view (merged), pending (set of
    (op, op') pairs)."""

    node_views: dict
    view: Any
    pending: frozenset

    def setup(self, test) -> "State":
        return self

    def node_view(self, test, node):
        """This node's view of the cluster, or None if unknown."""
        raise NotImplementedError

    def merge_views(self, test):
        """Derive the authoritative view from node_views."""
        raise NotImplementedError

    def fs(self) -> set:
        raise NotImplementedError

    def op(self, test):
        """Next operation to perform, or "pending" if none available."""
        raise NotImplementedError

    def invoke(self, test, op):
        """Apply an op; returns op' or (op', state')."""
        raise NotImplementedError

    def resolve(self, test) -> "State":
        """Evolve toward a fixed point (default: resolve each pending
        op via resolve_op)."""
        state = self
        for pair in list(state.pending):
            nxt = state.resolve_op(test, pair)
            if nxt is not None:
                state = nxt
                state.pending = frozenset(state.pending) - {pair}
        return state

    def resolve_op(self, test, pair) -> Optional["State"]:
        """If (op, op') has resolved, return the new state, else None."""
        return None

    def teardown(self, test) -> None:
        return None


def initial_fields(test: dict) -> dict:
    """membership.clj:69-78."""
    return {"node_views": {}, "view": None, "pending": frozenset()}


class MembershipNemesis(Nemesis):
    """Wraps a State into a Nemesis: refreshes node views on an interval
    in a background thread, routes invokes through the state, and tracks
    pending ops (membership.clj:80-270)."""

    def __init__(self, state: State):
        self.state = state
        self.lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _refresh(self, test):
        views = c.on_nodes(test, lambda t, n: self.state.node_view(t, n))
        with self.lock:
            self.state.node_views = {k: v for k, v in views.items()
                                     if v is not None}
            self.state.view = self.state.merge_views(test)
            self.state = self.state.resolve(test)

    def setup(self, test):
        self.state.node_views = {}
        self.state.view = None
        self.state.pending = frozenset()
        self.state = self.state.setup(test)
        self._refresh(test)

        def loop():
            while not self._stop.wait(NODE_VIEW_INTERVAL):
                try:
                    self._refresh(test)
                except Exception as e:  # noqa: BLE001
                    log.warning("membership view refresh failed: %s", e)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="membership-views")
        self._thread.start()
        return self

    def invoke(self, test, op):
        with self.lock:
            res = self.state.invoke(test, op)
            if isinstance(res, tuple):
                op2, state2 = res
                self.state = state2
            else:
                op2 = res
            self.state.pending = frozenset(self.state.pending) | {
                (_freeze(op), _freeze(op2))}
            return {**op2, "type": "info"}

    def teardown(self, test):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=NODE_VIEW_INTERVAL + 1)
        self.state.teardown(test)

    def fs(self):
        return self.state.fs()

    def generator(self):
        """A generator asking the state for legal ops
        (membership.clj:231-237). When the state has no op available it
        reports "pending"; we emit PENDING (keeping the generator alive
        so it is asked again) rather than None, which the DSL would
        treat as permanent exhaustion."""
        return _MembershipGen(self)


class _MembershipGen(gen.Generator):
    def __init__(self, nem: "MembershipNemesis"):
        self.nem = nem

    def op(self, test, ctx):
        with self.nem.lock:
            o = self.nem.state.op(test)
        if o == "pending":
            return (gen.PENDING, self)
        if o is None:
            return None
        filled = gen.fill_in_op(dict(o), ctx)
        return (filled, self)


def _freeze(op: dict):
    return tuple(sorted((k, str(v)) for k, v in op.items()))


def nemesis(state: State) -> MembershipNemesis:
    return MembershipNemesis(state)
