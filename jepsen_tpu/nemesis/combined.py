"""Composable nemesis packages (parity with jepsen.nemesis.combined,
`jepsen/src/jepsen/nemesis/combined.clj`): a *package* is a dict with
"nemesis", "generator", "final_generator", and "perf" keys; packages for
process kill/pause (via db.Process/db.Pause), network partitions, and
clock faults compose into one nemesis+generator pair
(combined.clj:305-328), with node-spec targeting (:one/:minority/
:majority/:minority-third/:primaries/:all, combined.clj:38-61)."""

from __future__ import annotations

from typing import Optional, Sequence

from .. import db as jdb
from .. import generator as gen
from ..util import majority, minority_third
from . import (Compose, Nemesis, Partitioner, RNG, bisect, complete_grudge,
               compose, f_map as nemesis_f_map, majorities_ring, noop as
               nemesis_noop, split_one)
from . import timefaults as nt

DEFAULT_INTERVAL = 10  # seconds between nemesis ops (combined.clj:27-29)

NOOP_PACKAGE = {"generator": None, "final_generator": None,
                "nemesis": nemesis_noop(), "perf": set()}


def random_nonempty_subset(nodes) -> list:
    ns = [n for n in nodes if RNG.random() < 0.5]
    return ns or [RNG.choice(list(nodes))]


def db_nodes(test: dict, db, node_spec) -> list:
    """Resolve a node spec to nodes (combined.clj:38-61)."""
    nodes = list(test["nodes"])
    if node_spec is None:
        return random_nonempty_subset(nodes)
    if node_spec == "one":
        return [RNG.choice(nodes)]
    if node_spec in ("minority", "majority", "minority-third"):
        shuffled = list(nodes)
        RNG.shuffle(shuffled)
        n = len(nodes)
        k = {"minority": majority(n) - 1,
             "majority": majority(n),
             "minority-third": minority_third(n)}[node_spec]
        return shuffled[:k]
    if node_spec == "primaries":
        return random_nonempty_subset(db.primaries(test))
    if node_spec == "all":
        return nodes
    return list(node_spec)


def node_specs(db) -> list:
    """All node specs valid for this DB (combined.clj:63-69)."""
    specs = [None, "one", "minority-third", "minority", "majority", "all"]
    if isinstance(db, jdb.Primary):
        specs.append("primaries")
    return specs


class DBNemesis(Nemesis):
    """start/kill/pause/resume a DB's processes (combined.clj:71-98)."""

    def __init__(self, db):
        self.db = db

    def invoke(self, test, op):
        from .. import control as c
        f = {"start": self.db.start,
             "kill": self.db.kill,
             "pause": self.db.pause,
             "resume": self.db.resume}[op["f"]]
        nodes = db_nodes(test, self.db, op.get("value"))
        res = c.on_nodes(test, lambda t, n: f(t, n), nodes)
        return {**op, "type": "info", "value": res}

    def fs(self):
        return {"start", "kill", "pause", "resume"}


def db_generators(opts: dict) -> dict:
    """Generators for kill/pause faults (combined.clj:100-139)."""
    db = opts["db"]
    faults = opts["faults"]
    kill = isinstance(db, jdb.Process) and "kill" in faults
    pause = isinstance(db, jdb.Pause) and "pause" in faults
    kill_targets = opts.get("kill", {}).get("targets", node_specs(db))
    pause_targets = opts.get("pause", {}).get("targets", node_specs(db))

    start = {"type": "info", "f": "start", "value": "all"}
    resume = {"type": "info", "f": "resume", "value": "all"}

    def kill_op(test, ctx):
        return {"type": "info", "f": "kill",
                "value": RNG.choice(kill_targets)}

    def pause_op(test, ctx):
        return {"type": "info", "f": "pause",
                "value": RNG.choice(pause_targets)}

    modes = []
    final = []
    if pause:
        modes.append(gen.flip_flop(pause_op, gen.repeat(resume)))
        final.append(resume)
    if kill:
        modes.append(gen.flip_flop(kill_op, gen.repeat(start)))
        final.append(start)
    return {"generator": gen.mix(modes) if modes else None,
            "final_generator": final or None}


def db_package(opts: dict) -> dict:
    """Package for killing/pausing the DB (combined.clj:141-161)."""
    needed = bool({"kill", "pause"} & set(opts["faults"]))
    gens = db_generators(opts)
    generator = gen.stagger(opts.get("interval", DEFAULT_INTERVAL),
                            gens["generator"]) \
        if gens["generator"] is not None else None
    return {
        "generator": generator if needed else None,
        "final_generator": gens["final_generator"] if needed else None,
        "nemesis": DBNemesis(opts["db"]),
        "perf": {("kill", frozenset({"kill"}), frozenset({"start"}),
                  "#E9A4A0"),
                 ("pause", frozenset({"pause"}), frozenset({"resume"}),
                  "#A0B1E9")},
    }


def grudge(test: dict, db, part_spec) -> dict:
    """Partition spec -> grudge (combined.clj:163-189)."""
    nodes = list(test["nodes"])
    if part_spec == "one":
        return complete_grudge(split_one(nodes))
    if part_spec == "majority":
        shuffled = list(nodes)
        RNG.shuffle(shuffled)
        return complete_grudge(bisect(shuffled))
    if part_spec == "majorities-ring":
        return majorities_ring(nodes)
    if part_spec == "minority-third":
        shuffled = list(nodes)
        RNG.shuffle(shuffled)
        k = minority_third(len(nodes))
        return complete_grudge([shuffled[:k], shuffled[k:]])
    if part_spec == "primaries":
        primaries = random_nonempty_subset(db.primaries(test))
        rest = [n for n in nodes if n not in set(primaries)]
        return complete_grudge([rest] + [[p] for p in primaries])
    return part_spec  # already a grudge


def partition_specs(db) -> list:
    """combined.clj:191-195."""
    specs = ["one", "minority-third", "majority", "majorities-ring"]
    if isinstance(db, jdb.Primary):
        specs.append("primaries")
    return specs


class PartitionNemesis(Nemesis):
    """Partitioner + partition specs (combined.clj:197-227)."""

    def __init__(self, db, p: Optional[Partitioner] = None):
        self.db = db
        self.p = p or Partitioner()

    def setup(self, test):
        return PartitionNemesis(self.db, self.p.setup(test))

    def invoke(self, test, op):
        if op["f"] == "start-partition":
            g = grudge(test, self.db, op.get("value"))
            res = self.p.invoke(test, {**op, "f": "start", "value": g})
        else:
            res = self.p.invoke(test, {**op, "f": "stop"})
        return {**res, "f": op["f"]}

    def teardown(self, test):
        self.p.teardown(test)

    def fs(self):
        return {"start-partition", "stop-partition"}


def partition_package(opts: dict) -> dict:
    """combined.clj:229-249."""
    needed = "partition" in opts["faults"]
    db = opts["db"]
    targets = opts.get("partition", {}).get("targets", partition_specs(db))

    def start(test, ctx):
        return {"type": "info", "f": "start-partition",
                "value": RNG.choice(targets)}

    stop = {"type": "info", "f": "stop-partition", "value": None}
    g = gen.stagger(opts.get("interval", DEFAULT_INTERVAL),
                    gen.flip_flop(start, gen.repeat(stop)))
    return {"generator": g if needed else None,
            "final_generator": stop if needed else None,
            "nemesis": PartitionNemesis(db),
            "perf": {("partition", frozenset({"start-partition"}),
                      frozenset({"stop-partition"}), "#E9DCA0")}}


def clock_package(opts: dict) -> dict:
    """combined.clj:251-282."""
    needed = "clock" in opts["faults"]
    db = opts["db"]
    nemesis = Compose(
        {_FrozenDict({"reset-clock": "reset",
                      "check-clock-offsets": "check-offsets",
                      "strobe-clock": "strobe",
                      "bump-clock": "bump"}): nt.clock_nemesis()})
    target_specs = opts.get("clock", {}).get("targets", node_specs(db))

    def targets(test):
        spec = RNG.choice(target_specs) if target_specs else None
        return db_nodes(test, db, spec)

    def reset_g(test, ctx):
        return {"type": "info", "f": "reset", "value": targets(test)}

    def bump_g(test, ctx):
        return {"type": "info", "f": "bump",
                "value": {n: int(RNG.choice([-1, 1])
                                 * 2 ** (2 + RNG.random() * 16))
                          for n in targets(test)}}

    def strobe_g(test, ctx):
        return {"type": "info", "f": "strobe",
                "value": {n: {"delta": int(2 ** (2 + RNG.random() * 16)),
                              "period": int(2 ** (RNG.random() * 10)),
                              "duration": RNG.random() * 32}
                          for n in targets(test)}}

    lifted = gen.f_map({"reset": "reset-clock",
                        "check-offsets": "check-clock-offsets",
                        "strobe": "strobe-clock",
                        "bump": "bump-clock"},
                       gen.phases({"type": "info", "f": "check-offsets"},
                                  gen.mix([reset_g, bump_g, strobe_g])))
    g = gen.stagger(opts.get("interval", DEFAULT_INTERVAL), lifted)
    return {"generator": g if needed else None,
            "final_generator": ({"type": "info", "f": "reset-clock"}
                                if needed else None),
            "nemesis": nemesis,
            "perf": {("clock", frozenset({"bump-clock"}),
                      frozenset({"reset-clock"}), "#A0E9E3")}}


class _FrozenDict(dict):
    """A hashable dict usable as a Compose routing key."""

    def __hash__(self):
        return hash(frozenset(self.items()))


def package_f_map(lift, pkg: dict) -> dict:
    """Lift a whole package's fs (combined.clj:284-303)."""
    lift_fn = lift if callable(lift) else lambda f: lift.get(f, f)
    fmap_dict = lift if isinstance(lift, dict) else None

    def lift_gen(g):
        if g is None:
            return None
        if fmap_dict is not None:
            return gen.f_map(fmap_dict, g)
        return gen.map_(lambda o: {**o, "f": lift_fn(o.get("f"))}, g)

    return {**pkg,
            "generator": lift_gen(pkg.get("generator")),
            "final_generator": lift_gen(pkg.get("final_generator")),
            "nemesis": nemesis_f_map(lift_fn, pkg["nemesis"]),
            "perf": {(lift_fn(name),
                      frozenset(lift_fn(f) for f in start),
                      frozenset(lift_fn(f) for f in stop), color)
                     for name, start, stop, color in pkg.get("perf", set())}}


def compose_packages(packages: Sequence[dict]) -> dict:
    """Combine packages: generators via any, finals sequentially,
    nemeses via compose (combined.clj:305-317)."""
    packages = list(packages)
    if not packages:
        return dict(NOOP_PACKAGE)
    if len(packages) == 1:
        return packages[0]
    perf: set = set()
    for p in packages:
        perf |= p.get("perf", set())
    return {
        "generator": gen.any_(*[p["generator"] for p in packages
                                if p.get("generator") is not None]),
        "final_generator": [p["final_generator"] for p in packages
                            if p.get("final_generator") is not None],
        "nemesis": compose([p["nemesis"] for p in packages
                            if p.get("nemesis") is not None]),
        "perf": perf,
    }


def nemesis_packages(opts: dict) -> list:
    """combined.clj:319-327."""
    opts = {**opts, "faults": set(opts.get("faults",
                                           ["partition", "kill", "pause",
                                            "clock"]))}
    return [partition_package(opts), clock_package(opts), db_package(opts)]


def nemesis_package(opts: dict) -> dict:
    """The kitchen-sink nemesis package (combined.clj:329-377)."""
    return compose_packages(nemesis_packages(opts))
