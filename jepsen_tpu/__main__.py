"""`python -m jepsen_tpu` — the built-in demo test runner.

A complete CLI suite wired around the in-process CAS-register fakes,
mirroring how per-DB suites wire `cli/single-test-cmd` in the reference
(e.g. `zookeeper/src/jepsen/zookeeper.clj:131-145`): `test` runs one
demo test end to end (dummy remote, in-process register, WGL checker)
and exits by validity; `test-all` sweeps seeds; `analyze` re-checks the
latest stored run; `serve` browses the store and exposes the live run
status at `/status.json` (+ the auto-refreshing `/status` panel —
doc/OBSERVABILITY.md "watching a live run").

Usage:
  python -m jepsen_tpu test --time-limit 5 --concurrency 2n
  python -m jepsen_tpu test-all --test-count 3
  python -m jepsen_tpu serve -p 8080
"""

from __future__ import annotations

from . import checker, cli, fakes, models
from . import generator as gen
from .cli import Opt


def demo_workload():
    """r/w/cas op mix over a small value alphabet
    (tests/linearizable_register.clj:18-29)."""
    return gen.mix([
        gen.repeat(lambda: {"f": "read"}),
        gen.repeat(lambda: {"f": "write", "value": gen.RNG.randrange(5)}),
        gen.repeat(lambda: {"f": "cas",
                            "value": [gen.RNG.randrange(5),
                                      gen.RNG.randrange(5)]}),
    ])


def demo_test(options: dict) -> dict:
    """Build the demo test map from parsed CLI options."""
    reg = fakes.SharedRegister()
    rate = options.get("rate") or 10.0
    return {
        "name": options.get("name") or "demo",
        "store_root": options.get("store_root") or "store",
        "nodes": options["nodes"],
        "concurrency": options["concurrency"],
        # the demo's "cluster" is in-process; always use the dummy remote
        "ssh": {"dummy?": True},
        "client": fakes.AtomClient(reg),
        "nemesis": fakes.NoopNemesis(),
        "leave_db_running?": options.get("leave_db_running?", False),
        # the reference register workload composes linearizable (+
        # timeline) only — stats would fail any short run where some op
        # type happens to record zero oks (checker.clj:166-183)
        "checker": checker.linearizable(models.cas_register(),
                                        algorithm="wgl"),
        "generator": gen.time_limit(
            options.get("time_limit") or 60,
            gen.clients(gen.stagger(1.0 / rate, demo_workload()))),
    }


def demo_tests(options: dict):
    """test-all: the demo test repeated across seeds."""
    for i in range(options.get("test_count") or 1):
        t = demo_test(options)
        yield {**t, "name": f"{t['name']}-{i}"}


DEMO_OPTS = [
    Opt("name", metavar="NAME", default="demo",
        help="Name for this test run"),
    Opt("store_root", metavar="DIR", default="store",
        help="Where to write results"),
    Opt("rate", metavar="HZ", default=10.0, parse=float,
        help="Approximate ops/sec per worker"),
]

def preflight_cmd() -> dict:
    """`python -m jepsen_tpu preflight` — the static admission
    analyzer (analysis/preflight): emit the plan report a check WOULD
    run (ladder buckets, kernel variants, Elle route, per-node
    cost_analysis, HBM peak) plus the feasible/degrade/infeasible
    verdict, without executing anything on a device."""
    spec = [
        Opt("help", short="-h", help="Print out this message and exit"),
        Opt("config", metavar="NAME", default="all",
            help="headline | elle_append_8k | dense_100k | all"),
        Opt("ops", metavar="N", default=10_000, parse=cli.pos_int,
            help="Headline history size (invocations)"),
        Opt("txns", metavar="N", default=4_000, parse=cli.pos_int,
            help="elle_append_8k history size (txns)"),
        Opt("execute", default=False,
            help="Also run the planned check and print the "
                 "planned-vs-executed parity block"),
        Opt("json", default=False,
            help="Emit the full plan reports as JSON"),
    ]

    def run(parsed):
        from .analysis import preflight as preflight_mod
        return preflight_mod.cli_main(parsed.options)

    return {"preflight": {"opt_spec": spec, "run": run,
                          "usage": "Usage: python -m jepsen_tpu "
                                   "preflight [OPTIONS ...]"}}


def doctor_cmd() -> dict:
    """`python -m jepsen_tpu doctor <run_id|latest|bench>` — the
    diagnosis engine (jepsen_tpu/doctor): correlate a recorded run's
    telemetry planes into ranked, evidence-backed findings under the
    D001-D012 rule catalog. Pure host-side reads of already-recorded
    artifacts — nothing executes on a device."""
    spec = [
        Opt("help", short="-h", help="Print out this message and exit"),
        Opt("target", metavar="TARGET",
            help="run_id | latest | bench (also accepted as a bare "
                 "positional argument; default bench)"),
        Opt("root", metavar="DIR",
            help="Repo root for bench artifacts (default: cwd)"),
        Opt("store", metavar="DIR",
            help="Store root holding the ledger (default: "
                 "<root>/store)"),
        Opt("json", default=False,
            help="Emit the full report as JSON"),
        Opt("strict", default=False,
            help="Exit 1 when any warn/critical finding fired"),
        Opt("no_record", default=False,
            help="Read-only: skip banking the kind=\"doctor\" "
                 "ledger record"),
        Opt("watch", default=False,
            help="Keep re-diagnosing whenever the store's ledger "
                 "index changes (read-only; Ctrl-C to stop)"),
        Opt("interval", metavar="SECONDS", default=2.0, parse=float,
            help="--watch throttle: at most one diagnosis per this "
                 "many seconds"),
    ]

    def run(parsed):
        from . import doctor as doctor_mod
        return doctor_mod.cli_main(parsed.options, parsed.arguments)

    return {"doctor": {"opt_spec": spec, "run": run,
                       "usage": "Usage: python -m jepsen_tpu doctor "
                                "[run_id|latest|bench] [OPTIONS ...]"}}


def autopilot_cmd() -> dict:
    """`python -m jepsen_tpu autopilot <run_id|latest|bench>` —
    offline replay of the autopilot's frozen policy table against a
    banked run: print which remedies the supervisor WOULD execute
    (decide step only — no actuators run, nothing is banked)."""
    spec = [
        Opt("help", short="-h", help="Print out this message and exit"),
        Opt("target", metavar="TARGET",
            help="run_id | latest | bench (also accepted as a bare "
                 "positional argument; default latest)"),
        Opt("root", metavar="DIR",
            help="Repo root for bench artifacts (default: cwd)"),
        Opt("store", metavar="DIR",
            help="Store root holding the ledger (default: "
                 "<root>/store)"),
        Opt("json", default=False,
            help="Emit the decisions + policy table as JSON"),
    ]

    def run(parsed):
        from . import autopilot as autopilot_mod
        return autopilot_mod.cli_main(parsed.options,
                                      parsed.arguments)

    return {"autopilot": {"opt_spec": spec, "run": run,
                          "usage": "Usage: python -m jepsen_tpu "
                                   "autopilot [run_id|latest|bench] "
                                   "[OPTIONS ...]"}}


def fleet_cmd() -> dict:
    """`python -m jepsen_tpu fleet <roots...>` — the fleet
    observatory (jepsen_tpu/observatory): federate N replicas' store
    ledgers into one snapshot (liveness heartbeats, request-weighted
    fleet SLO beside the per-replica breakdown, D013-D015 findings),
    reassemble a request's cross-process journey, or write a merged
    Perfetto trace with one process track per replica. Strictly
    read-only over every store."""
    spec = [
        Opt("help", short="-h", help="Print out this message and exit"),
        Opt("discover", metavar="DIR",
            help="Discover store roots in/around this directory "
                 "(used when no roots are given; default: ./store)"),
        Opt("journey", metavar="RUN_ID",
            help="Reassemble one request's cross-process journey "
                 "and print it as JSON (exit 1 when not found)"),
        Opt("perfetto", metavar="PATH",
            help="Write the merged fleet Perfetto trace here"),
        Opt("json", default=False,
            help="Emit the full fleet snapshot as JSON"),
    ]

    def run(parsed):
        from . import observatory as observatory_mod
        return observatory_mod.cli_main(parsed.options,
                                        parsed.arguments)

    return {"fleet": {"opt_spec": spec, "run": run,
                      "usage": "Usage: python -m jepsen_tpu fleet "
                               "[store_roots ...] [OPTIONS ...]"}}


COMMANDS = {
    **cli.single_test_cmd({"test_fn": demo_test, "opt_spec": DEMO_OPTS}),
    **cli.test_all_cmd({"tests_fn": demo_tests, "opt_spec": DEMO_OPTS}),
    **cli.serve_cmd(),
    **preflight_cmd(),
    **doctor_cmd(),
    **autopilot_cmd(),
    **fleet_cmd(),
}


def main() -> None:
    """Console-script entry point (pyproject [project.scripts])."""
    cli.main(COMMANDS)


if __name__ == "__main__":
    main()
