"""Network fault backend (parity with jepsen.net,
`jepsen/src/jepsen/net.clj` + `net/proto.clj`): the `Net` protocol
(drop/heal/slow/flaky/fast, net.clj:15-26), grudge application via
`drop_all` with the batched PartitionAll fast path (net.clj:29-44,
101-111), and two implementations — iptables/tc (net.clj:58-111) and
ipfilter for SmartOS/illumos (net.clj:113-145)."""

from __future__ import annotations

from typing import Optional

from . import control as c
from .control import netinfo
from .control.core import NonzeroExit, lit
from .util import real_pmap

TC = "/sbin/tc"


class Net:
    """net.clj:15-26."""

    def drop(self, test: dict, src: str, dest: str) -> None:
        raise NotImplementedError

    def heal(self, test: dict) -> None:
        raise NotImplementedError

    def slow(self, test: dict, opts: Optional[dict] = None) -> None:
        raise NotImplementedError

    def flaky(self, test: dict) -> None:
        raise NotImplementedError

    def fast(self, test: dict) -> None:
        raise NotImplementedError


class PartitionAll:
    """Optional fast path: apply a whole grudge at once
    (net/proto.clj:5-11)."""

    def drop_all(self, test: dict, grudge: dict) -> None:
        raise NotImplementedError


def drop_all(test: dict, grudge: dict) -> None:
    """Apply a grudge — {node: set of nodes it should drop traffic from}
    (net.clj:29-44)."""
    net = test["net"]
    if isinstance(net, PartitionAll):
        net.drop_all(test, grudge)
        return
    pairs = [(src, dst) for dst, srcs in grudge.items() for src in srcs]
    real_pmap(lambda p: net.drop(test, p[0], p[1]), pairs)


class Noop(Net):
    """net.clj:49-57."""

    def drop(self, test, src, dest):
        pass

    def heal(self, test):
        pass

    def slow(self, test, opts=None):
        pass

    def flaky(self, test):
        pass

    def fast(self, test):
        pass


noop = Noop


class IPTables(Net, PartitionAll):
    """Default iptables/tc implementation (net.clj:58-111)."""

    def drop(self, test, src, dest):
        with c.on(dest), c.su():
            c.exec_("iptables", "-A", "INPUT", "-s", netinfo.ip(src),
                    "-j", "DROP", "-w")

    def heal(self, test):
        def f(t, n):
            with c.su():
                c.exec_("iptables", "-F", "-w")
                c.exec_("iptables", "-X", "-w")
        c.on_nodes(test, f)

    def slow(self, test, opts=None):
        opts = opts or {}
        mean = opts.get("mean", 50)
        variance = opts.get("variance", 10)
        distribution = opts.get("distribution", "normal")

        def f(t, n):
            with c.su():
                c.exec_(TC, "qdisc", "add", "dev", "eth0", "root", "netem",
                        "delay", f"{mean}ms", f"{variance}ms",
                        "distribution", distribution)
        c.on_nodes(test, f)

    def flaky(self, test):
        def f(t, n):
            with c.su():
                c.exec_(TC, "qdisc", "add", "dev", "eth0", "root", "netem",
                        "loss", "20%", "75%")
        c.on_nodes(test, f)

    def fast(self, test):
        def f(t, n):
            try:
                with c.su():
                    c.exec_(TC, "qdisc", "del", "dev", "eth0", "root")
            except NonzeroExit as e:
                if "RTNETLINK answers: No such file or directory" not in (
                        e.result.get("err") or ""):
                    raise
        c.on_nodes(test, f)

    def drop_all(self, test, grudge):
        """One batched iptables rule per node (net.clj:101-111)."""
        def snub(t, node):
            srcs = grudge.get(node)
            if srcs:
                with c.su():
                    c.exec_("iptables", "-A", "INPUT", "-s",
                            ",".join(netinfo.ip(s) for s in srcs),
                            "-j", "DROP", "-w")
        c.on_nodes(test, snub, list(grudge.keys()))


iptables = IPTables


class IPFilter(Net):
    """ipfilter implementation for SmartOS/illumos (net.clj:113-145)."""

    def drop(self, test, src, dest):
        with c.on(dest), c.su():
            c.exec_("echo", "block", "in", "from", src, "to", "any",
                    lit("|"), "ipf", "-f", "-")

    def heal(self, test):
        def f(t, n):
            with c.su():
                c.exec_("ipf", "-Fa")
        c.on_nodes(test, f)

    def slow(self, test, opts=None):
        IPTables.slow(self, test, opts)  # type: ignore[arg-type]

    def flaky(self, test):
        IPTables.flaky(self, test)  # type: ignore[arg-type]

    def fast(self, test):
        def f(t, n):
            with c.su():
                c.exec_(TC, "qdisc", "del", "dev", "eth0", "root")
        c.on_nodes(test, f)


ipfilter = IPFilter
